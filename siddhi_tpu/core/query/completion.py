"""CompletionPump: a depth-bounded software pipeline for device batches.

PR 3 collapsed N device dispatches per junction batch into one, but every
batch still ended in a synchronous ``__meta__`` pull
(``runtime._finish_device_batch``): the host pack of batch k+1 could not
start until the device->host round trip of batch k completed (~70 ms on
the TPU tunnel per PERF.md's cost model), so the engine ran at
``pack + step + pull`` instead of ``max(pack, step)``. The static
``defer_meta`` hold-N-then-flush queue attacked only the pull count, was
opt-in, lagged emission by a full window under trickle load, and excluded
joins and scheduler-driven windows entirely.

The pump replaces both. A query step dispatches (JAX dispatch is already
asynchronous) and hands its device output plus the RAW ``__meta__`` ref
to the per-app pump; up to ``pipeline_depth`` batches per query ride in
flight while the producer packs the next batch ("Scaling Ordered Stream
Processing on Shared-Memory Multicores", PAPERS.md: ordered emission is
compatible with out-of-order/pipelined execution). Depth 1 is exactly
today's synchronous behavior (the runtimes bypass the pump).

Contract:

- **Per-owner dispatch order.** Each owner (a ``QueryRuntime`` or a
  ``FusedFanoutRuntime`` group) has a FIFO of in-flight completions;
  drains pop strictly from the head, so emission order per query always
  equals dispatch order. No ordering is promised ACROSS queries (the
  reference's @Async path never promised one either).
- **Batched drain rounds.** A drain pulls every popped entry's meta in
  ONE ``jax.device_get`` (or one bounded ``guarded_pull`` when the owner
  is sharded and ``cluster_step_timeout`` is set, so a dead peer still
  surfaces as a labeled ``ClusterPeerError``) — the metas-per-pull ratio
  is exported on ``/metrics``.
- **Overflow surfaces on the producer's next send.** A capacity overflow
  discovered at drain raises ``FatalQueryError`` out of whoever drained:
  the producer's own submit/flush (sync sends), or the @Async worker's
  idle flush — where the junction's ``_fatal`` pattern makes every later
  send re-raise. Drain-then-raise: the other entries of the round still
  emit; the overflowed batch itself is NOT emitted (matching the
  synchronous path's raise-before-emit).
- **Prompt completion.** Sync junction sends flush the pump before
  returning (synchronous semantics preserved — tests and single-shot
  sends observe their outputs immediately); @Async workers flush when
  their queue goes idle and on exit, bounding emission lag under trickle
  load to one idle poll — this is what lets scheduler-driven windows
  ride the pipeline (their ``__notify__`` wake times are delivered at
  drain, promptly) where ``defer_meta`` had to exclude them. Joins stay
  synchronous: their notify values are per SIDE and their two-sided
  state updates are order-coupled across streams (``join_runtime``).
- **Completion latency feedback.** Each entry remembers the delivering
  junction; at drain the TRUE pack->emit latency (not just the dispatch
  slice) feeds ``junction.record_completion`` -> the ``latency.target``
  adaptive batching loop, so a slow device step shrinks the batch cap
  even though dispatch returns instantly.

Telemetry (exported as ``siddhi_pipeline_*`` on ``GET /metrics``):
``pipeline.<owner>.inflight`` gauges, ``pipeline.stalls`` (forced drains
that had to WAIT on an unready meta — the producer genuinely blocked),
``pipeline.metas`` / ``pipeline.pulls`` (batching ratio).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from siddhi_tpu.analysis.guards import guarded
from siddhi_tpu.analysis.locks import make_lock
from siddhi_tpu.core.stream.junction import FatalQueryError
from siddhi_tpu.observability import journey as journey_mod

log = logging.getLogger(__name__)


class QueryCompletion:
    """One in-flight batch of a (single-stream / NFA / join) query
    runtime."""

    __slots__ = ("owner", "out", "overflow_msg", "junction", "batch",
                 "timer_cb", "t0", "wall", "tid", "journey")

    def __init__(self, owner, out, overflow_msg: str, junction=None,
                 batch=None, journey=None):
        self.owner = owner
        self.out = out                    # LazyColumns, __meta__ still inside
        self.overflow_msg = overflow_msg
        self.junction = junction          # delivering junction (or None)
        self.journey = journey            # batch-journey context (or None)
        # input batch, retained ONLY when the junction routes errors to a
        # fault stream (@OnError action='stream') — drain-time errors
        # must publish the failing events there, like the sync path
        self.batch = batch
        # per-SIDE notify attribution: a join batch's __notify__ must
        # re-arm the dispatching side's own timer callback, snapshotted
        # here at submit (the runtime's _cur_timer_cb is per-batch state)
        self.timer_cb = getattr(owner, "_cur_timer_cb", None)
        self.t0 = time.perf_counter()
        self.wall = time.monotonic()      # wedge detection (supervisor)
        self.tid = threading.get_ident()  # submitting thread (scoped flush)

    @property
    def label(self) -> str:
        return self.owner.name

    def meta_refs(self) -> list:
        return [dict.__getitem__(self.out, "__meta__")]

    def ready(self) -> bool:
        return _is_ready(self.meta_refs()[0])

    def complete(self, metas: list) -> Optional[Exception]:
        from siddhi_tpu.core.event import HostBatch

        q = self.owner
        meta = np.asarray(metas[0])
        dict.pop(self.out, "__meta__")
        overflow, notify, size = int(meta[0]), int(meta[1]), int(meta[2])
        try:
            check = getattr(q, "decode_meta_suffix", None)
            if check is not None and len(meta) > 3:
                # instrument/structural suffix behind the standard
                # prefix (observability/instruments.py): data slots feed
                # device.<q>.<slot> telemetry; check slots (route
                # overflow, join seq) run their structural consumers —
                # an exchange overflow is fatal for this batch exactly
                # like a capacity overflow
                try:
                    check(meta)
                except FatalQueryError as routed_err:
                    return routed_err
            if overflow > 0:
                # the overflowed batch's rows are clamped garbage —
                # matching the synchronous path, it does not emit (the
                # rest of the drain round still does: drain-then-raise).
                # Joins pass a CALLABLE decoding the overflow bitmask to
                # the exact knob (overflow_knob_msg convention).
                msg = (self.overflow_msg(overflow)
                       if callable(self.overflow_msg) else self.overflow_msg)
                return FatalQueryError(
                    f"query '{q.name}': {msg} before "
                    f"creating the runtime")
            jr = self.journey
            t_e = time.perf_counter() if jr is not None else None
            q._emit(HostBatch(self.out, size=size))
            if jr is not None:
                jr.emit_ms = (time.perf_counter() - t_e) * 1000.0
                jr.finish(q.app_context, (q.name,))
            if notify >= 0 and q.scheduler is not None:
                q.scheduler.notify_at(
                    notify, self.timer_cb
                    or getattr(q, "_timer_cb", q.process_timer))
            return None
        finally:
            if self.junction is not None:
                # recorded AFTER emit: the depth-1 _timed_deliver sample
                # covered decode/rate-limit/callbacks too, and an
                # emit-dominated workload must still shrink the cap
                self.junction.record_completion(
                    (time.perf_counter() - self.t0) * 1000.0)


class FusedCompletion:
    """One in-flight junction batch of a fused fan-out group: a single
    stacked ``[n_clusters, 3]`` meta covers every member; per-member
    emission/attribution runs in ``FusedFanoutRuntime.complete_entry``."""

    __slots__ = ("owner", "outs", "metas_ref", "members", "cluster_of",
                 "batch", "junction", "t0", "wall", "tid", "journey")

    def __init__(self, owner, outs, metas_ref, members, cluster_of, batch,
                 junction=None, journey=None):
        self.owner = owner
        self.outs = outs
        self.metas_ref = metas_ref
        self.members = members            # member list snapshot (ordering)
        self.cluster_of = cluster_of
        self.batch = batch                # input batch, for fault routing
        self.junction = junction
        self.journey = journey            # one journey for the group batch
        self.t0 = time.perf_counter()
        self.wall = time.monotonic()
        self.tid = threading.get_ident()  # submitting thread (scoped flush)

    @property
    def label(self) -> str:
        return f"fanout.{self.owner.stream_id}"

    def meta_refs(self) -> list:
        return [self.metas_ref]

    def ready(self) -> bool:
        return _is_ready(self.metas_ref)

    def complete(self, metas: list) -> Optional[Exception]:
        try:
            return self.owner.complete_entry(self, np.asarray(metas[0]))
        finally:
            if self.junction is not None:
                # after per-member emission — see QueryCompletion
                self.junction.record_completion(
                    (time.perf_counter() - self.t0) * 1000.0)


# numpy/unknown/deleted refs read as ready (never stalls) — shared with
# the journey's device-attribution pivot so the two probes cannot drift
_is_ready = journey_mod.ready_of


@guarded
class CompletionPump:
    """Per-app registry of in-flight device batches (one FIFO per owner).

    Thread contract: ``submit`` and ``flush_owner`` are called with the
    owner's ``_lock`` held (process_batch already holds it); ``flush``
    acquires each owner's lock itself. Lock order is always
    ``owner._lock`` -> ``pump._lock`` — the pump lock is never held
    across a device pull or an emit.
    """

    # `_n_pending` and `_submits_by_j` stay undeclared: both are
    # lock-free has-work/progress probes read from hot sync paths
    GUARDED_BY = {"_pending": "pump"}

    def __init__(self, app_context):
        self.app_context = app_context
        self._pending: Dict[object, deque] = {}
        self._lock = make_lock("pump")
        self._tls = threading.local()
        self._n_pending = 0       # cheap has-work probe for sync senders
        # monotonic submit counts PER DELIVERING JUNCTION: lets a worker
        # tell whether ITS delivery pipelined (and skip the near-zero
        # dispatch-slice _adapt sample) without a foreign stream's
        # concurrent submit suppressing an unrelated junction's sample
        self._submits_by_j: Dict[int, int] = {}
        self._gauged = set()

    # ------------------------------------------------------------- config

    @property
    def depth(self) -> int:
        return max(1, int(getattr(self.app_context, "pipeline_depth", 1)))

    @property
    def has_pending(self) -> bool:
        return self._n_pending > 0

    def submits_of(self, junction) -> int:
        """Monotonic count of entries this junction's deliveries have
        submitted (see ``StreamJunction._pump_submits``)."""
        return self._submits_by_j.get(id(junction), 0)

    def inflight(self, owner) -> int:
        with self._lock:
            dq = self._pending.get(owner)
            return len(dq) if dq is not None else 0

    @staticmethod
    def _label_of(owner) -> str:
        name = getattr(owner, "name", None)
        return name if name is not None else f"fanout.{owner.stream_id}"

    def _inflight_by_label(self, label: str) -> int:
        """Gauge backend: resolves owners by LABEL at scrape time, so a
        rebuilt owner under the same label (a fused group dissolved and
        re-formed) keeps feeding the same /metrics series — and no owner
        object is pinned by a gauge closure."""
        with self._lock:
            return sum(len(dq) for o, dq in self._pending.items()
                       if self._label_of(o) == label)

    def oldest_age_s(self) -> Optional[float]:
        """Age of the oldest in-flight entry (wedge detection: a meta
        that never arrives means the device/collective hung)."""
        with self._lock:
            oldest = None
            for dq in self._pending.values():
                if dq and (oldest is None or dq[0].wall < oldest):
                    oldest = dq[0].wall
        if oldest is None:
            return None
        return time.monotonic() - oldest

    # ------------------------------------------------------------- submit

    def submit(self, entry) -> None:
        """Hand a dispatched batch to the pipeline (owner lock held).

        Keeps at most ``depth`` batches of this owner in flight: when the
        new entry would exceed the bound, the older entries drain in one
        batched round (the newest keeps riding, so the producer can go
        straight back to packing). With overload quotas registered
        (``resilience/overload.py``) the app-wide ``pipeline_quota``
        additionally collapses each submitting owner to ONE riding entry
        while the app total exceeds it — bounding the steady-state total
        at ``max(quota, one per active query)`` instead of
        ``depth × N_queries`` (cross-owner drains are off-limits here:
        lock order is owner -> pump, and we hold only OUR owner's lock) —
        and each submit is a weighted-fair yield point so a flooded
        tenant's dispatches don't monopolize the device."""
        owner = entry.owner
        ctl = getattr(self.app_context, "overload", None)
        if ctl is not None:
            ctl.throttle(0)     # yield-only: usage is charged at delivery
        with self._lock:
            dq = self._pending.get(owner)
            if dq is None:
                dq = self._pending[owner] = deque()
                self._register_gauge(owner, entry.label)
            dq.append(entry)
            self._n_pending += 1
            j = getattr(entry, "junction", None)
            if j is not None:
                self._submits_by_j[id(j)] = \
                    self._submits_by_j.get(id(j), 0) + 1
            # per-thread count: flush() loops only while THIS thread's
            # own emit cascades keep producing new entries
            self._tls.submitted = getattr(self._tls, "submitted", 0) + 1
            over = len(dq) - self.depth
            pq = ctl.pipeline_quota if ctl is not None else None
            if pq is not None and over <= 0 and self._n_pending > pq:
                # app-wide quota: drain THIS owner's older entries (other
                # owners' locks cannot be taken here — their own submits
                # and flushes bound them the same way)
                over = 1
        if over > 0:
            # drain everything but the newest in ONE batched pull: the
            # oldest entries have had depth-1 pack cycles to complete, so
            # the producer rarely blocks, and the just-dispatched batch
            # keeps riding while the producer goes back to packing
            self._drain_owner(owner, keep_newest=1, forced=True)

    def _register_gauge(self, owner, label: str) -> None:
        if label in self._gauged:
            return
        self._gauged.add(label)
        tel = getattr(self.app_context, "telemetry", None)
        if tel is not None:
            tel.gauge(f"pipeline.{label}.inflight",
                      lambda lbl=label: self._inflight_by_label(lbl))

    # -------------------------------------------------------------- drain

    def _draining(self) -> set:
        s = getattr(self._tls, "draining", None)
        if s is None:
            s = self._tls.draining = set()
        return s

    def _drain_owner(self, owner, keep_newest: Optional[int],
                     forced: bool = False) -> None:
        """Pop entries from ``owner``'s FIFO head and complete them in
        order; the popped metas travel in ONE device pull. Caller holds
        ``owner._lock``. Re-entrant submits for the SAME owner (feedback
        topologies: a query emitting into its own input stream) must not
        drain past the in-progress round — they queue and the outer
        flush/drain picks them up."""
        draining = self._draining()
        if id(owner) in draining:
            return
        with self._lock:
            dq = self._pending.get(owner)
            if not dq:
                return
            n = len(dq) - (keep_newest or 0)
            if n <= 0:
                return
            take = [dq.popleft() for _ in range(n)]
            self._n_pending -= n
            if not dq:
                # an empty deque must not keep a released/dissolved owner
                # alive for the app's lifetime — re-submits re-key it
                del self._pending[owner]
        tel = getattr(self.app_context, "telemetry", None)
        if tel is not None:
            if forced and not take[0].ready():
                # the producer genuinely blocks on the device here — the
                # pipeline is too shallow for this pack/step ratio
                tel.count("pipeline.stalls")
            tel.count("pipeline.pulls")
            tel.count("pipeline.metas", len(take))
        draining.add(id(owner))
        try:
            refs = [r for e in take for r in e.meta_refs()]
            jt = journey_mod.enabled()
            if jt:
                # device-stage pivot: is_ready BEFORE the blocking pull
                # tells whether the device was still busy for the ride
                # (service) or the output sat parked (slack) — journey.py
                for e in take:
                    jr = getattr(e, "journey", None)
                    if jr is not None:
                        jr.pre_drain(e.ready())
                t_pull0 = time.perf_counter()
            try:
                metas = self._pull(owner, refs)
            except Exception as pull_err:  # noqa: BLE001 — dead peer etc.
                # the pull itself failed (a dead peer's ClusterPeerError
                # from guarded_pull): route it exactly like the old
                # synchronous _pull_meta raise inside a delivery —
                # through EVERY distinct delivering junction among the
                # popped entries (a multi-stream NFA's FIFO can mix
                # junctions), so each one's supervisor/_fatal machinery
                # sees it. The entries are lost either way:
                # ClusterPeerError is terminal for this runtime (see
                # parallel/distributed.guarded_pull).
                routed = False
                seen = set()
                for e in take:
                    jn = getattr(e, "junction", None)
                    if jn is None or id(jn) in seen:
                        continue
                    seen.add(id(jn))
                    routed = self._route_error(e, pull_err) or routed
                if not routed:
                    raise
                return
            if jt:
                pull_ms = (time.perf_counter() - t_pull0) * 1000.0
                for e in take:
                    jr = getattr(e, "journey", None)
                    if jr is not None:
                        # one batched round trip serves the whole round;
                        # each entry is attributed the round's pull
                        jr.drained(pull_ms)
            errors: List[Exception] = []
            i = 0
            for e in take:
                k = len(e.meta_refs())
                try:
                    err = e.complete(metas[i:i + k])
                except Exception as raised:  # noqa: BLE001 — drain-then-raise
                    err = raised
                if err is not None:
                    # route through the entry's OWN delivering junction
                    # (fatals arm THAT junction's _fatal so ITS producers
                    # re-raise; peer failures notify the supervisor;
                    # others log-and-drop, exactly like the synchronous
                    # per-receiver delivery path) — the drain may have
                    # been triggered by an unrelated stream's send, whose
                    # junction must not absorb this error's attribution
                    if not self._route_error(e, err):
                        errors.append(err)
                i += k
            if errors:
                for extra in errors[1:]:
                    # drain-then-raise can only surface one exception to
                    # the caller; the rest must not vanish silently
                    log.error("pipeline drain: additional error "
                              "suppressed behind the raised one: %r", extra)
                raise errors[0]
        finally:
            draining.discard(id(owner))

    @staticmethod
    def _route_error(entry, err: Exception) -> bool:
        """Returns True when the error is fully ABSORBED by the routing
        (non-fatal, logged/dropped or fault-routed by the junction — the
        synchronous path's per-receiver semantics); False when the drain
        must still raise it to its caller (framework fatals, which
        handle_error re-raises after arming ``_fatal``, and any error of
        an entry that has no delivering junction)."""
        j = getattr(entry, "junction", None)
        if j is None:
            return False
        # fused entries retain the input batch (per-member fault
        # attribution needs it) — hand its events to the fault-stream
        # routing; query entries retain only the device OUTPUT, so their
        # non-fatal drain errors are logged here (an empty-events STREAM
        # route would silently publish nothing)
        events = []
        batch = getattr(entry, "batch", None)
        if batch is not None:
            try:
                events = j.decode_events(batch)
            except Exception:  # noqa: BLE001 — routing must not mask
                events = []
        if not events and not isinstance(err, FatalQueryError):
            # fatals surface loudly through _fatal + the drain's raise;
            # a NON-fatal with no events would otherwise vanish into an
            # empty fault-stream publish
            log.error(
                "pipeline drain error on stream '%s' (input events not "
                "retained past dispatch): %r", j.definition.id, err)
        try:
            # handle_error arms j._fatal and re-raises for framework
            # failures, notifies the supervisor of peer failures, and
            # logs/fault-routes the rest; the re-raise is swallowed here
            # because the drain raises the collected error to ITS caller
            j.handle_error(events, err)
        except Exception:  # noqa: BLE001 — fatal: surfaced by the drain
            return False
        return True

    def _pull(self, owner, refs: list) -> list:
        import jax

        timeout = getattr(self.app_context, "cluster_step_timeout", None)
        if timeout is not None and getattr(owner, "_shard_mesh", None) is not None:
            from siddhi_tpu.parallel.distributed import guarded_pull

            name = getattr(owner, "name", None) or getattr(
                owner, "stream_id", "?")
            return guarded_pull(refs, timeout,
                                what=f"query '{name}' pipeline drain")
        return jax.device_get(refs)

    # -------------------------------------------------------------- flush

    def flush_owner(self, owner) -> None:
        """Drain everything of one owner (owner lock held) — called
        before a timer step so the timer observes a fully-drained
        timeline, and by restores/tests."""
        self._drain_owner(owner, keep_newest=None)

    def flush(self, own_only: bool = False) -> None:
        """Drain owners to empty. Sync junction sends and @Async workers
        call this with ``own_only=True`` — draining only owners whose
        FIFO head was submitted by THIS thread (its own dispatches and
        their emit cascades), so a latency-sensitive synchronous sender
        never pays an unrelated busy stream's device pulls; ``persist``
        (inside the barrier), shutdown, and restore flush everything.
        Nested flushes (an emit cascading into a downstream sync send)
        are no-ops — the outer flush loops until nothing is pending."""
        if self._n_pending == 0:
            return
        if getattr(self._tls, "in_flush", False):
            return
        if self._draining():
            # this thread is inside a drain round (submit's forced drain
            # or flush_owner) and HOLDS that owner's lock: acquiring a
            # different owner's lock here would ABBA-deadlock against a
            # peer worker doing the mirror-image cascade. The entries
            # this nested flush wanted stay pending for the caller's own
            # idle/sync flush, which runs lock-free.
            return
        self._tls.in_flush = True
        ident = threading.get_ident()
        try:
            while True:
                draining = self._draining()
                with self._lock:
                    # owners THIS thread is mid-draining are excluded:
                    # their new entries (feedback topologies) belong to
                    # the in-progress round's caller, and looping on them
                    # here would spin forever without progress
                    # own_only matches ANY entry of this thread, not just
                    # the head: a sync sender's dispatch queued behind a
                    # worker's entry in the same owner FIFO must still
                    # drain before the send returns (the foreign head
                    # drains first — same-owner FIFO order is inherent)
                    owners = [o for o, dq in self._pending.items()
                              if dq and id(o) not in draining
                              and (not own_only
                                   or any(en.tid == ident for en in dq))]
                if not owners:
                    return
                submitted0 = getattr(self._tls, "submitted", 0)
                for owner in owners:
                    lock = getattr(owner, "_lock", None)
                    if lock is not None:
                        with lock:
                            self._drain_owner(owner, keep_newest=None)
                    else:
                        self._drain_owner(owner, keep_newest=None)
                if getattr(self._tls, "submitted", 0) == submitted0:
                    # only re-loop when THIS thread's own emit cascades
                    # produced new entries — a busy @Async producer on
                    # another thread must not turn a synchronous sender's
                    # flush into an unbounded drain of foreign streams
                    return
        finally:
            self._tls.in_flush = False

    def discard_all(self) -> None:
        """Drop every in-flight entry WITHOUT emitting (snapshot restore:
        pre-restore outputs belong to the rolled-back timeline, exactly
        like ``q._deferred``)."""
        with self._lock:
            self._pending.clear()
            self._n_pending = 0
