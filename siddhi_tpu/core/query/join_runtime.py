"""Host driver for two-stream window joins.

The counterpart of reference ``query/input/stream/join/JoinProcessor.java``
+ ``JoinInputStreamParser.java``: each side owns a window stage; an arriving
chunk is inserted into its own window first (pre-join forwards, trigger
false — ``JoinInputStreamParser.java:344``), then every row the window
emits (CURRENT and EXPIRED) probes the other side's buffer with the
compiled `on` condition (post-join trigger — ``:348``,
``JoinProcessor.execute:107-170``) as one masked [N, W] broadcast compare.
Outer sides emit a null-padded row when nothing matches.

Extensions beyond the basic stream-stream shape:
- group-by selectors (host keyer over the joined columns — split pipeline)
- joins inside partitions: keyed window sides, per-row probes gathered from
  the other side's ``[K, W]`` ring by partition key
- host-mode window sides (sort/frequent/session): the window runs host-side
  and exposes its ``contents()`` as the probe surface
- aggregation joins (``join AggName within ... per ...``): the aggregation's
  stitched buckets are the probe store (``AggregationRuntime.java:331-357``)
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.eligibility import ReasonCode as _RC
from siddhi_tpu.core.eligibility import reason as _reason
from siddhi_tpu.core.event import Event, HostBatch, LazyColumns, pack_pool_of
from siddhi_tpu.core.plan.selector_plan import FLUSH_KEY, GK_KEY
from siddhi_tpu.core.query.runtime import QueryRuntime, pack_meta
from siddhi_tpu.core.stream.junction import FatalQueryError, Receiver
from siddhi_tpu.ops.expressions import (
    OKEY_KEY,
    PK_KEY,
    TS_KEY,
    TYPE_KEY,
    VALID_KEY,
    ColumnRef,
    CompileError,
    Resolver,
)
from siddhi_tpu.ops.windows import conform_cols
from siddhi_tpu.query_api.definitions import AttrType, StreamDefinition
from siddhi_tpu.query_api.expressions import Variable

_LOG = logging.getLogger("siddhi_tpu.join")

CURRENT, EXPIRED, TIMER, RESET = 0, 1, 2, 3


@dataclass
class JoinSide:
    key: str                     # 'left' | 'right'
    stream_id: str
    ref_id: Optional[str]
    definition: StreamDefinition
    window_stage: object         # None for shared-store (table/window) sides
    filters: List[Callable]
    triggers: bool               # unidirectional: does this side emit?
    outer: bool                  # emit null-padded row when no match
    # shared probe-only store with a contents() -> (cols, valid) surface
    # (InMemoryTable / NamedWindowRuntime / AggregationJoinStore)
    store: object = None
    # host-mode window (sort/frequent/...): processed host-side; its
    # contents() is the probe surface, its emissions trigger the join
    host_window: object = None
    keyer: object = None         # partition keyer (partitioned joins)
    # stream-function column transforms applied before filters/window
    transforms: List = field(default_factory=list)
    # when transforms append attributes, `definition` is the extended
    # (post-transform) shape; ingest packing uses the declared one
    input_definition: Optional[StreamDefinition] = None
    # filters after the window: mask this side's emitted (trigger) rows
    post_filters: List = field(default_factory=list)
    # inside a partition: a NON-partitioned stream side — one shared
    # (unkeyed) window, events visible to every partition instance
    # (reference: non-partitioned streams reach all instances)
    global_side: bool = False
    # inner '#stream' / partition-local side: rows carry their pk
    carried_pk: bool = False

    @property
    def pack_definition(self) -> StreamDefinition:
        return self.input_definition or self.definition

    @property
    def prefix(self) -> str:
        return "l__" if self.key == "left" else "r__"

    @property
    def probe_external(self) -> bool:
        """Probe columns come from outside the jitted state."""
        return self.store is not None or self.host_window is not None


class AggregationJoinStore:
    """Probe adapter over an incremental aggregation's stitched buckets
    (reference ``join AggName within <start>, <end> per '<duration>'``)."""

    def __init__(self, agg, duration, within: Optional[tuple]):
        self.agg = agg
        self.duration = duration
        self.within = within
        self.definition = agg.output_definition()
        self.dynamic = None      # (per_of, within_of) raw-value closures
        self.dynamic_raw = None  # uncompiled expressions (set by the planner)

    def contents(self):
        _defn, cols, valid = self.agg.contents(self.duration, self.within)
        return cols, valid

    def resolve_groups(self, cols, ctx):
        """Group trigger rows by their per-event (duration, within) values
        (``within i.startTime, i.endTime per i.perValue``); each group
        probes its own stitched-bucket surface. Timer rows and rows whose
        values don't parse ride the first group (they only advance window
        clocks — no probe of their own)."""
        from siddhi_tpu.core.aggregation.incremental import parse_duration_name
        from siddhi_tpu.core.aggregation.within_time import (
            bound_ms, single_within_range)
        from siddhi_tpu.ops.expressions import TYPE_KEY, VALID_KEY

        per_of, within_of = self.dynamic
        valid = np.asarray(cols[VALID_KEY])
        is_timer = np.asarray(cols[TYPE_KEY]) == TIMER
        n = len(valid)
        pers = per_of(cols, ctx) if per_of is not None else None
        wins = within_of(cols, ctx) if within_of is not None else None
        groups: dict = {}
        carry = []
        for i in range(n):
            if not valid[i]:
                continue
            if is_timer[i]:
                carry.append(i)
                continue
            try:
                dur = parse_duration_name(pers[i]) if pers is not None \
                    else self.duration
                if wins is not None:
                    w = wins[i]
                    if isinstance(w, tuple):
                        win = (bound_ms(w[0]), bound_ms(w[1]))
                        if not win[0] < win[1]:
                            raise ValueError("within start must be < end")
                    elif isinstance(w, str):
                        win = single_within_range(w)
                    else:
                        win = (int(w), 2 ** 62)
                else:
                    win = self.within
            except Exception as e:
                # reference logs at the processor and drops the event
                _LOG.warning("aggregation join: dropping trigger row with "
                             "unresolvable within/per: %s", e)
                continue
            groups.setdefault((dur, win), []).append(i)
        if not groups:
            groups[(self.duration or parse_duration_name("seconds"),
                    self.within)] = []
        out = []
        for gi, ((dur, win), idx) in enumerate(groups.items()):
            mask = np.zeros(n, bool)
            mask[idx] = True
            if gi == 0:
                mask[carry] = True
            out.append((mask, dur, win))
        return out


class JoinResolver(Resolver):
    """Resolve selector/on-condition variables to prefixed joined columns."""

    def __init__(self, left: JoinSide, right: JoinSide, dictionary):
        self.sides = [left, right]
        self.dictionary = dictionary
        self.synthetic: Dict[str, AttrType] = {}

    def resolve(self, var: Variable) -> ColumnRef:
        if var.attribute_name in self.synthetic and var.stream_id is None:
            return ColumnRef(var.attribute_name, self.synthetic[var.attribute_name])
        sid = var.stream_id
        matches = []
        for side in self.sides:
            if sid is not None and sid not in (side.ref_id, side.stream_id):
                continue
            try:
                attr = side.definition.attribute(var.attribute_name)
            except Exception:
                continue
            matches.append((side, attr))
        if not matches:
            raise CompileError(
                f"cannot resolve '{(sid + '.') if sid else ''}{var.attribute_name}' "
                f"in join query"
            )
        if len(matches) > 1:
            # self-joins: the raw stream id matches both sides too
            raise CompileError(
                f"'{(sid + '.') if sid else ''}{var.attribute_name}' is ambiguous "
                f"between the join sides — qualify it with the `as` reference"
            )
        side, attr = matches[0]
        return ColumnRef(side.prefix + attr.name, attr.type)

    def encode_string(self, s: str) -> int:
        return self.dictionary.encode(s)


class JoinSideProxy(Receiver):
    """Per-side receiver of a join runtime. Beyond plain delivery it
    implements the fused fan-out MEMBER protocol
    (``core/query/fused_fanout.py``): an engine-attached join side can
    fuse with sibling single-stream queries on a shared junction — the
    side's insert+probe folds into the junction's ONE jitted step and its
    meta rides the group's combined pull (the engine's in-state probe
    surfaces are what make the side step a pure ``(state, cols, now)``
    function like any other member's)."""

    _fanout_group = None
    _own_keyer = None

    def __init__(self, runtime: "JoinQueryRuntime", side_key: str):
        self.runtime = runtime
        self.side_key = side_key

    # ------------------------------------------------ fused member protocol

    def fusion_ineligibility(self) -> Optional[str]:
        """Why this join side cannot join a fused fan-out group (None =
        eligible) — consulted by ``fanout_plan.fusion_ineligibility``."""
        rt = self.runtime
        if rt.engine is None:
            return _reason(
                _RC.NO_DEVICE_ENGINE,
                f"join side without device engine ({rt.engine_reason})")
        if rt.keyer is not None:
            return _reason(_RC.GROUPED_SELECT,
                           "grouped join selector (split host-keyed "
                           "pipeline)")
        if rt._shard_mesh is not None or rt._route_layout is not None:
            return _reason(_RC.SHARDED, "mesh-sharded join")
        for side in rt.sides.values():
            st = side.window_stage
            if st is not None and getattr(st, "needs_scheduler", False):
                return _reason(_RC.SCHEDULER_WINDOW,
                               "scheduler-driven join window")
        if rt.sides["left"].stream_id == rt.sides["right"].stream_id:
            # both proxies would fuse onto ONE junction sharing one state
            # pytree — the fused step would donate it twice per dispatch
            return _reason(_RC.SELF_JOIN,
                           "self-join (both sides share the junction batch)")
        return None

    @property
    def name(self) -> str:
        return f"{self.runtime.name}.{self.side_key}"

    @property
    def app_context(self):
        return self.runtime.app_context

    @property
    def input_definition(self):
        return self.runtime.sides[self.side_key].pack_definition

    @property
    def dictionary(self):
        return self.runtime.dictionary

    @property
    def selector_plan(self):
        return self.runtime.selector_plan

    @property
    def keyer(self):
        return self.runtime.keyer

    @keyer.setter
    def keyer(self, value):
        self.runtime.keyer = value

    @property
    def _win_keys(self):
        return self.runtime._win_keys

    @property
    def _lock(self):
        return self.runtime._lock

    @property
    def _state(self):
        return self.runtime._state

    @_state.setter
    def _state(self, value):
        self.runtime._state = value

    @property
    def scheduler(self):
        return self.runtime.scheduler

    def process_timer(self, ts: int):
        # per-side notify attribution: a fused side's wake time re-enters
        # through ITS OWN timer callback (defensive — eligible sides carry
        # no scheduler-driven window)
        self.runtime._timer(self.side_key, ts)

    def _ensure_capacity(self):
        self.runtime._ensure_capacity()

    def _init_state(self):
        return self.runtime._init_state()

    def prepare_cols(self, cols) -> bool:
        """Fused-group pre-dispatch hook: adaptive sub-window growth for
        this side's batch (mirrors ``process_side_batch``'s call). True =
        state shapes changed, the group must re-jit its fused step."""
        eng = self.runtime.engine
        if eng is None:
            return False
        if self.runtime._state is None:
            self.runtime._state = self.runtime._init_state()
        return eng.prepare_batch(self.side_key, cols)

    def overflow_knob_msg(self, code: Optional[int] = None):
        # forward the overflow bitmask: the fused drain must name the
        # partition/selector knob, not default to window capacity
        return self.runtime.overflow_knob_msg(code)

    def decode_meta_suffix(self, meta):
        """Fused-member drain hook: this side's padded meta row decodes
        by the RUNTIME's spec (seq + partition fills — the unrouted
        runtime's instrument_slots), into the runtime's telemetry."""
        self.runtime.decode_meta_suffix(meta)

    def _emit(self, out: HostBatch):
        self.runtime._emit(out)

    def build_step_fn(self):
        """The side's fused-member step: the engine's probe surfaces live
        inside the state, so the probe placeholders of the side-step
        signature are inert."""
        step = self.runtime.build_side_step_fn(self.side_key)
        placeholder = jnp.zeros((1,), bool)

        def fn(state, cols, now):
            return step(state, {}, placeholder, cols, now)

        return fn

    # ---------------------------------------------------------- delivery

    def receive(self, events: List[Event]):
        side = self.runtime.sides[self.side_key]
        if side.carried_pk:
            # inner-'#stream' / partition-local side: rows keep the
            # producing instance's pk. Events WITHOUT a pk (the stream is
            # a global junction anyone can feed) are broadcast to every
            # active instance like a global side — attributing them to
            # instance 0 would corrupt key 0's join state.
            keyed = [e for e in events if e.pk is not None]
            bare = [e for e in events if e.pk is None]
            if keyed:
                batch = HostBatch.from_events(
                    keyed, side.pack_definition, self.runtime.dictionary)
                pk = np.zeros(batch.capacity, np.int32)
                for i, e in enumerate(keyed):
                    pk[i] = e.pk
                batch.cols[PK_KEY] = pk
                self.runtime.process_side_batch(self.side_key, batch)
            if bare:
                n = self.runtime.partition_ctx.active_keys() \
                    if self.runtime.partition_ctx is not None else 0
                if n > 0:
                    rep = [Event(timestamp=e.timestamp, data=e.data,
                                 is_expired=e.is_expired, pk=k)
                           for e in bare for k in range(n)]
                    batch = HostBatch.from_events(
                        rep, side.pack_definition, self.runtime.dictionary)
                    pk = np.zeros(batch.capacity, np.int32)
                    for i, e in enumerate(rep):
                        pk[i] = e.pk
                    batch.cols[PK_KEY] = pk
                    self.runtime.process_side_batch(self.side_key, batch)
            return
        batch = HostBatch.from_events(
            events, side.pack_definition, self.runtime.dictionary,
            pool=pack_pool_of(self.runtime.app_context))
        self.runtime.process_side_batch(self.side_key, batch)


class JoinQueryRuntime(QueryRuntime):
    def is_stateful(self) -> bool:
        # window/NFA state is always snapshot-relevant
        return True

    def __init__(self, name, app_context, left: JoinSide, right: JoinSide,
                 on_cond: Optional[Callable], selector_plan, dictionary,
                 partition_ctx=None, group_keyer=None):
        super().__init__(
            name=name,
            app_context=app_context,
            input_definition=None,
            filters=[],
            window_stage=None,
            selector_plan=selector_plan,
            keyer=group_keyer,
            dictionary=dictionary,
            partition_ctx=partition_ctx,
        )
        self.sides = {"left": left, "right": right}
        self.on_cond = on_cond
        # @index equality probe spec from the planner (None = broadcast
        # compare): {"store_side", "attr", "val_fn", "residual_fn"}
        self.index_probe = None
        self._steps: Dict[str, object] = {}
        # device join engine (core/join/): attached by the planner for
        # eligible stream-stream shapes; None keeps the legacy probe path
        self.engine = None
        self.engine_reason: Optional[str] = _reason(
            _RC.NOT_ATTACHED, "engine not attached")
        self.pipeline_reason: Optional[str] = _reason(
            _RC.NOT_ATTACHED, "engine not attached")
        self._in_timer = False       # timer sweeps run synchronously
        self._drain_seq = None       # last cross-stream seq seen at drain
        self._cur_timer_cb = None    # per-side notify attribution (pump)
        # stable per-side timer callbacks so the scheduler's
        # (id(target), ts) dedup holds across batches
        self._timer_cbs = {
            k: (lambda ts, sk=k: self._timer(sk, ts)) for k in ("left", "right")
        }

    def make_proxies(self) -> Dict[str, JoinSideProxy]:
        # store sides produce no events — no proxy; named-window sides get
        # one (subscribed to the window's emission junction). The proxies
        # are retained: fan-out fusion subscribes THEM as group members
        # (fanout_plan), and the seq check consults their group state.
        self._proxies = {
            k: JoinSideProxy(self, k)
            for k in ("left", "right")
            if self.sides[k].window_stage is not None
        }
        return self._proxies

    def _init_state(self) -> dict:
        state = {"sel": self.selector_plan.init_state()}
        partitioned = self.partition_ctx is not None
        for k, wk in (("left", "lwin"), ("right", "rwin")):
            side = self.sides[k]
            if side.window_stage is not None and side.host_window is None:
                state[wk] = (side.window_stage.init_state(self._win_keys)
                             if partitioned else side.window_stage.init_state())
        if self.engine is not None:
            state.update(self.engine.init_pidx_state())
        return state

    def strip_engine_state(self, state):
        """Snapshot canonicalization: the partition directories and the
        cross-stream sequence are derived state — captures store only the
        legacy ``[W]`` ring layout, so revisions cross-restore between
        the device engine and the legacy path bit-identically (and across
        ``siddhi_tpu.join_partitions`` values)."""
        if state is None or self.engine is None:
            return state
        from siddhi_tpu.core.join import ENGINE_STATE_KEYS

        return {k: v for k, v in state.items()
                if k not in ENGINE_STATE_KEYS}

    def adopt_restored_state(self):
        """Snapshot-restore hook: the restored state is canonical (no
        partition directories) — rebuild them from the rings and reset
        the drain-sequence expectation."""
        self._drain_seq = None
        if self.engine is None or self._state is None:
            return
        from siddhi_tpu.core.join import SEQ_KEY

        state = dict(self._state)
        if SEQ_KEY not in state:
            import jax.numpy as _jnp

            state[SEQ_KEY] = _jnp.int64(0)
        self._state = state
        self.engine.rebuild_probe_state()

    def _seq_check(self, seq: int) -> None:
        """Drain-side verification of the engine's explicit cross-stream
        sequence: the pump's per-owner FIFO must hand batches back in
        dispatch order — a gap means an ordering bug, which must be loud
        (the outputs would silently interleave wrong). Skipped when a
        side rides a fused fan-out group: its seqs drain through the
        GROUP's entries, so this runtime's own FIFO legitimately sees
        gaps (cross-owner order was never promised)."""
        if any(getattr(p, "_fanout_group", None) is not None
               for p in getattr(self, "_proxies", {}).values()):
            self._drain_seq = None
            return
        exp = self._drain_seq
        self._drain_seq = seq
        if exp is not None and seq != exp + 1:
            _LOG.error(
                "query '%s': join drain sequence break (expected %d, "
                "got %d) — cross-stream emission order violated",
                self.name, exp + 1, seq)
            tel = getattr(self.app_context, "telemetry", None)
            if tel is not None:
                tel.count("join.seq_breaks")

    def _ensure_capacity(self):
        before = (self.selector_plan.num_keys, self._win_keys)
        super()._ensure_capacity()
        if (self.selector_plan.num_keys, self._win_keys) != before:
            self._steps.clear()

    def overflow_knob_msg(self, code: Optional[int] = None) -> str:
        """Join overflow naming the exact knob per the
        ``QueryRuntime.overflow_knob_msg`` convention. ``code`` is the
        step's overflow bitmask: 1 = window ring capacity, 2 = indexed
        probe candidate window, 4 = partition sub-window, 8 = selector
        value table (distinctCount)."""
        if code is None:
            code = 1
        code = int(code)
        parts = []
        if code & 1:
            knob = ("app_context.partition_window_capacity"
                    if self.partition_ctx is not None
                    else "app_context.window_capacity")
            parts.append(f"join window capacity exceeded — raise {knob}")
        if code & 2:
            parts.append("indexed join probe candidate window saturated — "
                         "raise app_context.index_probe_width")
        if code & 4:
            parts.append("join partition sub-window overflow — raise "
                         "siddhi_tpu.join_partition_slack (or lower "
                         "siddhi_tpu.join_partitions)")
        if code & 8:
            parts.append("join selector aggregation overflow — raise "
                         "app_context.distinct_values_capacity")
        if not parts:
            parts.append("join window capacity exceeded — raise "
                         "app_context.window_capacity")
        return "; ".join(parts)

    def _step_instrument_slots(self):
        """Spec of the engine side step's meta suffix (must mirror
        ``DeviceJoinEngine.build_side_step`` exactly): the structural
        cross-stream sequence, then — instruments on — each
        partitioned side's per-partition directory fill. Routed
        (mesh-sharded) joins run the LEGACY side step (engine None),
        whose meta carries no inner suffix; their route slots come from
        the base ``instrument_slots``."""
        from siddhi_tpu.observability.instruments import Slot

        if self.engine is None:
            return []
        slots = [Slot("seq", kind="check")]
        if self._instruments_on():
            for side_key in ("left", "right"):
                plan = self.engine.plans[side_key]
                if plan.use_pidx:
                    slots.append(Slot(f"fill.{side_key}",
                                      width=self.engine.P, reduce="max"))
        return slots

    def _consume_check_slot(self, name, vals) -> None:
        if name == "seq":
            self._seq_check(int(vals[0]))
            return
        super()._consume_check_slot(name, vals)

    def _instrument_capacity(self, name):
        if name.startswith("fill.") and self.engine is not None:
            plan = self.engine.plans.get(name[len("fill."):])
            if plan is not None:
                # live: adaptive growth moves Wp, the gauge must follow
                return float(plan.Wp)
        return super()._instrument_capacity(name)

    def build_side_step_fn(self, side_key: str):
        if self.engine is not None:
            return self.engine.build_side_step(side_key)
        side = self.sides[side_key]
        other = self.sides["right" if side_key == "left" else "left"]
        win_key = "lwin" if side_key == "left" else "rwin"
        other_key = "rwin" if side_key == "left" else "lwin"
        sel = self.selector_plan
        on_cond = self.on_cond
        # host-window sides run their transforms + filters + window host-side
        host_pre = side.host_window is not None
        filters = [] if host_pre else side.filters
        transforms = [] if host_pre else side.transforms
        partitioned = self.partition_ctx is not None
        split = self.keyer is not None
        other_external = other.probe_external
        # indexed probe: only when THIS side triggers against the indexed
        # store side (the store never triggers)
        iprobe = self.index_probe
        use_index = (iprobe is not None and side.triggers
                     and iprobe["store_side"] == other.key
                     and other.store is not None and not partitioned)
        probe_width = int(getattr(self.app_context, "index_probe_width", 64))

        def step(state, probe_cols, probe_valid, cols, current_time):
            from siddhi_tpu.core.plan.selector_plan import STR_RANK

            ctx = {"xp": jnp, "current_time": current_time}
            cols = dict(cols)
            # the rank table rides to the SELECTOR only — window stages
            # must not see the non-row-shaped extra column
            strrank = cols.pop(STR_RANK, None)
            for t in transforms:
                cols = t.apply(cols, ctx)
            valid = cols[VALID_KEY]
            timer = cols[TYPE_KEY] == TIMER
            for f in filters:
                valid = valid & (f(cols, ctx) | timer)
            cols[VALID_KEY] = valid
            new_state = dict(state)
            new_win, wout = side.window_stage.apply(
                state.get(win_key),
                conform_cols(side.window_stage, cols), ctx)
            if win_key in state:
                new_state[win_key] = new_win
            wout = dict(wout)
            notify = wout.pop("__notify__", None)
            overflow = wout.pop("__overflow__", None)
            wout.pop("__flush__", None)
            # device-routed dispatch: the keyed window emits a global
            # emission-order key per trigger row (RIDX-derived); the join
            # carries it to the joined rows below for the cross-shard
            # ordered re-merge
            okey_w = wout.pop(OKEY_KEY, None)
            # post-window filters mask emitted rows (probe/trigger side
            # only — the window's retained contents are unaffected)
            pvalid = wout[VALID_KEY]
            ptimer = wout[TYPE_KEY] == TIMER
            for f in side.post_filters:
                pvalid = pvalid & (f(wout, ctx) | ptimer)
            wout[VALID_KEY] = pvalid

            N = wout[VALID_KEY].shape[0]
            if not other_external:
                probe_cols, probe_valid = other.window_stage.contents(state[other_key])

            # joined eval dict: this side [N,1]; other side [1,W]
            # (or, partitioned, this row's key's ring gathered to [N,W];
            # or, INDEXED, per-row candidate windows gathered to [N,G])
            ev: Dict[str, jnp.ndarray] = {}
            idx_overflow = None
            if use_index:
                # sort the probe column once (invalid/null rows to the
                # end), then per-event searchsorted gives a contiguous
                # candidate range — O(W log W + N log W + N*G) instead of
                # the O(N*W) broadcast compare, and the join materializes
                # [N, G+1] instead of [N, W+1]
                attr = iprobe["attr"]
                ev0 = {TS_KEY: wout[TS_KEY][:, None]}
                for a in side.definition.attributes:
                    ev0[side.prefix + a.name] = wout[a.name][:, None]
                    ev0[side.prefix + a.name + "?"] = wout[a.name + "?"][:, None]
                v, vmask = iprobe["val_fn"](ev0, ctx)
                pvals = probe_cols[attr]
                pnull = probe_cols.get(attr + "?")
                ok = probe_valid
                if pnull is not None:
                    ok = ok & ~pnull
                if jnp.issubdtype(pvals.dtype, jnp.floating):
                    big = jnp.asarray(jnp.inf, pvals.dtype)
                else:
                    big = jnp.asarray(jnp.iinfo(pvals.dtype).max, pvals.dtype)
                sortkey = jnp.where(ok, pvals, big)
                order = jnp.argsort(sortkey)
                sk = sortkey[order]
                Wfull = sk.shape[0]
                vv = jnp.broadcast_to(jnp.asarray(v), (N, 1))[:, 0] \
                    .astype(pvals.dtype)
                lo = jnp.searchsorted(sk, vv, side="left")
                hi = jnp.searchsorted(sk, vv, side="right")
                G = min(probe_width, Wfull)
                grid = lo[:, None] + jnp.arange(G)[None, :]
                cmask = grid < hi[:, None]
                if vmask is not None:
                    cmask = cmask & ~jnp.broadcast_to(
                        jnp.asarray(vmask), (N, 1))
                idx_overflow = jnp.any((hi - lo) > G).astype(jnp.int32)
                cand = order[jnp.clip(grid, 0, Wfull - 1)]        # [N, G]
                W = G
                for a in other.definition.attributes:
                    ev[other.prefix + a.name] = probe_cols[a.name][cand]
                    ev[other.prefix + a.name + "?"] = \
                        probe_cols[a.name + "?"][cand]
                # belt-and-braces equality re-check on the gathered rows:
                # guards the dtype-max/inf sentinel (a probe value equal
                # to it would otherwise sweep deleted/null rows in) and
                # any residual dtype edge case
                pv = (cmask & ok[cand]
                      & (pvals[cand] == vv[:, None]))
            elif partitioned and not other_external:
                pk_rows = jnp.clip(wout[PK_KEY].astype(jnp.int32), 0,
                                   probe_valid.shape[0] - 1)
                probe_cols = {a: v[pk_rows] for a, v in probe_cols.items()}
                probe_valid = probe_valid[pk_rows]          # [N, W]
                W = probe_valid.shape[1]
                for a in other.definition.attributes:
                    ev[other.prefix + a.name] = probe_cols[a.name]
                    ev[other.prefix + a.name + "?"] = probe_cols[a.name + "?"]
                pv = probe_valid
            else:
                W = probe_valid.shape[0]
                for a in other.definition.attributes:
                    ev[other.prefix + a.name] = probe_cols[a.name][None, :]
                    ev[other.prefix + a.name + "?"] = probe_cols[a.name + "?"][None, :]
                pv = probe_valid[None, :]
            for a in side.definition.attributes:
                ev[side.prefix + a.name] = wout[a.name][:, None]
                ev[side.prefix + a.name + "?"] = wout[a.name + "?"][:, None]
            ev[TS_KEY] = wout[TS_KEY][:, None]

            row_live = wout[VALID_KEY] & ((wout[TYPE_KEY] == CURRENT) | (wout[TYPE_KEY] == EXPIRED))
            if use_index:
                # the probed equality holds by construction; only the
                # residual conjuncts (if any) still need evaluating
                rfn = iprobe["residual_fn"]
                cond = rfn(ev, ctx) if rfn is not None else jnp.ones((N, W), bool)
                cond = jnp.broadcast_to(cond, (N, W))
                match = row_live[:, None] & jnp.broadcast_to(pv, (N, W)) & cond
            elif side.triggers:
                cond = on_cond(ev, ctx) if on_cond is not None else jnp.ones((N, W), bool)
                cond = jnp.broadcast_to(cond, (N, W))
                match = row_live[:, None] & jnp.broadcast_to(pv, (N, W)) & cond
            else:
                match = jnp.zeros((N, W), bool)

            # column W carries the one-sided row: outer no-match + RESET
            no_match = row_live & ~jnp.any(match, axis=1) & side.outer & side.triggers
            one_sided = no_match | (wout[VALID_KEY] & (wout[TYPE_KEY] == RESET))

            NW = N * (W + 1)
            joined: Dict[str, jnp.ndarray] = {}
            for a in side.definition.attributes:
                v = jnp.broadcast_to(wout[a.name][:, None], (N, W + 1))
                mk = jnp.broadcast_to(wout[a.name + "?"][:, None], (N, W + 1))
                joined[side.prefix + a.name] = v.reshape(NW)
                joined[side.prefix + a.name + "?"] = mk.reshape(NW)
            for a in other.definition.attributes:
                pc = ev[other.prefix + a.name]
                pm = ev[other.prefix + a.name + "?"]
                v = jnp.concatenate(
                    [jnp.broadcast_to(pc, (N, W)),
                     jnp.zeros((N, 1), pc.dtype)], axis=1)
                mk = jnp.concatenate(
                    [jnp.broadcast_to(pm, (N, W)),
                     jnp.ones((N, 1), bool)], axis=1)
                joined[other.prefix + a.name] = v.reshape(NW)
                joined[other.prefix + a.name + "?"] = mk.reshape(NW)
            joined[VALID_KEY] = jnp.concatenate(
                [match, one_sided[:, None]], axis=1).reshape(NW)
            joined[TS_KEY] = jnp.repeat(wout[TS_KEY], W + 1)
            joined[TYPE_KEY] = jnp.repeat(wout[TYPE_KEY], W + 1)
            if partitioned:
                pk_out = jnp.repeat(wout[PK_KEY].astype(jnp.int32), W + 1)
                joined[PK_KEY] = pk_out
                joined[GK_KEY] = pk_out
            else:
                joined[GK_KEY] = jnp.zeros(NW, jnp.int32)
            # one reference chunk per trigger event (JoinProcessor.execute):
            # the selector's batch collapse keys on (trigger row, group)
            joined[FLUSH_KEY] = jnp.repeat(
                jnp.arange(N, dtype=jnp.int32), W + 1)
            if okey_w is not None:
                # joined emission-order key: trigger okey stridden by the
                # probe width reproduces the legacy [N, W+1] row-major
                # order ACROSS shards (one-sided rows at column W); the
                # invalid-row _BIG sentinel is zeroed before the multiply
                # (the route wrapper re-masks invalid rows itself)
                okw = jnp.asarray(okey_w, jnp.int64)
                okw = jnp.where(okw >= jnp.int64(2 ** 61), jnp.int64(0), okw)
                joined[OKEY_KEY] = (
                    okw[:, None] * jnp.int64(W + 1)
                    + jnp.arange(W + 1, dtype=jnp.int64)[None, :]
                ).reshape(NW)

            if idx_overflow is not None:
                # candidate window saturated: surfacing it beats silently
                # dropping matches. Bit 2 of the overflow mask — the host
                # decodes it to app_context.index_probe_width, distinct
                # from the window-capacity knob (overflow_knob_msg)
                base = (jnp.int32(0) if overflow is None else jnp.where(
                    jnp.asarray(overflow).astype(jnp.int32) > 0, 1, 0))
                overflow = base | (idx_overflow * 2)

            if strrank is not None:   # string order-by: rank table -> selector
                joined[STR_RANK] = strrank

            if split:
                # host keyer computes GK from joined columns; the selector
                # runs as a separate jitted step (_host_keyed_select)
                if notify is not None:
                    joined["__notify__"] = notify
                if overflow is not None:
                    joined["__overflow__"] = overflow
                return new_state, pack_meta(joined)

            new_state["sel"], out = sel.apply(state["sel"], joined, ctx)
            if notify is not None:
                out["__notify__"] = notify
            if overflow is not None:
                out["__overflow__"] = overflow
            return new_state, pack_meta(out)

        return step

    def build_step_fn(self):
        key = "left" if self.sides["left"].window_stage is not None else "right"
        return self.build_side_step_fn(key)

    def process_side_batch(self, side_key: str, batch: HostBatch):
        import time as _time

        from siddhi_tpu.core.stream.junction import \
            current_delivering_junction
        from siddhi_tpu.observability.tracing import span

        t_host0 = _time.perf_counter()
        with span("query.step", query=self.name, side=side_key), self._lock:
            from siddhi_tpu.observability import journey

            # pipelined completions need the delivering junction (error
            # attribution + latency feedback) and the SIDE's own timer
            # callback (per-side notify attribution at drain)
            j = current_delivering_junction()
            self._cur_junction = j
            self._cur_fault_batch = batch if (
                j is not None and j.on_error_action == "STREAM"
                and j.fault_junction is not None) else None
            self._cur_timer_cb = self._timer_cbs[side_key]
            # batch-journey (PR-11 coverage gap): join side batches get
            # the same stage attribution as single-stream ones — the
            # shared _finish_device_batch tail consumes the context.
            # The split (host-keyed) tail is synchronous and does not
            # thread the journey, so grouped joins skip the allocation.
            self._cur_journey = journey.begin(batch) \
                if journey.enabled() and self.keyer is None else None
            side = self.sides[side_key]
            cols = batch.cols
            partitioned = self.partition_ctx is not None
            notify_host = None
            if partitioned:
                if side.keyer is not None:
                    cols, pk = side.keyer.apply(cols)
                    batch = HostBatch(cols)
                    cols[PK_KEY] = np.asarray(pk, np.int32)
                elif side.global_side:
                    # non-partitioned stream inside a partition: the
                    # reference hands the event to every EXISTING
                    # instance (each holds its own window copy), so
                    # broadcast each row across the key axis, valid only
                    # for keys active at arrival — a later-created
                    # instance must NOT see earlier global events
                    # (JoinPartitionTestCase test10). _ensure_capacity
                    # runs before K is read so growth precedes the tile.
                    self._ensure_capacity()
                    n_active = self.partition_ctx.active_keys()
                    K = self._win_keys
                    B = batch.capacity
                    rep = {}
                    for name, v in cols.items():
                        rep[name] = np.repeat(np.asarray(v), K, axis=0)
                    pk_tile = np.tile(np.arange(K, dtype=np.int32), B)
                    rep[PK_KEY] = pk_tile
                    rep[VALID_KEY] = rep[VALID_KEY] & (pk_tile < n_active)
                    cols = rep
                    batch = HostBatch(cols)
                elif PK_KEY not in cols:
                    cols[PK_KEY] = np.zeros(batch.capacity, np.int32)
                if not side.global_side:   # global branch ensured already
                    self._ensure_capacity()
            if side.host_window is not None:
                now_h = int(self.app_context.timestamp_generator.current_time())
                hctx = {"xp": np, "current_time": now_h}
                for t in side.transforms:
                    cols = t.apply(cols, hctx)
                valid = cols[VALID_KEY]
                timer = cols[TYPE_KEY] == TIMER
                for f in side.filters:
                    valid = valid & (np.asarray(f(cols, hctx)) | timer)
                cols[VALID_KEY] = valid
                batch = HostBatch(cols)
                batch, notify_host = side.host_window.process(batch, now_h)
                cols = batch.cols
            cols[GK_KEY] = np.zeros(batch.capacity, np.int32)
            if self._state is None:
                self._state = self._init_state()
            if self.engine is not None:
                # adaptive sub-window capacity: mirror this batch's ring
                # occupancy and grow the partition directory BEFORE the
                # step could overflow it (clears _steps when it grows)
                self.engine.prepare_batch(side_key, cols)
            routed = self._route_layout is not None
            jitted = self._steps.get(side_key)
            if jitted is None:
                if routed:
                    # mesh-sharded partitioned join: the side step runs
                    # inside the device-router's shard_map (exchange by
                    # pk, partition-local probe, okey re-merge)
                    from siddhi_tpu.parallel.mesh import routed_step_for

                    jitted = routed_step_for(self, side_key=side_key)
                else:
                    jitted = self.app_context.telemetry.instrument_jit(
                        jax.jit(self.build_side_step_fn(side_key),
                                donate_argnums=0),
                        f"query.{self.name}.join.{side_key}",
                        family=f"device_join.{side_key}")
                self._steps[side_key] = jitted
            else:
                self.app_context.telemetry.record_jit(
                    getattr(jitted, "_key",
                            f"query.{self.name}.join.{side_key}"), hit=True)
            other = self.sides["right" if side_key == "left" else "left"]
            # callable: the step's overflow bitmask decodes to the exact
            # knob (window / index-probe / partition sub-window / selector)
            _ovf_msg = self.overflow_knob_msg
            tel = self.app_context.telemetry
            tel.histogram(f"join.insert_ms.{self.name}").record(
                (_time.perf_counter() - t_host0) * 1000.0)
            t_probe0 = _time.perf_counter()
            if (other.store is not None
                    and getattr(other.store, "dynamic", None) is not None):
                # per-event within/per: group trigger rows by their resolved
                # (duration, within) and probe each group's stitched surface
                now_h = int(self.app_context.timestamp_generator.current_time())
                groups = other.store.resolve_groups(
                    cols, {"xp": np, "current_time": now_h})
                notify = None
                base_valid = np.asarray(cols[VALID_KEY])
                saved = (other.store.duration, other.store.within)
                try:
                    for mask, dur, win in groups:
                        other.store.duration = dur
                        other.store.within = win
                        try:
                            probe_cols, probe_valid = other.store.contents()
                        except CompileError as e:
                            _LOG.error("query '%s': %s — dropping trigger "
                                       "events", self.name, e)
                            continue
                        sub = dict(cols)
                        sub[VALID_KEY] = base_valid & mask

                        def call(st, c, now, _pc=probe_cols, _pv=probe_valid):
                            return jitted(st, _pc, _pv, c, now)

                        n = self._finish_device_batch(call, sub, _ovf_msg)
                        if n is not None:
                            notify = n if notify is None else min(notify, n)
                finally:
                    # leave the planner-assigned static view on the shared
                    # store — the per-event values must not outlive the batch
                    other.store.duration, other.store.within = saved
            else:
                probe_ok = True
                if other.store is not None:
                    try:
                        probe_cols, probe_valid = other.store.contents()
                    except CompileError as e:
                        # e.g. `per "days"` against a sec...hour aggregation:
                        # the reference logs at the stream processor and
                        # drops the event (Aggregation1TestCase test22) —
                        # notify_host below must still be honored
                        _LOG.error("query '%s': %s — dropping trigger "
                                   "events", self.name, e)
                        probe_ok = False
                elif other.host_window is not None:
                    probe_cols, probe_valid = other.host_window.contents()
                else:  # placeholders; the step reads its own state instead
                    probe_cols, probe_valid = {}, jnp.zeros((1,), bool)

                notify = None
                if probe_ok:
                    if routed:
                        # pad/precheck host-side, splitting oversized
                        # batches, then run each piece through the routed
                        # side step in order (mirrors process_batch)
                        from siddhi_tpu.parallel.mesh import \
                            prepare_routed_batches

                        for piece in prepare_routed_batches(self, cols):
                            nt = self._finish_device_batch(
                                jitted, piece, _ovf_msg)
                            if nt is not None:
                                notify = (nt if notify is None
                                          else min(notify, nt))
                    else:
                        def call(st, cols, now):
                            return jitted(st, probe_cols, probe_valid,
                                          cols, now)

                        notify = self._finish_device_batch(
                            call, cols, _ovf_msg)
            tel.histogram(f"join.probe_ms.{self.name}").record(
                (_time.perf_counter() - t_probe0) * 1000.0)
        if notify_host is not None:
            notify = notify_host if notify is None else min(notify, notify_host)
        if notify is not None and self.scheduler is not None:
            self.scheduler.notify_at(notify, self._timer_cbs[side_key])

    @property
    def _defer_ok(self) -> bool:
        # per-side scheduler windows need their __notify__ promptly, and
        # notify values are per SIDE — never defer join metas
        return False

    @property
    def _pipeline_ok(self) -> bool:
        # Eligible joins ride the CompletionPump (core/join/ decides —
        # ``pipeline_reason`` is None when both probe surfaces live
        # inside the jitted state): probe-vs-insert coupling is resolved
        # at DISPATCH (state updates happen synchronously under the
        # runtime lock; only the meta pull + emission ride), both sides
        # share one owner FIFO so cross-stream emission order equals
        # dispatch order (the engine's explicit sequence number verifies
        # it at drain), and the per-side __notify__ is attributed to the
        # side's own timer callback captured on the entry. Timer sweeps
        # stay synchronous (flush-then-run, like process_timer).
        return self.pipeline_reason is None and not self._in_timer

    def _finish_device_batch(self, step, cols, overflow_msg):
        if self.keyer is None:
            return super()._finish_device_batch(step, cols, overflow_msg)
        # split (host-keyed) path: synchronous by construction; the
        # journey context is not threaded through the two-stage tail
        self._cur_journey = None
        from siddhi_tpu.core.util.statistics import latency_t0, record_elapsed_ms

        sm = self.app_context.statistics_manager
        t0 = latency_t0(sm)
        now = np.int64(self.app_context.timestamp_generator.current_time())
        if self.selector_plan.needs_str_rank:
            from siddhi_tpu.core.plan.selector_plan import STR_RANK

            cols[STR_RANK] = self.dictionary.rank_table()
        self._state, out = step(self._state, cols, now)
        out_host = LazyColumns(out)
        meta = out_host.pop("__meta__", None)
        if meta is not None:
            meta = np.asarray(meta)
            overflow, notify = int(meta[0]), int(meta[1])
            self.decode_meta_suffix(meta)
        else:
            ovf = out_host.pop("__overflow__", None)
            overflow = int(ovf) if ovf is not None else 0
            nt = out_host.pop("__notify__", None)
            notify = int(nt) if nt is not None else -1
        if overflow > 0:
            msg = (overflow_msg(overflow) if callable(overflow_msg)
                   else overflow_msg)
            raise FatalQueryError(f"query '{self.name}': {msg}")
        record_elapsed_ms(sm, self.name, t0)
        out_host = self._host_keyed_select(out_host)
        self._emit(HostBatch(out_host))
        if notify >= 0:
            return notify
        return None

    def _timer(self, side_key: str, ts: int):
        side = self.sides[side_key]
        from siddhi_tpu.core.event import TIMER as TIMER_TYPE
        from siddhi_tpu.core.query.runtime import _zero_value

        batch = HostBatch.from_events(
            [Event(timestamp=int(ts),
                   data=[_zero_value(a.type) for a in side.pack_definition.attributes])],
            side.pack_definition,
            self.dictionary,
        )
        batch.cols[TYPE_KEY][...] = TIMER_TYPE
        # timer sweeps run synchronously over a drained timeline, exactly
        # like process_timer: in-flight pipelined batches were dispatched
        # BEFORE this timer fired, and the sweep's own notify must re-arm
        # promptly (no producer will drain it later)
        with self._lock:
            pump = getattr(self.app_context, "completion_pump", None)
            if pump is not None and pump.has_pending:
                pump.flush_owner(self)
            self._in_timer = True
            try:
                self.process_side_batch(side_key, batch)
            finally:
                self._in_timer = False

    def receive(self, events: List[Event]):  # pragma: no cover — proxies only
        raise RuntimeError("join queries receive through per-side proxies")
