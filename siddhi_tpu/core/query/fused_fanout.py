"""FusedFanoutRuntime: one device dispatch per junction batch, not one
per query.

The junction delivers each batch to its receivers sequentially; before
this layer every subscribed ``QueryRuntime`` ran its own host pack, its
own group keyer, its own jitted step and its own ``__meta__`` pull — N
queries on one stream paid N device dispatches and N device->host round
trips per batch (the axon tunnel charges ~70 ms per pull, PERF.md). A
fused group subscribes ONE receiver in the members' place: the shared
packed batch feeds a single ``jax.jit`` step whose state is the tuple of
the members' state pytrees and whose output packs every member's columns
plus one combined ``[N, 3]`` ``__meta__`` — one dispatch and one meta
round trip per batch regardless of N.

Reference semantics are preserved per member:

- **subscription-order emission** — members emit in the order they
  subscribed (the group occupies the first member's receiver slot, so
  ordering against callbacks/sinks is unchanged);
- **state identity** — each member keeps its own ``_state`` pytree under
  its own name/lock, so snapshot capture/restore keys are exactly the
  unfused layout (pre-fusion revisions restore into a fused runtime and
  vice versa);
- **per-member error attribution** — a member's capacity overflow raises
  a ``FatalQueryError`` naming that query and its knob
  (``QueryRuntime.overflow_knob_msg``); under ``@OnError(action=
  'stream')`` only that member's failure is routed to the fault stream
  and the other members' outputs for the same batch are emitted
  normally (an upgrade over the unfused path, where the first fatal
  receiver starves the rest of the delivery loop);
- **group-key dedup** — members whose group-by expressions match share
  one ``GroupKeyer`` object (``group by symbol`` runs once per batch for
  the whole group); the member's own keyer is stashed so a restore that
  brings divergent per-member maps un-shares them
  (``fanout_plan.keyer_signature``);
- **identical-program dedup** — members whose step PROGRAMS are provably
  identical (equal jaxpr text, equal embedded constants, equal output
  tree, same group-key slot) AND whose current states are bit-equal run
  as ONE computation in the fused module; every member of the cluster is
  handed the (immutable) result arrays. This is sound because an
  identical program over the identical junction history produces an
  identical state trajectory — the common multi-tenant fan-out (the
  same analytics per consumer) collapses from N× compute to 1×, which
  is the semantic-overlap sharing PAPERS.md describes, not just
  dispatch amortization. Members whose programs differ keep their own
  sub-computation inside the same module (one dispatch either way).

Telemetry: the fused step compiles under jit key
``fanout.<stream>.step`` with one cache hit recorded PER MEMBER per
dispatch (hits/compiles = query-batches amortized per compile), plus
``fanout.<stream>.dispatches`` / ``fanout.<stream>.meta_pulls``
counters and ``fanout.<stream>.group_size`` /
``fanout.<stream>.unique_programs`` gauges — exported as
``siddhi_fanout_*`` on ``GET /metrics``.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.analysis.locks import make_lock
from siddhi_tpu.core.event import Event, HostBatch, LazyColumns, pack_pool_of
from siddhi_tpu.observability import journey
from siddhi_tpu.core.plan.selector_plan import GK_KEY, STR_RANK
from siddhi_tpu.core.stream.junction import FatalQueryError, Receiver
from siddhi_tpu.ops.expressions import VALID_KEY

_FGK = "__fgk{}__"   # per-slot shared group-key columns in the fused step


def _groups_of(junction) -> List["FusedFanoutRuntime"]:
    """Live fused groups subscribed to ``junction`` (an ineligible
    receiver mid-run can split one stream into two groups)."""
    return [r for r in junction.receivers
            if isinstance(r, FusedFanoutRuntime)]


def _same_program(a, b) -> bool:
    """Provably identical step programs: equal jaxpr text (deterministic
    variable naming, scalar literals inline), pairwise-equal embedded
    constants (closure-captured arrays are NOT in the text), and equal
    output tree/avals (catches output-name-only differences)."""
    a_str, a_consts, a_shape = a
    b_str, b_consts, b_shape = b
    if a_str != b_str:
        return False
    if len(a_consts) != len(b_consts):
        return False
    for x, y in zip(a_consts, b_consts):
        if not _values_equal(x, y):
            return False
    try:
        return (jax.tree_util.tree_structure(a_shape)
                == jax.tree_util.tree_structure(b_shape)
                and jax.tree_util.tree_leaves(a_shape)
                == jax.tree_util.tree_leaves(b_shape))
    except Exception:  # noqa: BLE001 — unequal on any doubt
        return False


def _values_equal(x, y) -> bool:
    try:
        x, y = np.asarray(x), np.asarray(y)
        if x.shape != y.shape or x.dtype != y.dtype:
            return False
        if np.issubdtype(x.dtype, np.floating):
            return bool(np.array_equal(x, y, equal_nan=True))
        return bool(np.array_equal(x, y))
    except Exception:  # noqa: BLE001 — unequal on any doubt
        return False


def _states_equal(sa, sb) -> bool:
    """Bit-equality of two state pytrees (same junction history + same
    program means same trajectory; this check makes the sharing
    assumption verified, not assumed — e.g. against states hand-mutated
    by tooling)."""
    if sa is sb:
        return True
    la, ta = jax.tree_util.tree_flatten(sa)
    lb, tb = jax.tree_util.tree_flatten(sb)
    if ta != tb or len(la) != len(lb):
        return False
    return all(_values_equal(x, y) for x, y in zip(la, lb))


class FusedFanoutRuntime(Receiver):
    def __init__(self, junction, members: List):
        self.junction = junction
        self.members = list(members)
        self.app_context = members[0].app_context
        self.stream_id = junction.definition.id
        self.input_definition = members[0].input_definition
        self.dictionary = members[0].dictionary
        self._needs_rank = any(m.selector_plan.needs_str_rank
                               for m in self.members)
        self._step = None
        self._sig = None          # (slots, per-member key capacities)
        self._clusters: List[List[int]] = []   # member idxs per computation
        self._cluster_of: List[int] = []       # member idx -> cluster idx
        self._lock = make_lock("owner")
        for m in self.members:
            m._fanout_group = self
        junction.replace_receivers(self.members, self)
        self.alias_keyers()
        # per-STREAM gauges aggregated over every live group on the
        # junction (a junction can host two groups when an ineligible
        # receiver splits the run): registration is idempotent and the
        # values are computed from the live receiver list, so a second
        # group's registration or a sibling's dissolve cannot corrupt them
        tel = self.app_context.telemetry
        tel.gauge(f"fanout.{self.stream_id}.group_size",
                  lambda j=junction: sum(len(g.members)
                                         for g in _groups_of(j)))
        tel.gauge(f"fanout.{self.stream_id}.unique_programs",
                  lambda j=junction: sum(len(g._clusters) or len(g.members)
                                         for g in _groups_of(j)))

    # ------------------------------------------------------ keyer sharing

    def alias_keyers(self):
        """Share one GroupKeyer across members with identical group-by
        expressions AND identical current maps (identical by construction
        on a fresh runtime; a restore may bring divergent maps, which
        stay private). The member's own keyer survives in ``_own_keyer``
        for restore to write into."""
        from siddhi_tpu.core.plan.fanout_plan import keyer_signature

        leaders = {}
        for m in self.members:
            if getattr(m, "_own_keyer", None) is None:
                m._own_keyer = m.keyer
            sig = keyer_signature(m)
            if sig is None or m.keyer is None:
                continue
            lead = leaders.get(sig)
            if lead is None:
                leaders[sig] = m
            elif (m.keyer._map == lead.keyer._map
                    and m.keyer._next == lead.keyer._next):
                m.keyer = lead.keyer
        self._step = None
        self._sig = None

    def on_restore(self):
        """Snapshot restore wrote each member's map into its OWN keyer
        (``snapshot.py``): re-derive sharing from the restored maps and
        drop the compiled step (key capacities/slot layout may differ)."""
        with self._lock:
            for m in self.members:
                own = getattr(m, "_own_keyer", None)
                if own is not None:
                    m.keyer = own
            self.alias_keyers()

    # --------------------------------------------------------- unwiring

    def release(self, member):
        """Hand one member back its own subscription (``parallel/mesh``
        sharding takes over its step). A first/last member splices out in
        place; releasing a MIDDLE member dissolves the whole group — the
        survivors' fused slot could not keep the released member between
        them, and subscription-order delivery outranks keeping the
        fusion. A group left with fewer than two members dissolves."""
        with self._lock:
            if member not in self.members:
                return
            idx = self.members.index(member)
            if 0 < idx < len(self.members) - 1:
                self.dissolve()
                return
            self.members.remove(member)
            self._restore_member(member, after_group=idx > 0)
            self._step = None
            self._sig = None
            if len(self.members) < 2:
                self.dissolve()

    def dissolve(self):
        """Unfuse entirely: members resume their own receiver slots in
        subscription order (used by ``SiddhiAppRuntime.debug()`` — the
        debugger instruments per-runtime delivery methods)."""
        with self._lock:
            recs = self.junction.receivers
            if self in recs:
                pos = recs.index(self)
                recs[pos:pos + 1] = list(self.members)
            for m in self.members:
                self._unalias(m)
            self.members = []
            if not _groups_of(self.junction):
                # last group on the stream: retire its metric surface
                tel = self.app_context.telemetry
                tel.remove_gauge(f"fanout.{self.stream_id}.group_size")
                tel.remove_gauge(f"fanout.{self.stream_id}.unique_programs")

    def _restore_member(self, member, after_group: bool):
        self._unalias(member)
        recs = self.junction.receivers
        if self in recs:
            pos = recs.index(self)
            recs.insert(pos + (1 if after_group else 0), member)

    @staticmethod
    def _unalias(member):
        member._fanout_group = None
        own = getattr(member, "_own_keyer", None)
        if own is not None:
            member.keyer = own
        if member._state is not None:
            # identical-program dedup may have the member sharing its
            # (immutable) state arrays with cluster siblings; the unfused
            # step donates its inputs, so a released member needs its own
            # buffers or its first donation deletes the siblings' state
            member._state = jax.tree_util.tree_map(
                lambda x: jnp.array(x), member._state)

    # ---------------------------------------------------------- receiving

    def receive(self, events: List[Event]):
        batch = HostBatch.from_events(
            events, self.input_definition, self.dictionary,
            pool=pack_pool_of(self.app_context))
        self.process_batch(batch)

    def receive_batch(self, batch: HostBatch, junction=None):
        from siddhi_tpu.core.query.runtime import backfill_null_masks

        backfill_null_masks(batch, self.input_definition)
        self.process_batch(batch, junction=junction)

    def process_batch(self, batch: HostBatch, junction=None):
        from siddhi_tpu.core.stream.junction import \
            current_delivering_junction
        from siddhi_tpu.observability.tracing import span

        if junction is None:
            junction = current_delivering_junction()
        with span("fanout.step", stream=self.stream_id,
                  members=len(self.members)):
            with self._lock, contextlib.ExitStack() as stack:
                # member locks in subscription order (snapshot takes them
                # one at a time — no cycle)
                for m in self.members:
                    stack.enter_context(m._lock)
                self._process_locked(batch, junction=junction)

    # ----------------------------------------------------------- internals

    def _now64(self) -> np.int64:
        return np.int64(
            int(self.app_context.timestamp_generator.current_time()))

    def _instruments_on(self) -> bool:
        from siddhi_tpu.observability import instruments

        return instruments.app_instruments_on(self.app_context)

    def _prepare(self, batch: HostBatch):
        """Shared per-batch prep: group-key columns (deduplicated by
        keyer identity), per-member capacity/state, the fused input dict,
        and the fused step (re-jitted when the slot layout or any key
        capacity changed — rebuilds also re-derive the identical-program
        clusters). Returns ``(states, cols_dev)`` ready for
        ``self._step``, where ``states`` holds ONE pytree per cluster."""
        cols = batch.cols
        cap = dict.__getitem__(cols, VALID_KEY).shape[0]
        gk_cols: List[np.ndarray] = []
        slots: List[int] = []
        slot_of = {}
        for m in self.members:
            kid = id(m.keyer) if m.keyer is not None else 0
            s = slot_of.get(kid)
            if s is None:
                s = slot_of[kid] = len(gk_cols)
                gk_cols.append(np.zeros(cap, np.int32) if m.keyer is None
                               else m.keyer(cols))
            slots.append(s)
        for m in self.members:
            if m.keyer is not None:
                m._ensure_capacity()
            if m._state is None:
                m._state = m._init_state()
            prep = getattr(m, "prepare_cols", None)
            if prep is not None and prep(cols):
                # a join side grew its partition directory: the member's
                # state shapes changed under the same (slots, capacities)
                # signature — drop the fused step so it re-jits
                self._step = None
        cols_dev = dict(cols)   # jit boundary: raw (possibly device) arrays
        for s, gk in enumerate(gk_cols):
            cols_dev[_FGK.format(s)] = gk
        if self._needs_rank:
            cols_dev[STR_RANK] = self.dictionary.rank_table()
        sig = (tuple(slots), tuple((m.selector_plan.num_keys, m._win_keys)
                                   for m in self.members))
        if self._step is None or sig != self._sig:
            self._step = self._build_step(tuple(slots), len(gk_cols),
                                          cols_dev)
            self._sig = sig
        else:
            tel = self.app_context.telemetry
            for _m in self.members:   # member hit-counting: N query-batches
                tel.record_jit(f"fanout.{self.stream_id}.step", hit=True)
        return (tuple(self.members[c[0]]._state for c in self._clusters),
                cols_dev)

    def _build_step(self, slots: Tuple[int, ...], n_slots: int, cols_dev):
        """Compile the group's single step. Members are first partitioned
        into identical-program clusters (equal jaxpr text + embedded
        constants + output tree, same group-key slot, bit-equal current
        state): each cluster contributes ONE sub-computation whose result
        every cluster member shares — the semantic-overlap dedup — and
        distinct programs sit side by side in the same module."""
        member_fns = [m.build_step_fn() for m in self.members]
        gk_names = tuple(_FGK.format(s) for s in range(n_slots))
        gk_set = frozenset(gk_names)
        base_example = {k: v for k, v in cols_dev.items() if k not in gk_set}
        now = self._now64()

        programs = []
        for i, fn in enumerate(member_fns):
            mcols = dict(base_example)
            mcols[GK_KEY] = cols_dev[gk_names[slots[i]]]
            try:
                jaxpr, out_shape = jax.make_jaxpr(fn, return_shape=True)(
                    self.members[i]._state, mcols, now)
                programs.append((str(jaxpr.jaxpr), jaxpr.consts, out_shape))
            except Exception:  # noqa: BLE001 — tracing for dedup is
                programs.append(None)   # best-effort; None never clusters
        clusters: List[List[int]] = []
        for i in range(len(self.members)):
            placed = False
            for c in clusters:
                lead = c[0]
                if (slots[i] == slots[lead] and programs[i] is not None
                        and programs[lead] is not None
                        and _same_program(programs[i], programs[lead])
                        and _states_equal(self.members[i]._state,
                                          self.members[lead]._state)):
                    c.append(i)
                    placed = True
                    break
            if not placed:
                clusters.append([i])
        self._clusters = clusters
        self._cluster_of = [next(ci for ci, c in enumerate(clusters)
                                 if i in c)
                            for i in range(len(self.members))]
        # distinct leader state objects per cluster: a stale shared object
        # (from a pre-rebuild cluster that has since split) would be
        # donated twice in one call
        seen_ids = set()
        for c in clusters:
            lead = self.members[c[0]]
            if id(lead._state) in seen_ids:
                lead._state = jax.tree_util.tree_map(
                    lambda x: jnp.array(x), lead._state)
            seen_ids.add(id(lead._state))
        cluster_fns = [member_fns[c[0]] for c in clusters]
        cluster_slots = [slots[c[0]] for c in clusters]
        ins_on = self._instruments_on()

        def fused(states, cols, now):
            base = {k: v for k, v in cols.items() if k not in gk_set}
            new_states, outs, metas = [], [], []
            for ci, fn in enumerate(cluster_fns):
                mcols = dict(base)
                mcols[GK_KEY] = cols[gk_names[cluster_slots[ci]]]
                st, out = fn(states[ci], mcols, now)
                # instruments ON: per-member meta SUFFIXES (a join
                # side's sequence + partition fills, a window member's
                # ring fill) ride the stack, zero-padded to the widest
                # member so it stays rectangular — the drain decodes
                # each member's row by its own instrument spec. OFF:
                # [:3] strips them, today's [n, 3] layout bit-for-bit.
                meta = out.pop("__meta__")
                metas.append(meta if ins_on else meta[:3])
                new_states.append(st)
                outs.append(out)
            width = max(m.shape[0] for m in metas)
            metas = [m if m.shape[0] == width else jnp.concatenate(
                [m, jnp.zeros(width - m.shape[0], m.dtype)])
                for m in metas]
            return tuple(new_states), (tuple(outs), jnp.stack(metas))

        jitted = jax.jit(fused, donate_argnums=0)
        return self.app_context.telemetry.instrument_jit(
            jitted, f"fanout.{self.stream_id}.step", family="fused_fanout")

    def _process_locked(self, batch: HostBatch, junction=None):
        from siddhi_tpu.core.util.statistics import (latency_t0,
                                                     record_elapsed_ms)

        members = self.members
        if not members:          # dissolved under a racing release
            return
        sm = self.app_context.statistics_manager
        tel = self.app_context.telemetry
        t0 = latency_t0(sm)
        # one journey per group batch: the shared dispatch/device stages
        # are recorded under EVERY member's name at finish
        jr = journey.begin(batch) if journey.enabled() else None
        states, cols_dev = self._prepare(batch)
        new_states, (outs, metas) = self._step(states, cols_dev,
                                               self._now64())
        if jr is not None:
            jr.end_dispatch()
        tel.count(f"fanout.{self.stream_id}.dispatches")
        for i, m in enumerate(members):
            # cluster members share the (immutable) result arrays
            m._state = new_states[self._cluster_of[i]]
        pump = getattr(self.app_context, "completion_pump", None)
        if pump is not None and pump.depth > 1:
            # pipelined: the whole group batch rides in flight; per-member
            # emission/attribution runs at drain (complete_entry). The
            # member list and cluster map are snapshotted — a release or
            # rebuild between dispatch and drain must not re-map outputs.
            from siddhi_tpu.core.query.completion import FusedCompletion

            for m in members:
                record_elapsed_ms(sm, m.name, t0)
            pump.submit(FusedCompletion(
                self, outs, metas, list(members), list(self._cluster_of),
                batch, junction=junction, journey=jr))
            return
        # ONE combined [n_clusters, 3] meta pull for the whole group — the
        # single device->host round trip this layer exists to amortize
        if jr is not None:
            jr.pre_drain(journey.ready_of(metas))
            _tp = time.perf_counter()
            metas_host = np.asarray(jax.device_get(metas))
            jr.drained((time.perf_counter() - _tp) * 1000.0)
        else:
            metas_host = np.asarray(jax.device_get(metas))
        tel.count(f"fanout.{self.stream_id}.meta_pulls")
        t_e = time.perf_counter() if jr is not None else None
        fatal = self._emit_members(list(members), list(self._cluster_of),
                                   outs, metas_host, batch, t0sm=t0)
        if jr is not None:
            jr.emit_ms = (time.perf_counter() - t_e) * 1000.0
            jr.finish(self.app_context, tuple(m.name for m in members))
        if fatal is not None:
            # surfaced AFTER every member emitted: the junction's
            # handle_error stores it so later sends re-raise, exactly as
            # an unfused member's fatal would
            raise fatal

    def complete_entry(self, entry, metas_host) -> Optional[Exception]:
        """Drain-side tail of a pipelined group batch (CompletionPump):
        per-member emission and fault attribution over the snapshotted
        member list. Returns the fatal (if any) for the pump's
        drain-then-raise instead of raising mid-round."""
        tel = self.app_context.telemetry
        tel.count(f"fanout.{self.stream_id}.meta_pulls")
        with self._lock, contextlib.ExitStack() as stack:
            for m in entry.members:
                stack.enter_context(m._lock)
            jr = entry.journey
            t_e = time.perf_counter() if jr is not None else None
            fatal = self._emit_members(entry.members, entry.cluster_of,
                                       entry.outs, np.asarray(metas_host),
                                       entry.batch, t0sm=None)
            if jr is not None:
                jr.emit_ms = (time.perf_counter() - t_e) * 1000.0
                jr.finish(self.app_context,
                          tuple(m.name for m in entry.members))
            return fatal

    def _emit_members(self, members, cluster_of, outs, metas_host, batch,
                      t0sm) -> Optional[Exception]:
        from siddhi_tpu.core.util.statistics import record_elapsed_ms

        sm = self.app_context.statistics_manager
        fatal: Optional[Exception] = None
        for i, m in enumerate(members):
            row = metas_host[cluster_of[i]]
            overflow, notify, size = int(row[0]), int(row[1]), int(row[2])
            try:
                if row.shape[0] > 3:
                    # per-member instrument suffix (zero-padded to the
                    # stack width): each member decodes its own spec —
                    # device.<q>.<slot> telemetry, join seq (self-
                    # skipping inside a fused group)
                    decode = getattr(m, "decode_meta_suffix", None)
                    if decode is not None:
                        decode(row)
                if overflow > 0:
                    raise FatalQueryError(
                        f"query '{m.name}': {m.overflow_knob_msg(overflow)} "
                        f"before creating the runtime")
                if t0sm is not None:   # pipelined path recorded at dispatch
                    record_elapsed_ms(sm, m.name, t0sm)
                # own LazyColumns wrapper per member over the shared
                # arrays: materialization/mutation must not leak across
                m._emit(HostBatch(LazyColumns(outs[cluster_of[i]]),
                                  size=size))
                if notify >= 0 and m.scheduler is not None:
                    # defensive: eligible members carry no scheduler-driven
                    # window, so this timer re-entry (which would run the
                    # member's own unfused step) should never arm
                    m.scheduler.notify_at(notify, m.process_timer)
            except Exception as e:  # noqa: BLE001 — per-member attribution
                fatal = self._route_member_error(m, batch, e, fatal)
        return fatal

    def _route_member_error(self, member, batch: HostBatch, e: Exception,
                            fatal: Optional[Exception]):
        """Per-member fault attribution: framework failures route to the
        fault stream when @OnError(action='stream') is configured —
        naming ONLY the failing member — else they re-raise to the
        sender after the other members emitted; per-event processing
        errors take the junction's reference routing (route or
        log-and-drop)."""
        from siddhi_tpu.ops.expressions import CompileError

        j = self.junction
        if isinstance(e, (FatalQueryError, CompileError)):
            if j.on_error_action == "STREAM" and j.fault_junction is not None:
                j.route_fault_events(j.decode_events(batch), e)
                return fatal
            return fatal if fatal is not None else e
        try:
            j.handle_error(j.decode_events(batch), e)
        except Exception as raised:  # noqa: BLE001 — handle_error re-raises
            return fatal if fatal is not None else raised  # fatals only
        return fatal

    # ------------------------------------------------------------ tooling

    def lower_hlo_text(self, batch: HostBatch) -> str:
        """Lower the fused step for ``batch`` and return its optimized
        HLO — ONE module containing every member's computation
        (``tools/hlo_audit.py`` asserts exactly that)."""
        with self._lock, contextlib.ExitStack() as stack:
            for m in self.members:
                stack.enter_context(m._lock)
            states, cols_dev = self._prepare(batch)
            return self._step.lower(
                states, cols_dev, self._now64()).compile().as_text()
