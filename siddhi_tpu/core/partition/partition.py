"""Partition keyers: host-side partition-key evaluation.

The counterpart of reference ``partition/PartitionStreamReceiver.java:96-135``
+ ``partition/executor/{Value,Range}PartitionExecutor.java`` — but instead of
routing events into per-key inner junction instances, rows get a dense
partition-key id column (``PK_KEY``) and all keys are processed by one device
step over ``[K, ...]`` state (see ``ops/keyed_windows.py``).

Reference semantics preserved:
- value partition: key = value of the expression; a null key drops the event
  (``ValuePartitionExecutor.execute`` returns null on NPE and the chunked
  receive path skips null keys);
- range partition: one copy of the event per matching range condition, in
  range-declaration order; events matching no range are dropped.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core.event import CURRENT, _pad_len
from siddhi_tpu.ops.expressions import TYPE_KEY, VALID_KEY
from siddhi_tpu.query_api.definitions import AttrType


class PartitionKeySpace:
    """Shared partition-key dictionary: key tuple -> dense id. One per
    partition block — two streams partitioned by equal values land in the
    same partition instance (reference keys are strings compared across
    streams). ``@purge`` retires idle ids into a free list for reuse
    (reference PartitionRuntimeImpl idle-partition purge)."""

    _LUT_MAX = 1 << 22  # raw-key bound for the vectorized table (4 M ids)

    def __init__(self):
        import threading

        self._lock = threading.RLock()
        self._map: Dict[tuple, int] = {}
        self._reverse: List[tuple] = []
        self._free: List[int] = []
        # single-int-key fast table: raw value (dictionary-encoded string
        # id or int key) -> dense pk; -1 = unseen. Steady state keys a
        # whole batch with ONE np.take instead of a per-row Python probe
        # (the partitioned-NFA host bottleneck — PERF.md round 5)
        self._lut = np.full(1024, -1, np.int32)
        # last-seen tracking is enabled only when the partition has @purge
        # (a per-batch touch would otherwise tax every partitioned app)
        self.last_seen: Optional[Dict[int, int]] = None

    def ids_of_ints(self, raw: np.ndarray) -> Optional[np.ndarray]:
        """Vectorized ``id_of`` over a single-int-key batch; None when the
        values fall outside the table's domain (negative / huge)."""
        if raw.size == 0:
            return np.empty(0, np.int32)
        vmin, vmax = int(raw.min()), int(raw.max())
        if vmin < 0 or vmax >= self._LUT_MAX:
            return None
        with self._lock:
            lut = self._lut
            if vmax >= lut.shape[0]:
                n = lut.shape[0]
                while n <= vmax:
                    n *= 2
                grown = np.full(n, -1, np.int32)
                grown[: lut.shape[0]] = lut
                self._lut = lut = grown
            out = lut[raw]
            miss = out < 0
            if miss.any():
                for x in np.unique(raw[miss]):
                    lut[int(x)] = self.id_of((int(x),))
                out = lut[raw]
        return out

    def enable_purge_tracking(self):
        if self.last_seen is None:
            self.last_seen = {}

    def id_of(self, key: tuple) -> int:
        with self._lock:
            i = self._map.get(key)
            if i is None:
                if self._free:
                    i = self._free.pop()
                    self._reverse[i] = key
                else:
                    i = len(self._reverse)
                    self._reverse.append(key)
                self._map[key] = i
            return i

    def touch(self, ids, now_ms: int):
        if self.last_seen is None:
            return
        with self._lock:
            for i in np.unique(np.asarray(ids)):
                self.last_seen[int(i)] = now_ms

    def retire_idle(self, now_ms: int, idle_ms: int) -> List[int]:
        """Unmap keys idle past ``idle_ms``. Their ids are NOT freed yet —
        the caller resets the ids' state rows first, then ``release``s
        them; in between the ids are unreachable (not in the map, not in
        the free list), so concurrent ingest cannot be wiped."""
        if self.last_seen is None:
            return []
        with self._lock:
            retired = []
            for i, t in list(self.last_seen.items()):
                if now_ms - t > idle_ms and i < len(self._reverse) \
                        and self._reverse[i] is not None:
                    self._map.pop(self._reverse[i], None)
                    self._reverse[i] = None
                    del self.last_seen[i]
                    retired.append(i)
            if retired:
                self._lut.fill(-1)  # retired raw keys must re-probe
            return retired

    def release(self, ids: List[int]):
        with self._lock:
            self._free.extend(ids)

    def __len__(self):
        # capacity semantics: freed slots still occupy the dense range
        return len(self._reverse)

    def snapshot(self) -> dict:
        with self._lock:
            return {"map": dict(self._map), "free": list(self._free),
                    "n": len(self._reverse)}

    def restore(self, snap: dict):
        import time as _time

        with self._lock:
            self._map = dict(snap["map"])
            n = snap.get("n", len(self._map))
            self._reverse = [None] * n
            for k, i in self._map.items():
                self._reverse[i] = k
            self._free = list(snap.get("free", []))
            self._lut.fill(-1)  # raw-key bindings may have changed
            if self.last_seen is not None:
                # restored keys start their idle clocks at restore time —
                # otherwise pre-restart keys would be invisible to purge
                now = int(_time.time() * 1000)
                self.last_seen = {i: now for i in self._map.values()}


class ValuePartitionKeyer:
    """``partition with (expr of Stream)``: tuple of expression values ->
    dense pk id via the partition's shared key space."""

    def __init__(self, fns: List[Tuple[Callable, AttrType]], keyspace: PartitionKeySpace):
        self._fns = fns
        self._keyspace = keyspace

    def __len__(self):
        return max(len(self._keyspace), 1)

    @property
    def static_keys(self) -> Optional[int]:
        return None  # dynamic key space

    def apply(self, cols: Dict[str, np.ndarray]):
        """Returns (cols, pk_ids). Null-key CURRENT rows are invalidated;
        non-CURRENT rows (TIMER) pass through with pk 0."""
        ctx = {"xp": np}
        valid = cols[VALID_KEY]
        is_cur = valid & (cols[TYPE_KEY] == CURRENT)
        B = valid.shape[0]
        pk = np.zeros(B, np.int32)
        vals = []
        drop = np.zeros(B, bool)
        for fn, _t in self._fns:
            v, m = fn(cols, ctx)
            vals.append(np.broadcast_to(np.asarray(v), (B,)))
            if m is not None:
                drop |= np.broadcast_to(np.asarray(m), (B,)) & is_cur
        keyed = np.nonzero(is_cur & ~drop)[0]
        if keyed.size:
            got = None
            if len(vals) == 1 and vals[0].dtype.kind in "iu":
                # single int key (dictionary-encoded strings included):
                # one np.take through the keyspace table in steady state
                got = self._keyspace.ids_of_ints(
                    np.ascontiguousarray(vals[0][keyed]).astype(np.int64))
            if got is not None:
                pk[keyed] = got
            else:
                # vectorized dictionary encoding (shared helper — unique the
                # key tuples once, probe the Python keyspace only per unique)
                from siddhi_tpu.core.event import encode_key_tuples

                pk[keyed] = encode_key_tuples(vals, keyed, self._keyspace.id_of)
            if self._keyspace.last_seen is not None:
                import time as _time

                self._keyspace.touch(pk[keyed], int(_time.time() * 1000))
        if drop.any():
            cols = dict(cols)
            cols[VALID_KEY] = valid & ~drop
        return cols, pk


class RangePartitionKeyer:
    """``partition with (cond as 'label' or ... of Stream)``: pk id = range
    index (static key space). Rows are duplicated per matching range."""

    def __init__(self, conditions: List[Tuple[str, Callable]]):
        self._conditions = conditions  # [(label, condition fn)]
        # highest range id a keyed event has actually hit + 1: range
        # instances are lazily created too (reference initPartition), so
        # only instances this watermark covers may receive global-side
        # broadcast events
        self.seen_keys = 0

    def __len__(self):
        return len(self._conditions)

    @property
    def static_keys(self) -> Optional[int]:
        return len(self._conditions)

    def apply(self, cols: Dict[str, np.ndarray]):
        """Expand rows: a CURRENT row matching R ranges becomes R rows (in
        range order, reference PartitionStreamReceiver copy loop); rows
        matching none are dropped. TIMER/other rows are kept once (pk 0)."""
        ctx = {"xp": np}
        valid = cols[VALID_KEY]
        is_cur = valid & (cols[TYPE_KEY] == CURRENT)
        B = valid.shape[0]
        masks = np.zeros((B, len(self._conditions)), bool)
        for r, (_label, fn) in enumerate(self._conditions):
            masks[:, r] = np.asarray(fn(cols, ctx)) & is_cur
        keep_once = valid & ~is_cur  # TIMER etc. — not range-matched

        rows_cur, rngs = np.nonzero(masks)          # row-major: event order kept
        if rngs.size:
            self.seen_keys = max(self.seen_keys, int(rngs.max()) + 1)
        rows_other = np.nonzero(keep_once)[0]
        rows = np.concatenate([rows_cur, rows_other])
        pk_out = np.concatenate([rngs, np.zeros(len(rows_other), np.int64)]).astype(np.int32)
        order = np.argsort(rows, kind="stable")
        rows, pk_out = rows[order], pk_out[order]

        n = len(rows)
        cap = _pad_len(max(n, 1))
        out: Dict[str, np.ndarray] = {}
        for k, v in cols.items():
            arr = np.zeros(cap, v.dtype)
            arr[:n] = v[rows]
            out[k] = arr
        out[VALID_KEY] = np.zeros(cap, bool)
        out[VALID_KEY][:n] = True  # selected rows are valid by construction
        pk = np.zeros(cap, np.int32)
        pk[:n] = pk_out
        return out, pk

    def snapshot(self) -> dict:
        return {}

    def restore(self, snap: dict):
        pass


class PartitionContext:
    """Planning context for one ``partition ... begin ... end`` block:
    the per-stream keyers plus the partition's inner-stream ('#stream')
    definitions and junctions (reference PartitionRuntimeImpl holds inner
    junctions per partition, here one junction whose events carry pk ids)."""

    def __init__(self, index: int):
        self.index = index
        self.keyspace = PartitionKeySpace()
        self.keyers: Dict[str, object] = {}      # outer stream id -> keyer
        self.inner_definitions: Dict[str, object] = {}   # '#X' -> StreamDefinition
        self.inner_junctions: Dict[str, object] = {}     # '#X' -> StreamJunction
        # @purge config + the block's query runtimes (wired by app_runtime)
        self.purge_interval_ms: Optional[int] = None
        self.purge_idle_ms: Optional[int] = None
        self.runtimes: List[object] = []

    def num_keys(self) -> int:
        static = [k.static_keys for k in self.keyers.values() if k.static_keys]
        return max(max(static, default=0), len(self.keyspace), 1)

    def active_keys(self) -> int:
        """Keys whose instances actually EXIST (no 1-floor, no static
        floor): bounds which instances receive a global stream's events —
        an instance created later must not see earlier events (reference
        lazy initPartition). Range keyers report their seen-id watermark,
        value keyers the allocated keyspace."""
        seen = [getattr(k, "seen_keys", 0) for k in self.keyers.values()]
        return max(max(seen, default=0), len(self.keyspace))

    def purge(self, now_ms: Optional[int] = None) -> List[int]:
        """Retire idle partition keys, reset their dense state rows in
        every query runtime of this block, then release the ids for reuse
        (reference @purge idle-partition eviction). Idle comparison uses
        WALL clock (touch() stamps wall time) — the scheduler's event-time
        tick value is ignored on purpose (playback apps mix clocks)."""
        import time as _time

        if now_ms is None:
            now_ms = int(_time.time() * 1000)
        idle = self.purge_idle_ms if self.purge_idle_ms is not None else 3600_000
        retired = self.keyspace.retire_idle(now_ms, idle)
        if retired:
            for rt in self.runtimes:
                rt.reset_partition_keys(retired)
            self.keyspace.release(retired)
        return retired
