from siddhi_tpu.core.partition.partition import (
    PartitionContext,
    RangePartitionKeyer,
    ValuePartitionKeyer,
)

__all__ = ["PartitionContext", "RangePartitionKeyer", "ValuePartitionKeyer"]
