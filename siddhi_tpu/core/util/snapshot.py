"""SnapshotService: checkpoint/restore of a whole app's state.

Mirror of reference ``util/snapshot/SnapshotService.java:51-800`` + the
``persist()/restoreRevision/restoreLastRevision`` lifecycle
(``SiddhiAppRuntimeImpl.java:677-755``), redesigned for dense state: the
hierarchical map-of-State-objects walk becomes one pytree per query
(device arrays -> numpy), plus the host-side key dictionaries (string
dictionary, group keyers, partition key spaces) and the shared stores
(tables, named windows). The app barrier quiesces input during both
operations (the ThreadBarrier role, ``util/ThreadBarrier.java``).

The wire format is a versioned pickle of numpy arrays — intentionally not
the reference's JDK serialization (impl-private there too, SURVEY.md §7).
"""

from __future__ import annotations

import itertools
import logging
import pickle
import time
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

log = logging.getLogger(__name__)

# v2: named-window entries became {'host','data'} wrappers, queries gained
# 'host_window'
# v3: aggregation snapshots carry base_keys (avg gained per-output cnt@
# bases; positional slot lists would misalign against v2 snapshots)
# v4: GroupKeyer key tuples gained null-mask elements (general path) and
# the single-string LUT moved to shifted dict ids — older keyer_map
# snapshots would silently orphan their aggregate rows
FORMAT_VERSION = 4


# one jitted identity per replicated sharding: jax.jit caches by wrapped
# function identity, so a fresh lambda per leaf per persist would pay a
# full recompile of the allgather at every checkpoint
_REPLICATE_JIT: dict = {}


def _telemetry():
    # process-global registry: _to_host is a module function with no app
    # context in scope, and the replicate-jit cache is process-wide too
    from siddhi_tpu.observability.telemetry import global_registry

    return global_registry()


def _to_host(tree):
    import jax

    def pull(x):
        if getattr(x, "is_fully_addressable", True) is False:
            # multi-process mesh: this host cannot read the peer shards
            # directly — replicate through one allgather so the snapshot
            # is WHOLE on every host and any survivor can restore
            # (requires every process to capture at the same point, the
            # SPMD contract persist() already runs under). jit identity
            # with a replicated out_sharding compiles to that allgather.
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(x.sharding.mesh, PartitionSpec())
            fn = _REPLICATE_JIT.get(rep)
            _telemetry().record_jit("snapshot.replicate_allgather",
                                    hit=fn is not None)
            if fn is None:
                fn = jax.jit(lambda a: a, out_shardings=rep)
                _REPLICATE_JIT[rep] = fn
            x = fn(x)
        return np.asarray(x)

    return jax.tree_util.tree_map(pull, tree)


def _to_device(tree):
    import jax

    return jax.tree_util.tree_map(lambda x: jnp.asarray(x), tree)


class SnapshotService:
    def __init__(self, app_runtime):
        self.app_runtime = app_runtime

    # ------------------------------------------------------------ capture

    def _capture_common(self) -> dict:
        rt = self.app_runtime
        dictionary = rt.app_context.string_dictionary
        pump = getattr(rt.app_context, "completion_pump", None)
        if pump is not None and pump.has_pending:
            # batches riding the dispatch pipeline drain INSIDE the
            # barrier: their state updates are already in the pytrees the
            # capture reads, so their outputs must emit before the cut —
            # a restore must neither lose nor re-emit them
            pump.flush()
        for q in rt.query_runtimes.values():
            if getattr(q, "_deferred", None):
                q.flush_deferred()   # un-emitted outputs must not be lost
        queries = {}
        for name, q in rt.query_runtimes.items():
            with q._lock:
                rl = getattr(q, "_route_layout", None)
                if rl is not None and q._state is not None:
                    # device-routed runtimes snapshot CANONICAL (unsharded)
                    # state at GLOBAL capacities, so revisions cross-restore
                    # between any shard counts and the unsharded runtime
                    from siddhi_tpu.parallel.mesh import canonical_route_state

                    state = canonical_route_state(q)
                    sel_keys = rl.n * rl.localK
                    win_keys = (rl.n * rl.local_win
                                if rl.local_win > 1 else q._win_keys)
                else:
                    state = q._state
                    sel_keys = q.selector_plan.num_keys
                    win_keys = q._win_keys
                strip = getattr(q, "strip_engine_state", None)
                if strip is not None and state is not None:
                    # join engine (core/join/): the partition directories
                    # and cross-stream sequence are derived state — the
                    # snapshot stores the canonical [W] ring layout only,
                    # so revisions cross-restore engine<->legacy and
                    # across join_partitions values
                    state = strip(state)
                queries[name] = {
                    "state": _to_host(state) if state is not None else None,
                    "sel_keys": sel_keys,
                    "win_keys": win_keys,
                    "keyer_map": dict(q.keyer._map) if q.keyer is not None else None,
                    "host_window": (q.host_window.snapshot()
                                    if q.host_window is not None else None),
                    "nfa_hwm": (np.array(q._nfa_hwm_arr)
                                if getattr(q, "_nfa_hwm_arr", None)
                                is not None else None),
                }
        windows = {}
        for wid, w in rt.named_windows.items():
            with w._lock:
                if w.host_mode:
                    windows[wid] = {"host": True, "data": w.stage.snapshot()}
                else:
                    windows[wid] = {"host": False, "data": _to_host(w.state)}
        return {
            "version": FORMAT_VERSION,
            "app": rt.name,
            "strings": list(dictionary._to_str),
            "queries": queries,
            "windows": windows,
            "partitions": [p.keyspace.snapshot() for p in rt.partition_contexts],
            # playback event clock: restoring mid-trace must resume event
            # time, or re-armed timers land at WALL-clock timestamps and
            # held windows never expire (reference persists via the
            # element snapshot map; the clock travels with it)
            "clock": rt.app_context.timestamp_generator._last_event_ts,
        }

    def full_snapshot(self) -> bytes:
        """Pure capture — op logs are untouched; PersistenceManager calls
        ``mark_checkpoint`` only after the revision is durably saved."""
        rt = self.app_runtime
        obj = self._capture_common()
        tables = {}
        for tid, t in rt.tables.items():
            if not hasattr(t, "state"):
                continue    # @store record tables own their durability
            with t._lock:
                tables[tid] = {"state": _to_host(t.state), "capacity": t.capacity}
        obj["tables"] = tables
        obj["aggregations"] = {aid: a.snapshot() for aid, a in rt.aggregations.items()}
        return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)

    def mark_checkpoint(self):
        """Clear the incremental op logs after a checkpoint is durably
        stored (clear-before-save would lose deltas on a failed save)."""
        rt = self.app_runtime
        for t in rt.tables.values():
            if hasattr(t, "clear_oplog"):
                t.clear_oplog()
        for a in rt.aggregations.values():
            a.clear_oplog()

    def incremental_snapshot(self, base_revision: str) -> bytes:
        """Checkpoint with op-log deltas for the heavy history holders
        (aggregation buckets, table inserts) and full state for the light
        components — the reference's incremental SnapshotService split
        (``SnapshotService.java:189`` IncrementalSnapshotable)."""
        rt = self.app_runtime
        obj = self._capture_common()
        obj["incremental"] = True
        obj["base"] = base_revision
        obj["tables_inc"] = {
            tid: t.incremental_snapshot()
            for tid, t in rt.tables.items() if hasattr(t, "incremental_snapshot")
        }
        obj["aggregations_inc"] = {
            aid: a.incremental_snapshot() for aid, a in rt.aggregations.items()
        }
        return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)

    # ------------------------------------------------------------ restore

    def restore(self, data: bytes):
        obj = pickle.loads(data)
        if obj.get("incremental"):
            raise ValueError(
                "incremental snapshot cannot be restored standalone — "
                "restore its base chain via PersistenceManager")
        self._restore_obj(obj)
        self.mark_checkpoint()   # restored state must not re-enter op logs

    def apply_incremental(self, data: bytes, rearm: bool = True):
        """Apply one incremental checkpoint on top of already-restored
        state: light components overwrite, heavy ones apply op logs."""
        obj = pickle.loads(data) if isinstance(data, (bytes, bytearray)) else data
        self._restore_obj(obj, incremental=True)
        rt = self.app_runtime
        for tid, snap in obj.get("tables_inc", {}).items():
            t = rt.tables.get(tid)
            if t is not None and hasattr(t, "apply_increment"):
                t.apply_increment(snap)
        for aid, snap in obj.get("aggregations_inc", {}).items():
            a = rt.aggregations.get(aid)
            if a is not None:
                a.apply_increment(snap)
        if rearm:
            self._rearm_schedulers()

    def _restore_obj(self, obj, incremental: bool = False):
        if obj.get("version") != FORMAT_VERSION:
            raise ValueError(
                f"snapshot format {obj.get('version')} is not supported "
                f"(expected {FORMAT_VERSION})"
            )
        rt = self.app_runtime
        if obj.get("app") != rt.name:
            raise ValueError(
                f"snapshot belongs to app '{obj.get('app')}', not '{rt.name}' — "
                f"name apps with @app:name for stable restore identities"
            )
        dictionary = rt.app_context.string_dictionary
        # the fresh runtime's compile-time dictionary entries are a prefix of
        # the snapshot's (same app text parses in the same order)
        strings = obj["strings"]
        if strings[: len(dictionary._to_str)] != dictionary._to_str[:len(strings)]:
            raise ValueError(
                "snapshot belongs to a different app (string dictionaries diverge)"
            )
        dictionary.restore_strings(strings)

        # resume the event clock: re-armed timers and window deadlines
        # must anchor to restored EVENT time, not wall time. Forced (not
        # monotone) — restoring an EARLIER revision in-place rolls the
        # clock back with the state (reference restoreRevision replay)
        clock = obj.get("clock", -1)
        if clock is not None and clock >= 0:
            rt.app_context.timestamp_generator.reset_timestamp(int(clock))

        for snap, pctx in zip(obj["partitions"], rt.partition_contexts):
            pctx.keyspace.restore(snap)

        pump = getattr(rt.app_context, "completion_pump", None)
        if pump is not None:
            # in-flight pipelined outputs belong to the rolled-back
            # timeline — discard without emitting (like q._deferred below)
            pump.discard_all()

        for name, qsnap in obj["queries"].items():
            q = rt.query_runtimes.get(name)
            if q is None:
                raise ValueError(f"snapshot has unknown query '{name}'")
            with q._lock:
                q._deferred = []   # pre-restore outputs belong to the
                #                    rolled-back timeline — discard
                if q.rate_limiter is not None:
                    # likewise: buffered/counted limiter state would flush
                    # phantom pre-restore events after the rollback
                    q.rate_limiter.reset()
                q.selector_plan.num_keys = qsnap["sel_keys"]
                q._win_keys = qsnap["win_keys"]
                if getattr(q, "_route_layout", None) is not None:
                    # device-routed runtimes relayout host-side and upload
                    # shard-major below (adopt_canonical) — a _to_device
                    # here would round-trip the whole canonical state
                    # through the device for nothing
                    q._state = qsnap["state"]
                else:
                    q._state = _to_device(qsnap["state"]) if qsnap["state"] is not None else None
                if q.keyer is not None and qsnap["keyer_map"] is not None:
                    # write into the member's OWN keyer: a fused fan-out
                    # group may have aliased q.keyer to a sibling's
                    # (identical-computation dedup), and a restored
                    # snapshot can carry divergent per-member maps — the
                    # group re-derives sharing below (on_restore)
                    keyer = getattr(q, "_own_keyer", None)
                    if keyer is None:   # explicit: an empty keyer is falsy
                        keyer = q.keyer
                    keyer._map = dict(qsnap["keyer_map"])
                    keyer._next = max(keyer._map.values(), default=-1) + 1
                    keyer._lut = np.full(64, -1, np.int32)  # lazily rebuilt
                    if keyer is not q.keyer:
                        q.keyer = keyer
                if getattr(q, "_route_layout", None) is not None:
                    # snapshots store canonical layout/capacities; re-derive
                    # THIS runtime's shard-major layout (the snapshot may
                    # come from a different shard count, or be unsharded)
                    from siddhi_tpu.parallel.mesh import adopt_canonical

                    adopt_canonical(q, qsnap["sel_keys"], qsnap["win_keys"])
                if q.host_window is not None and qsnap.get("host_window") is not None:
                    q.host_window.restore(qsnap["host_window"])
                if hasattr(q, "_nfa_hwm_arr"):
                    # no nfa_hwm in the snapshot -> the mirror must RESET:
                    # keeping post-snapshot high-water marks after a
                    # rollback would permanently classify every later
                    # batch as hard (fast kernel never used) and feed
                    # expire_to clocks from the abandoned timeline
                    hwm = qsnap.get("nfa_hwm")
                    q._nfa_hwm_arr = (np.array(hwm, np.int64)
                                      if hwm is not None else None)
                q._step = None
                if hasattr(q, "_steps"):
                    q._steps.clear()
                adopt = getattr(q, "adopt_restored_state", None)
                if adopt is not None:
                    # join engine: rebuild the partition directories from
                    # the restored canonical rings (and reset the drain-
                    # sequence expectation)
                    adopt()

        # fused fan-out groups: re-derive keyer sharing from the restored
        # maps and drop the compiled fused step (key capacities changed)
        for g in getattr(rt, "fused_fanout_groups", ()) or ():
            g.on_restore()

        for tid, tsnap in obj.get("tables", {}).items():
            t = rt.tables.get(tid)
            if t is None:
                raise ValueError(f"snapshot has unknown table '{tid}'")
            with t._lock:
                t.state = _to_device(tsnap["state"])
                t.capacity = tsnap["capacity"]
                t._pk_dirty = True

        for aid, asnap in obj.get("aggregations", {}).items():
            a = rt.aggregations.get(aid)
            if a is None:
                raise ValueError(f"snapshot has unknown aggregation '{aid}'")
            a.restore(asnap)

        for wid, wsnap in obj["windows"].items():
            w = rt.named_windows.get(wid)
            if w is None:
                raise ValueError(f"snapshot has unknown window '{wid}'")
            with w._lock:
                if wsnap.get("host"):
                    w.stage.restore(wsnap["data"])
                else:
                    w.state = _to_device(wsnap["data"])
                    w._step = None

        if not incremental:
            self._rearm_schedulers()

    def _rearm_schedulers(self):
        """Re-arm expiry timers on restored time-driven stages (the
        reference re-schedules on restore; without this, in live mode
        restored held events would wait for the next arrival to expire).
        One immediate TIMER step per stage drains anything already due and
        re-requests the stage's next wake time via ``__notify__``."""
        rt = self.app_runtime
        scheduler = rt.app_context.scheduler
        if scheduler is None:
            return
        # timers of the pre-restore timeline are void (esp. on rollback,
        # where they'd sit in the FUTURE of the restored clock)
        scheduler.clear_pending()
        now = int(rt.app_context.timestamp_generator.current_time())
        for q in rt.query_runtimes.values():
            if getattr(q, "_state", None) is None:
                continue
            sides = getattr(q, "sides", None)
            if sides is not None:  # join runtime: per-side timer callbacks
                for sk, side in sides.items():
                    if side.window_stage is not None and side.window_stage.needs_scheduler:
                        scheduler.notify_at(now, q._timer_cbs[sk])
                continue
            win = getattr(q, "window_stage", None)
            host = getattr(q, "host_window", None)
            needs = (win is not None and win.needs_scheduler) or (
                host is not None and getattr(host, "needs_scheduler", False))
            if needs:
                scheduler.notify_at(now, q.process_timer)
        for w in rt.named_windows.values():
            stage_needs = getattr(w.stage, "needs_scheduler", False)
            if stage_needs:
                scheduler.notify_at(now, w.process_timer)


class PersistenceManager:
    """persist/restore lifecycle against the configured store (reference
    SiddhiAppRuntimeImpl.persist:677 / restoreRevision:719)."""

    def __init__(self, app_runtime):
        self.app_runtime = app_runtime
        self.snapshot_service = SnapshotService(app_runtime)
        self._last_revision: Optional[str] = None
        # persistence is in use: start journaling table inserts so
        # incremental checkpoints have an op log to draw from
        for t in app_runtime.tables.values():
            if hasattr(t, "journal_enabled"):
                t.journal_enabled = True

    def _store(self):
        store = self.app_runtime.app_context.siddhi_context.persistence_store
        if store is None:
            raise RuntimeError(
                "no persistence store configured — call "
                "SiddhiManager.set_persistence_store(...) first"
            )
        return store

    _seq = itertools.count()  # ms collisions must not overwrite snapshots

    def _drain_async_junctions(self, timeout_s: float = 5.0) -> bool:
        """Wait (holding the barrier) until every @Async junction's queue
        and in-flight unit have been APPLIED. The WAL records at the
        InputHandler boundary — BEFORE the async queue — so a cut taken
        while batches are still queued would trim events whose effects are
        not in the snapshot, and a restore would silently lose them. The
        barrier stops new sends; the workers keep draining. Returns False
        if a (wedged) worker did not drain in time."""
        from siddhi_tpu.core.stream.junction import _NOTHING

        rt = self.app_runtime
        deadline = time.monotonic() + timeout_s
        while True:
            busy = [j for j in rt.junctions.values()
                    if getattr(j, "_async", False) and j._running
                    and (not j._queue.empty()
                         or j._inflight is not _NOTHING)]
            if not busy:
                return True
            if time.monotonic() > deadline:
                log.warning(
                    "persist: async junction(s) %s did not drain in %.1fs "
                    "— the ingest WAL will not be trimmed for this "
                    "checkpoint (replay may overlap the snapshot)",
                    [j.definition.id for j in busy], timeout_s)
                return False
            time.sleep(0.001)

    def persist(self, incremental: bool = False) -> str:
        """Full checkpoint, or (``incremental=True``, after at least one
        full) an op-log delta chained to the previous revision (reference
        incremental SnapshotService + IncrementalPersistenceStore)."""
        from siddhi_tpu.observability.tracing import span

        t_start = time.perf_counter()
        rt = self.app_runtime
        store = self._store()
        wal = getattr(rt.app_context, "ingest_wal", None)
        with span("persist", app=rt.name, incremental=incremental):
            with rt._barrier:  # quiesce inputs (ThreadBarrier)
                # accepted-but-queued async batches must be applied before
                # the capture, or the WAL cut below would cover them
                # unapplied
                drained = self._drain_async_junctions() if wal is not None \
                    else True
                if incremental and self._last_revision is not None:
                    data = self.snapshot_service.incremental_snapshot(
                        self._last_revision)
                else:
                    data = self.snapshot_service.full_snapshot()
                # the WAL cut marks what this snapshot covers; the trim
                # waits for the durable save — a batch accepted after the
                # barrier releases must survive in the log
                # (resilience/replay.py)
                wal_cut = wal.cut() if (wal is not None and drained) else None
            # sortable: ms prefix, then a process-monotonic counter
            revision = (f"{int(time.time() * 1000):020d}_"
                        f"{next(self._seq):06d}_{rt.name}")
            store.save(rt.name, revision, data)
            # only after the save is durable: clear the op logs
            self.snapshot_service.mark_checkpoint()
            if wal_cut is not None:
                wal.trim(wal_cut)
                wal.checkpoint_revision = revision
            self._last_revision = revision
        sm = rt.app_context.statistics_manager
        if sm is not None and sm.level >= 1:
            # checkpoint stalls ingest for its whole barrier'd capture —
            # its tail belongs on the same percentile surface as queries
            sm.latency_tracker("snapshot.persist").record(
                (time.perf_counter() - t_start) * 1000.0)
        return revision

    def persist_incremental(self) -> str:
        return self.persist(incremental=True)

    def restore_revision(self, revision: str):
        rt = self.app_runtime
        store = self._store()
        # walk the base chain: a stack of increments over one full snapshot
        chain: List[dict] = []
        rev: Optional[str] = revision
        while rev is not None:
            data = store.load(rt.name, rev)
            if data is None:
                raise KeyError(f"revision '{rev}' not found for app '{rt.name}'")
            obj = pickle.loads(data)
            chain.append(obj)
            rev = obj.get("base") if obj.get("incremental") else None
        with rt._barrier:
            self.snapshot_service._restore_obj(chain[-1])
            for obj in reversed(chain[:-1]):
                self.snapshot_service.apply_incremental(obj, rearm=False)
            self.snapshot_service._rearm_schedulers()
            # replayed state must not re-enter the next delta's op log
            self.snapshot_service.mark_checkpoint()
        self._last_revision = revision
        # effectively-once: re-feed the post-checkpoint ingest suffix in
        # arrival order (outside the barrier — replay sends re-enter it).
        # The suffix FOLLOWS wal.checkpoint_revision; replaying it onto an
        # OLDER restored revision would graft it onto a base it never
        # followed (with the middle missing), so that case skips the
        # replay and leaves the log intact. Revisions sort by their ms
        # prefix; a NEWER revision (an SPMD peer's simultaneous
        # checkpoint, cluster recovery) is a valid base for the suffix.
        wal = getattr(rt.app_context, "ingest_wal", None)
        if wal is not None and len(wal):
            if (wal.checkpoint_revision is None
                    or revision >= wal.checkpoint_revision):
                wal.replay(rt)
            else:
                log.warning(
                    "ingest-WAL replay skipped: restored revision %s "
                    "precedes the WAL's checkpoint %s — the retained "
                    "suffix does not follow this base",
                    revision, wal.checkpoint_revision)

    def restore_last_revision(self) -> Optional[str]:
        rt = self.app_runtime
        store = self._store()
        rev = store.get_last_revision(rt.name)
        if rev is not None:
            self.restore_revision(rev)
        return rev

    def clear_all_revisions(self):
        self._store().clear_all_revisions(self.app_runtime.name)
        # the next incremental must not chain to a wiped revision
        self._last_revision = None
