"""Persistence stores: where snapshots go.

Mirror of reference ``util/persistence/{InMemoryPersistenceStore.java:30,
FileSystemPersistenceStore.java:33}``. Incremental (op-log) stores are
intentionally absent: dense-array state snapshots are already O(state)
(SURVEY.md §5.4) — a full snapshot IS the efficient form here.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional


class InMemoryPersistenceStore:
    def __init__(self):
        self._store: Dict[str, Dict[str, bytes]] = {}

    def save(self, app_name: str, revision: str, snapshot: bytes):
        self._store.setdefault(app_name, {})[revision] = snapshot

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        return self._store.get(app_name, {}).get(revision)

    def get_last_revision(self, app_name: str) -> Optional[str]:
        revs = self.revisions(app_name)
        return revs[-1] if revs else None

    def revisions(self, app_name: str) -> List[str]:
        return sorted(self._store.get(app_name, {}))

    def clear_all_revisions(self, app_name: str):
        self._store.pop(app_name, None)


class FileSystemPersistenceStore:
    def __init__(self, base_path: str):
        self.base_path = base_path

    def _dir(self, app_name: str) -> str:
        return os.path.join(self.base_path, app_name)

    def save(self, app_name: str, revision: str, snapshot: bytes):
        d = self._dir(app_name)
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, revision + ".tmp")
        with open(tmp, "wb") as f:
            f.write(snapshot)
        os.replace(tmp, os.path.join(d, revision + ".snapshot"))

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        path = os.path.join(self._dir(app_name), revision + ".snapshot")
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def get_last_revision(self, app_name: str) -> Optional[str]:
        revs = self.revisions(app_name)
        return revs[-1] if revs else None

    def revisions(self, app_name: str) -> List[str]:
        d = self._dir(app_name)
        if not os.path.isdir(d):
            return []
        return sorted(
            f[: -len(".snapshot")] for f in os.listdir(d) if f.endswith(".snapshot")
        )

    def clear_all_revisions(self, app_name: str):
        for rev in self.revisions(app_name):
            os.remove(os.path.join(self._dir(app_name), rev + ".snapshot"))
