"""Process-global compiled-program cache: N tenant apps, one compile.

ROADMAP item 2's finish line. PR 3 proved the dedup rule inside one
junction's fused fan-out group (equal jaxpr text + pairwise bit-equal
embedded constants + equal output tree => provably the same program);
PR 11's cost registry then MEASURED the cross-app duplicate clusters
that rule leaves on the table (``GET /programs``). This module promotes
the rule to a refcounted process-wide registry consulted by EVERY
jitted step family at first call, through the one choke point they all
share — ``observability.telemetry.InstrumentedJit`` (the
``analysis/step_registry.py`` inventory routes each builder's jit
through ``instrument_jit``).

Key anatomy — an entry is shared only when ALL of these match:

- **family** — the step-builder tag passed by the instrument_jit call
  site (``query_step``, ``fused_fanout``, ``device_join.left``, ...).
  Shardings on the jit wrapper (``in_shardings=...``) are INVISIBLE in
  the traced jaxpr, so construction families that differ only by
  wrapper sharding must never alias; the family tag is that witness.
- **extra** — a call-site sharding/mesh witness (e.g. ``str(mesh)`` for
  the GSPMD and routed builders) for variation WITHIN a family.
- **platform** — jax backend platform (a cpu executable is not a tpu
  executable).
- **donate signature** — the traced ``donate_argnums``.
- **jaxpr text** — the full closed-jaxpr string (deterministic variable
  naming, scalar literals inline; shapes/dtypes are part of the text,
  so a capacity re-jit is a different program by construction).
- **embedded constants** — pairwise bit-equal (closure-captured arrays
  are NOT in the text; ``equal_nan`` floats).
- **output tree** — structure + (shape, dtype, sharding) of every leaf
  (catches output-name-only differences).

Sharing guarantees: the shared object is the immutable ``jax.jit``
callable (and thus its compiled executables). State pytrees stay
per-app — every caller passes (and donates) its OWN state argument, so
two tenants sharing an executable can never observe each other, and
snapshots/restores stay canonical per app. A fingerprint (sha1 over the
jaxpr text, the PR-11 convention) buckets candidates; the full witness
above decides.

Refcounting is OWNER-scoped and identity-pinned (the PR-8 blue/green
convention): the owner token is the app's ``TelemetryRegistry``
INSTANCE, unique per runtime, so shutting down an OLD runtime during a
blue/green replace can never evict the program a newer same-named app
is sharing. ``SiddhiAppRuntime.shutdown`` releases its owner; entries
evict at refcount zero. Within an app's lifetime a replaced step's ref
lingers until that app's shutdown (refs are per owner, not per
wrapper) — the ``program_cache_max`` cap bounds the resulting slack by
evicting zero-ref entries LRU-first and, at a full cache, compiling
privately instead of caching.

Knobs (typed registry, ``core/util/knobs.py``):
``siddhi_tpu.program_cache`` (bool, default on) gates participation per
app; ``siddhi_tpu.program_cache_max`` (int, default 256) caps live
entries. Process-default env spellings: ``SIDDHI_TPU_PROGRAM_CACHE`` /
``SIDDHI_TPU_PROGRAM_CACHE_MAX``.

Telemetry: ``program_cache.{hits,misses,evictions}`` counters and the
``program_cache.size`` gauge on the process registry (rendered as the
``siddhi_program_cache_*`` families; the gauge is removed at
``drain()``, graftlint R3 pairing). ``GET /programs`` serves
``cache().snapshot()`` next to the cost registry's clusters.
"""

from __future__ import annotations

import hashlib
import logging
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)


def values_equal(x, y) -> bool:
    """Bit-equality of two array-likes (shape, dtype, every element;
    ``equal_nan`` floats). Unequal on any doubt — the PR-3 rule."""
    try:
        x, y = np.asarray(x), np.asarray(y)
        if x.shape != y.shape or x.dtype != y.dtype:
            return False
        if np.issubdtype(x.dtype, np.floating):
            return bool(np.array_equal(x, y, equal_nan=True))
        return bool(np.array_equal(x, y))
    except Exception:  # noqa: BLE001 — unequal on any doubt
        return False


def _normalize_out(out_info) -> Tuple:
    """Hashable witness of a traced output tree: structure + per-leaf
    (shape, dtype, sharding) — OutInfo objects don't define equality."""
    import jax

    leaves, tree = jax.tree_util.tree_flatten(out_info)
    return (str(tree),
            tuple((tuple(leaf.shape), str(leaf.dtype),
                   str(getattr(leaf, "sharding", None)))
                  for leaf in leaves))


class CacheEntry:
    """One shared compiled program. ``jitted`` is the immutable
    ``jax.jit`` callable every sharer dispatches through; ``refs`` maps
    owner tokens (app ``TelemetryRegistry`` instances — identity-pinned)
    to their acquire counts."""

    __slots__ = ("fingerprint", "family", "extra", "platform", "donated",
                 "jaxpr_str", "consts", "out_norm", "jitted", "refs",
                 "hits", "keys", "seq")

    def __init__(self, fingerprint: str, family: str, extra: str,
                 platform: str, donated: Tuple, jaxpr_str: str, consts,
                 out_norm: Tuple, jitted):
        self.fingerprint = fingerprint
        self.family = family
        self.extra = extra
        self.platform = platform
        self.donated = donated
        self.jaxpr_str = jaxpr_str
        self.consts = list(consts)
        self.out_norm = out_norm
        self.jitted = jitted
        self.refs: Dict[object, int] = {}
        self.hits = 0
        self.keys: set = set()
        self.seq = 0

    def refcount(self) -> int:
        return sum(self.refs.values())

    def shared_by(self) -> List[str]:
        """App names holding refs (owner display; an owner token without
        a bound name reports as ``<process>``)."""
        return sorted({getattr(tok, "owner_name", "") or "<process>"
                       for tok in self.refs})

    def matches(self, family: str, extra: str, platform: str,
                donated: Tuple, jaxpr_str: str, consts,
                out_norm: Tuple) -> bool:
        if (self.family != family or self.extra != extra
                or self.platform != platform or self.donated != donated):
            return False
        if self.jaxpr_str != jaxpr_str or self.out_norm != out_norm:
            return False
        if len(self.consts) != len(consts):
            return False
        return all(values_equal(a, b)
                   for a, b in zip(self.consts, consts))


class ProgramCache:
    """The process-global registry (module singleton via ``cache()``)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._by_fp: Dict[str, List[CacheEntry]] = {}
        self._seq = 0
        self._gauge_on = False

    # ------------------------------------------------------------- attach

    def attach(self, key: str, family: str, jitted, args,
               owner, extra: str = "",
               max_entries: Optional[int] = None):
        """First-call hook: trace ``jitted`` with the real call args,
        look the program up, and either share an existing executable or
        register this one. Returns ``(fn, traced, hit)`` — ``fn`` is
        what the caller must dispatch through from now on; ``traced``
        is the jax AOT trace (reused by the cost registry so profiling
        never traces twice); ``hit`` is True when ``fn`` is a shared
        executable that did NOT need a compile. Never raises: any
        trace/introspection failure degrades to the uncached path."""
        try:
            trace = getattr(jitted, "trace", None)
            if trace is None:
                return jitted, None, False      # not a jax.jit callable
            traced = trace(*args)
            jaxpr_str = str(traced.jaxpr)
            consts = list(traced.jaxpr.consts)
            out_norm = _normalize_out(traced.out_info)
            donated = tuple(getattr(traced, "donate_argnums", ()) or ())
            fp = hashlib.sha1(jaxpr_str.encode()).hexdigest()[:16]
            import jax

            platform = jax.devices()[0].platform
        except Exception as e:  # noqa: BLE001 — cache must not break steps
            log.debug("program-cache trace failed for '%s': %r", key, e)
            return jitted, None, False
        from siddhi_tpu.observability.telemetry import global_registry

        tel = global_registry()
        with self._lock:
            self._seq += 1
            for entry in self._by_fp.get(fp, ()):
                if entry.matches(family, extra, platform, donated,
                                 jaxpr_str, consts, out_norm):
                    entry.refs[owner] = entry.refs.get(owner, 0) + 1
                    entry.keys.add(key)
                    entry.hits += 1
                    entry.seq = self._seq
                    tel.count("program_cache.hits")
                    return entry.jitted, traced, True
            tel.count("program_cache.misses")
            if max_entries is not None and max_entries >= 0:
                # a cap of zero caches nothing (every step compiles
                # privately); entries never evict a live-ref program
                if self._size_locked() >= max_entries:
                    self._evict_unreferenced_locked(
                        tel, down_to=max_entries - 1)
                if self._size_locked() >= max_entries:
                    # full of live programs: compile privately, uncached
                    return jitted, traced, False
            entry = CacheEntry(fp, family, extra, platform, donated,
                               jaxpr_str, consts, out_norm, jitted)
            entry.refs[owner] = 1
            entry.keys.add(key)
            entry.seq = self._seq
            self._by_fp.setdefault(fp, []).append(entry)
            self._ensure_gauge_locked(tel)
        return jitted, traced, False

    # ---------------------------------------------------------- lifecycle

    def release_owner(self, owner) -> int:
        """Drop every ref the owner token holds; entries reaching
        refcount zero are evicted (freed) immediately. Identity-pinned:
        a token that never acquired is a no-op, so an OLD runtime's
        shutdown cannot touch a survivor's programs. Returns the number
        of entries evicted."""
        from siddhi_tpu.observability.telemetry import global_registry

        tel = global_registry()
        evicted = 0
        with self._lock:
            for fp in list(self._by_fp):
                kept = []
                for entry in self._by_fp[fp]:
                    entry.refs.pop(owner, None)
                    if entry.refs:
                        kept.append(entry)
                    else:
                        evicted += 1
                        tel.count("program_cache.evictions")
                if kept:
                    self._by_fp[fp] = kept
                else:
                    del self._by_fp[fp]
        return evicted

    def _evict_unreferenced_locked(self, tel, down_to: int) -> None:
        """Evict zero-ref entries oldest-first until the cache holds at
        most ``down_to`` entries (cap enforcement; live-ref entries are
        never evicted by the cap)."""
        dead = [e for entries in self._by_fp.values()
                for e in entries if not e.refs]
        dead.sort(key=lambda e: e.seq)
        for entry in dead:
            if self._size_locked() <= down_to:
                break
            bucket = self._by_fp.get(entry.fingerprint, [])
            if entry in bucket:
                bucket.remove(entry)
                if not bucket:
                    del self._by_fp[entry.fingerprint]
                tel.count("program_cache.evictions")

    def _size_locked(self) -> int:
        return sum(len(v) for v in self._by_fp.values())

    def size(self) -> int:
        with self._lock:
            return self._size_locked()

    def _ensure_gauge_locked(self, tel) -> None:
        if not self._gauge_on:
            tel.gauge("program_cache.size", self.size)
            self._gauge_on = True

    def drain(self) -> int:
        """Evict everything and unregister the size gauge (R3 pairing:
        the gauge dies with the cache, not with the process). Tooling /
        test hook — live apps re-register on their next compile."""
        from siddhi_tpu.observability.telemetry import global_registry

        tel = global_registry()
        with self._lock:
            n = self._size_locked()
            for _ in range(n):
                tel.count("program_cache.evictions")
            self._by_fp.clear()
            if self._gauge_on:
                tel.remove_gauge("program_cache.size")
                self._gauge_on = False
        return n

    # ------------------------------------------------------------ reading

    def snapshot(self) -> dict:
        """The ``GET /programs`` cache section: every live entry with
        its sharers, plus the counter roll-up."""
        from siddhi_tpu.observability.telemetry import global_registry

        with self._lock:
            entries = [e for v in self._by_fp.values() for e in v]
            rows = [{
                "fingerprint": e.fingerprint,
                "family": e.family,
                "platform": e.platform,
                "keys": sorted(e.keys),
                "shared_by": e.shared_by(),
                "refcount": e.refcount(),
                "hits": e.hits,
            } for e in sorted(entries, key=lambda e: (-e.hits,
                                                      e.fingerprint))]
        counters = global_registry().snapshot().get("counters", {})
        return {
            "entries": rows,
            "size": len(rows),
            "hits": counters.get("program_cache.hits", 0),
            "misses": counters.get("program_cache.misses", 0),
            "evictions": counters.get("program_cache.evictions", 0),
        }


def enabled_for(app_context) -> bool:
    """Does this app participate? The per-app typed knob when a context
    is bound; the env process default otherwise."""
    if app_context is not None:
        return bool(getattr(app_context, "program_cache", True))
    from siddhi_tpu.core.util.knobs import env_knob

    return bool(env_knob("SIDDHI_TPU_PROGRAM_CACHE", "bool", True))


def max_entries_for(app_context) -> int:
    if app_context is not None:
        return int(getattr(app_context, "program_cache_max", 256))
    from siddhi_tpu.core.util.knobs import env_knob

    return int(env_knob("SIDDHI_TPU_PROGRAM_CACHE_MAX", "int", 256))


_CACHE = ProgramCache()


def cache() -> ProgramCache:
    return _CACHE
