"""Config system: ConfigManager + ConfigReader.

Mirror of reference ``util/config/{ConfigManager,InMemoryConfigManager,
FileConfigManager}.java`` + ``ConfigReader``: deployment-level properties
consulted by the engine (capacity knobs) and handed to extensions
(sources/sinks/stores) as namespaced readers. ``FileConfigManager`` reads a
flat ``key: value`` properties file (a YAML subset — no dependency).

Engine-consulted system keys (SiddhiAppContext startup):
  siddhi_tpu.window_capacity, siddhi_tpu.partition_window_capacity,
  siddhi_tpu.nfa_slots, siddhi_tpu.initial_key_capacity
"""

from __future__ import annotations

from typing import Dict, Optional


class ConfigManager:
    """Deployment config SPI (reference ConfigManager.java:26)."""

    def get_property(self, key: str) -> Optional[str]:
        raise NotImplementedError

    def generate_config_reader(self, namespace: str) -> "ConfigReader":
        return ConfigReader(self, namespace)


class InMemoryConfigManager(ConfigManager):
    def __init__(self, properties: Optional[Dict[str, str]] = None,
                 system_configs: Optional[Dict[str, str]] = None):
        self.properties = dict(properties or {})
        self.properties.update(system_configs or {})

    def get_property(self, key: str) -> Optional[str]:
        return self.properties.get(key)


class FileConfigManager(ConfigManager):
    """Flat `key: value` lines; '#' comments (FileConfigManager.java)."""

    def __init__(self, path: str):
        self.properties: Dict[str, str] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#") or ":" not in line:
                    continue
                k, v = line.split(":", 1)
                self.properties[k.strip()] = v.strip().strip("'\"")

    def get_property(self, key: str) -> Optional[str]:
        return self.properties.get(key)


class ConfigReader:
    """Namespaced view handed to extensions (reference ConfigReader):
    ``reader.read('topic')`` resolves ``<namespace>.topic``."""

    def __init__(self, manager: Optional[ConfigManager], namespace: str):
        self.manager = manager
        self.namespace = namespace

    def read(self, key: str, default: Optional[str] = None) -> Optional[str]:
        if self.manager is None:
            return default
        v = self.manager.get_property(f"{self.namespace}.{key}")
        return v if v is not None else default

    def get_all_configs(self) -> Dict[str, str]:
        if self.manager is None or not hasattr(self.manager, "properties"):
            return {}
        prefix = self.namespace + "."
        return {k[len(prefix):]: v
                for k, v in self.manager.properties.items()
                if k.startswith(prefix)}
