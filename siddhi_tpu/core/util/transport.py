"""In-memory pub/sub broker for the inMemory source/sink pair.

Mirror of reference ``util/transport/InMemoryBroker.java:29`` — a static
topic -> subscribers map used by tests and by apps wiring streams across
SiddhiApp instances without an external transport.
"""

from __future__ import annotations

import threading
from typing import Dict, List


class InMemoryBroker:
    _lock = threading.RLock()
    _subscribers: Dict[str, List[object]] = {}

    class Subscriber:
        """Implement ``on_message(payload)`` and ``topic`` (reference
        InMemoryBroker.Subscriber)."""

        topic: str = ""

        def on_message(self, payload):  # pragma: no cover - interface
            raise NotImplementedError

    @classmethod
    def subscribe(cls, subscriber) -> None:
        with cls._lock:
            cls._subscribers.setdefault(subscriber.topic, []).append(subscriber)

    @classmethod
    def unsubscribe(cls, subscriber) -> None:
        with cls._lock:
            subs = cls._subscribers.get(subscriber.topic, [])
            if subscriber in subs:
                subs.remove(subscriber)

    @classmethod
    def publish(cls, topic: str, payload) -> None:
        with cls._lock:
            subs = list(cls._subscribers.get(topic, []))
        for s in subs:
            s.on_message(payload)

    @classmethod
    def clear(cls) -> None:
        with cls._lock:
            cls._subscribers.clear()
