"""Statistics: throughput/latency/memory trackers with OFF/BASIC/DETAIL
levels.

Mirror of reference ``util/statistics/SiddhiStatisticsManager.java:35`` +
``ThroughputTracker`` / ``LatencyTracker`` metrics hung off junctions and
query runtimes (``StreamJunction.java:153-155``). Counters are plain host
ints guarded by the GIL (incremented at batch granularity, not per event —
the columnar pump makes per-batch the natural unit).

Levels: OFF (no collection), BASIC (throughput per junction/query),
DETAIL (adds per-query step latency). Enable with
``@app:statistics('true')`` or ``@app:statistics(level='detail',
reporter='console', interval='5 sec')``; snapshot programmatically with
``SiddhiAppRuntime.statistics()``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

OFF, BASIC, DETAIL = 0, 1, 2

_LEVELS = {"off": OFF, "basic": BASIC, "detail": DETAIL,
           "false": OFF, "true": BASIC}


def parse_level(s: Optional[str]) -> int:
    if s is None:
        return BASIC
    lv = _LEVELS.get(s.strip().lower())
    if lv is None:
        raise ValueError(f"unknown statistics level '{s}'")
    return lv


class ThroughputTracker:
    """Event counts + rate since creation/reset."""

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.batches = 0
        self._t0 = time.perf_counter()

    def add(self, n: int):
        self.count += n
        self.batches += 1

    def rate(self) -> float:
        dt = time.perf_counter() - self._t0
        return self.count / dt if dt > 0 else 0.0

    def reset(self):
        self.count = 0
        self.batches = 0
        self._t0 = time.perf_counter()


class LatencyTracker:
    """Per-batch processing latency aggregates (ms)."""

    def __init__(self, name: str):
        self.name = name
        self.n = 0
        self.total_ms = 0.0
        self.max_ms = 0.0

    def record(self, ms: float):
        self.n += 1
        self.total_ms += ms
        if ms > self.max_ms:
            self.max_ms = ms

    @property
    def avg_ms(self) -> float:
        return self.total_ms / self.n if self.n else 0.0

    def reset(self):
        self.n = 0
        self.total_ms = 0.0
        self.max_ms = 0.0


class StatisticsManager:
    """Per-app metric registry (reference SiddhiStatisticsManager)."""

    def __init__(self, level: int = OFF, reporter: Optional[str] = None,
                 interval_ms: int = 60_000):
        self.level = level
        self.reporter = reporter
        self.interval_ms = interval_ms
        self._lock = threading.RLock()
        self.throughput: Dict[str, ThroughputTracker] = {}
        self.latency: Dict[str, LatencyTracker] = {}
        self._job = None

    # ------------------------------------------------------------ trackers

    def throughput_tracker(self, name: str) -> ThroughputTracker:
        with self._lock:
            t = self.throughput.get(name)
            if t is None:
                t = self.throughput[name] = ThroughputTracker(name)
            return t

    def latency_tracker(self, name: str) -> LatencyTracker:
        with self._lock:
            t = self.latency.get(name)
            if t is None:
                t = self.latency[name] = LatencyTracker(name)
            return t

    # ------------------------------------------------------------- control

    def set_level(self, level: int):
        self.level = level

    def start_reporting(self, scheduler):
        if self.reporter == "console" and scheduler is not None:
            self._job = scheduler.schedule_periodic(
                self.interval_ms, lambda ts: print(self.format_report()))

    def stop_reporting(self, scheduler):
        if self._job is not None and scheduler is not None:
            scheduler.cancel(self._job)
            self._job = None

    # -------------------------------------------------------------- report

    def report(self) -> dict:
        with self._lock:
            return {
                "level": {OFF: "off", BASIC: "basic", DETAIL: "detail"}[self.level],
                "throughput": {
                    n: {"events": t.count, "batches": t.batches,
                        "events_per_sec": round(t.rate(), 1)}
                    for n, t in self.throughput.items()
                },
                "latency": {
                    n: {"batches": t.n, "avg_ms": round(t.avg_ms, 3),
                        "max_ms": round(t.max_ms, 3)}
                    for n, t in self.latency.items()
                },
            }

    def format_report(self) -> str:
        import json

        return json.dumps(self.report(), indent=1)

    def reset(self):
        with self._lock:
            for t in self.throughput.values():
                t.reset()
            for t in self.latency.values():
                t.reset()
