"""Statistics: throughput/latency trackers plus DETAIL-level memory and
buffered-events probes, with OFF/BASIC/DETAIL levels.

Mirror of reference ``util/statistics/SiddhiStatisticsManager.java:35`` +
``ThroughputTracker`` / ``LatencyTracker`` metrics hung off junctions and
query runtimes (``StreamJunction.java:153-155``). Counters are plain host
ints guarded by the GIL (incremented at batch granularity, not per event —
the columnar pump makes per-batch the natural unit).

Levels: OFF (no collection), BASIC (throughput per junction/query),
DETAIL (adds per-query step latency, per-element state memory and
buffered-event depths). Memory is the dense-state answer to the
reference's reflective deep-size walk
(``util/statistics/memory/ObjectSizeCalculator.java:66``,
``SiddhiAppRuntimeImpl.monitorQueryMemoryUsage:757-782``): every stateful
element is a pytree of arrays, so its footprint is the sum of leaf
``nbytes`` — exact and O(leaves), where the reference pays a reflective
object-graph walk. Buffered events mirror ``monitorBufferedEvents``
(``SiddhiAppRuntimeImpl.java:784-821`` / ``StreamJunction.
getBufferedEvents:356-361``): @Async junction queue depths + deferred
device outputs. Enable with ``@app:statistics('true')`` or
``@app:statistics(level='detail', reporter='console', interval='5 sec')``;
snapshot programmatically with ``SiddhiAppRuntime.statistics()``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional


def pytree_nbytes(tree) -> int:
    """Total bytes of every array leaf in a (possibly nested) pytree —
    exact state footprint for dense device/host arrays."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total

OFF, BASIC, DETAIL = 0, 1, 2


def latency_t0(sm: Optional["StatisticsManager"],
               level: int = DETAIL) -> Optional[float]:
    """Start a latency measurement: ``perf_counter()`` when ``sm`` collects
    at ``level``, else None. Pair with ``record_elapsed_ms`` — the shared
    timing pattern of the query/join/NFA runtimes (one helper so the
    copies cannot drift)."""
    if sm is not None and sm.level >= level:
        return time.perf_counter()
    return None


def record_elapsed_ms(sm: Optional["StatisticsManager"], name: str,
                      t0: Optional[float]) -> None:
    """Record elapsed ms since ``t0`` on ``sm``'s tracker; no-op when the
    paired ``latency_t0`` returned None."""
    if t0 is not None:
        sm.latency_tracker(name).record((time.perf_counter() - t0) * 1000.0)

_LEVELS = {"off": OFF, "basic": BASIC, "detail": DETAIL,
           "false": OFF, "true": BASIC}


def parse_level(s: Optional[str]) -> int:
    if s is None:
        return BASIC
    lv = _LEVELS.get(s.strip().lower())
    if lv is None:
        raise ValueError(f"unknown statistics level '{s}'")
    return lv


class ThroughputTracker:
    """Event counts + rate since creation/reset."""

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.batches = 0
        self._t0 = time.perf_counter()

    def add(self, n: int):
        self.count += n
        self.batches += 1

    def rate(self) -> float:
        dt = time.perf_counter() - self._t0
        return self.count / dt if dt > 0 else 0.0

    def reset(self):
        self.count = 0
        self.batches = 0
        self._t0 = time.perf_counter()


class LatencyTracker:
    """Per-batch processing latency aggregates (ms) with tail
    percentiles: every record also lands in a fixed-bucket log-spaced
    histogram (``observability/histogram.py``), so the avg-only view
    the reference's LatencyTracker offers is extended with p50/p95/p99
    — the numbers the PERF.md batching decisions actually hinge on."""

    def __init__(self, name: str):
        from siddhi_tpu.observability.histogram import Histogram

        self.name = name
        self.n = 0
        self.total_ms = 0.0
        self.max_ms = 0.0
        self.hist = Histogram()

    def record(self, ms: float):
        self.n += 1
        self.total_ms += ms
        if ms > self.max_ms:
            self.max_ms = ms
        self.hist.record(ms)

    @property
    def avg_ms(self) -> float:
        return self.total_ms / self.n if self.n else 0.0

    @property
    def p50_ms(self) -> float:
        return self.hist.quantile(0.50)

    @property
    def p95_ms(self) -> float:
        return self.hist.quantile(0.95)

    @property
    def p99_ms(self) -> float:
        return self.hist.quantile(0.99)

    def reset(self):
        self.n = 0
        self.total_ms = 0.0
        self.max_ms = 0.0
        self.hist.reset()


class StatisticsManager:
    """Per-app metric registry (reference SiddhiStatisticsManager)."""

    def __init__(self, level: int = OFF, reporter: Optional[str] = None,
                 interval_ms: int = 60_000):
        self.level = level
        self.reporter = reporter
        self.interval_ms = interval_ms
        self._lock = threading.RLock()
        self.throughput: Dict[str, ThroughputTracker] = {}
        self.latency: Dict[str, LatencyTracker] = {}
        # DETAIL probes, polled at report time (state footprints move with
        # every batch — sampling at the report beats tracking per step)
        self.memory_probes: Dict[str, Callable[[], int]] = {}
        self.buffer_probes: Dict[str, Callable[[], int]] = {}
        # named event counters (resilience: worker restarts, WAL replayed/
        # dropped batches, source/sink retries, peer recoveries) — rare,
        # operationally load-bearing events counted at every level > OFF
        self.counters: Dict[str, int] = {}
        self._job = None

    # ------------------------------------------------------------ trackers

    def throughput_tracker(self, name: str) -> ThroughputTracker:
        with self._lock:
            t = self.throughput.get(name)
            if t is None:
                t = self.throughput[name] = ThroughputTracker(name)
            return t

    def latency_tracker(self, name: str) -> LatencyTracker:
        with self._lock:
            t = self.latency.get(name)
            if t is None:
                t = self.latency[name] = LatencyTracker(name)
            return t

    def register_memory_probe(self, name: str, probe: Callable[[], int]):
        """Register a state-footprint probe (bytes), polled at DETAIL
        report time — the analog of monitorQueryMemoryUsage registering a
        MemoryUsageTracker per query/table/window/aggregation."""
        with self._lock:
            self.memory_probes[name] = probe

    def register_buffer_probe(self, name: str, probe: Callable[[], int]):
        """Register a buffered-events probe (pending event/batch count) —
        the analog of monitorBufferedEvents on @Async junctions."""
        with self._lock:
            self.buffer_probes[name] = probe

    def count(self, name: str, n: int = 1):
        """Bump a named event counter (see ``counters``)."""
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    # ------------------------------------------------------------- control

    def set_level(self, level: int):
        self.level = level

    def start_reporting(self, scheduler):
        if self.reporter == "console" and scheduler is not None:
            # with statistics OFF the tick prints nothing (the reference
            # stops its reporter when stats are disabled —
            # StatisticsTestCase test2)
            self._job = scheduler.schedule_periodic(
                self.interval_ms,
                lambda ts: print(self.format_report())
                if self.level > OFF else None)

    def stop_reporting(self, scheduler):
        if self._job is not None and scheduler is not None:
            scheduler.cancel(self._job)
            self._job = None

    # -------------------------------------------------------------- report

    def report(self) -> dict:
        with self._lock:
            out = {
                "level": {OFF: "off", BASIC: "basic", DETAIL: "detail"}[self.level],
                "throughput": {
                    n: {"events": t.count, "batches": t.batches,
                        "events_per_sec": round(t.rate(), 1)}
                    for n, t in self.throughput.items()
                },
                "latency": {
                    n: {"batches": t.n, "avg_ms": round(t.avg_ms, 3),
                        "max_ms": round(t.max_ms, 3),
                        "total_ms": round(t.total_ms, 3),
                        "p50_ms": round(t.p50_ms, 3),
                        "p95_ms": round(t.p95_ms, 3),
                        "p99_ms": round(t.p99_ms, 3)}
                    for n, t in self.latency.items()
                },
            }
            if self.counters:
                out["counters"] = dict(self.counters)
            if self.level >= DETAIL:
                mem = {}
                for n, probe in self.memory_probes.items():
                    try:
                        mem[n] = int(probe())
                    except Exception:
                        mem[n] = -1   # probe raced a teardown/regrow
                out["memory_bytes"] = mem
                out["memory_total_bytes"] = sum(v for v in mem.values()
                                                if v > 0)
                buf = {}
                for n, probe in self.buffer_probes.items():
                    try:
                        buf[n] = int(probe())
                    except Exception:
                        buf[n] = -1
                out["buffered_events"] = buf
            return out

    def format_report(self) -> str:
        import json

        return json.dumps(self.report(), indent=1)

    def reset(self):
        with self._lock:
            for t in self.throughput.values():
                t.reset()
            for t in self.latency.values():
                t.reset()
            self.counters.clear()
