"""Host scheduler: TIMER injection for time-based windows & rate limiters.

Mirror of reference ``util/Scheduler.java:48-171``: stages request a wake
time (``notifyAt``); in live mode a wall-clock timer fires, in playback mode
(``@app:playback``) the event-time clock drives firing
(``Scheduler.java:74-100`` onTimeChange). Fired targets receive the
timestamp and inject a TIMER chunk into their query chain (the role of
``EntryValveProcessor`` + ``sendTimerEvents``).

Playback ordering parity: the reference sets the clock in
``InputHandler.send`` *before* publishing to the junction, so pending timers
<= the new event time fire before the event is processed. Our
TimestampGenerator listeners run inside ``set_current_timestamp``, which
``InputHandler.send`` calls before ``junction.send_events`` — same order.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, Dict, List, Tuple


class Scheduler:
    def __init__(self, app_context):
        self.app_context = app_context
        self._lock = threading.RLock()
        self._heap: List[Tuple[int, int, Callable]] = []
        self._counter = itertools.count()
        self._scheduled: Dict[Tuple[int, int], bool] = {}
        self._live_timers: List[threading.Timer] = []
        self._periodic: List["_PeriodicJob"] = []
        self._stopped = False
        if app_context.playback:
            app_context.timestamp_generator.add_time_change_listener(self._on_time_change)

    # ------------------------------------------------------------- notify

    def notify_at(self, ts: int, target: Callable[[int], None]):
        """Request `target(ts)` to run at event/wall time `ts` (deduped)."""
        key = (id(target), int(ts))
        with self._lock:
            if self._stopped or key in self._scheduled:
                return
            self._scheduled[key] = True
            if self.app_context.playback:
                heapq.heappush(self._heap, (int(ts), next(self._counter), target))
                return
        # live mode: wall-clock timer
        delay = max(0.0, (ts - self.app_context.timestamp_generator.current_time()) / 1000.0)
        timer = threading.Timer(delay, self._fire_live, args=(ts, target, key))
        timer.daemon = True
        with self._lock:
            self._live_timers.append(timer)
        timer.start()

    def _fire_live(self, ts: int, target, key):
        with self._lock:
            if self._stopped:
                return
            self._scheduled.pop(key, None)
        target(ts)

    def _on_time_change(self, new_ts: int):
        while True:
            with self._lock:
                if not self._heap or self._heap[0][0] > new_ts:
                    return
                ts, _seq, target = heapq.heappop(self._heap)
                self._scheduled.pop((id(target), ts), None)
            target(ts)

    # ----------------------------------------------------------- periodic

    def schedule_periodic(self, interval_ms: int, callback: Callable[[int], None]):
        """Recurring tick every interval (used by time-based rate limiters
        and periodic triggers)."""
        job = _PeriodicJob(self, interval_ms, callback)
        with self._lock:
            self._periodic.append(job)
        job.arm()
        return job

    def cancel(self, job):
        job.cancelled = True
        if getattr(job, "_anchor_cancel", None) is not None:
            job._anchor_cancel()
            job._anchor_cancel = None

    def clear_pending(self):
        """Drop every pending timer of the abandoned timeline (snapshot
        restore): one-shots are re-requested by the restored stages, and
        periodic jobs (triggers, time rate limiters) are re-armed HERE at
        the restored clock — after a rollback their old heap entries
        would sit in the future of the replayed window and never fire."""
        with self._lock:
            self._heap.clear()
            self._scheduled.clear()
            for t in self._live_timers:
                t.cancel()
            self._live_timers.clear()
            jobs = [j for j in self._periodic if not j.cancelled]
        for j in jobs:
            j.arm()

    def shutdown(self):
        with self._lock:
            self._stopped = True
            for t in self._live_timers:
                t.cancel()
            self._live_timers.clear()
            self._heap.clear()
            self._scheduled.clear()


class _PeriodicJob:
    def __init__(self, scheduler: Scheduler, interval_ms: int, callback):
        self.scheduler = scheduler
        self.interval_ms = interval_ms
        self.callback = callback
        self.cancelled = False

    _anchor_cancel = None

    def arm(self):
        ctx = self.scheduler.app_context
        if self._anchor_cancel is not None:
            # re-arm (snapshot-restore clear_pending): a stale first-event
            # anchor would start a second interleaved periodic chain
            self._anchor_cancel()
            self._anchor_cancel = None
        if ctx.playback and ctx.timestamp_generator._last_event_ts < 0:
            def _anchor(first_ts: int):
                self._anchor_cancel = None
                if self.cancelled:
                    return
                self.next_ts = first_ts + self.interval_ms
                self.scheduler.notify_at(self.next_ts, self._tick)

            self._anchor_cancel = ctx.timestamp_generator.once_first_time(_anchor)
            return
        now = ctx.timestamp_generator.current_time()
        self.next_ts = now + self.interval_ms
        self.scheduler.notify_at(self.next_ts, self._tick)

    def _tick(self, ts: int):
        if self.cancelled:
            return
        self.callback(ts)
        if not self.cancelled:
            self.next_ts = ts + self.interval_ms
            self.scheduler.notify_at(self.next_ts, self._tick)
