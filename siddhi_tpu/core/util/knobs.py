"""Typed parser registry for every ``siddhi_tpu.*`` config knob.

The PR-9 regression class this kills: knob reads used to ride a generic
``int(v)`` loop in ``app_runtime`` plus per-key ad-hoc parsers, so
``siddhi_tpu.join_partition_grow: 'false'`` crashed with a bare
``ValueError`` and a typo'd enum value silently fell through. Every
engine-consulted key is now declared here once — name, type, accepted
spellings, target ``SiddhiAppContext`` attribute — and EVERY read
resolves through this module (graftlint R2 flags any
``get_property("siddhi_tpu.…")`` elsewhere). A junk value raises
``SiddhiAppValidationException`` naming the key and the accepted
spellings.

Env spellings of process defaults (``SIDDHI_TPU_PIPELINE_DEPTH``) get
the same treatment via :func:`env_knob`.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from siddhi_tpu.compiler.errors import SiddhiAppValidationException

PREFIX = "siddhi_tpu."

_TRUE = ("1", "true", "on", "yes")
_FALSE = ("0", "false", "off", "no")

# single source of truth for the overload shed policies — the engine
# (resilience/overload.py OverloadConfig) validates against THIS tuple,
# so a policy added there cannot drift apart from the config parser
SHED_POLICIES = ("block", "shed_oldest", "shed_newest")


@dataclass(frozen=True)
class Knob:
    """One declared config knob (key is the bare name after the
    ``siddhi_tpu.`` prefix)."""

    key: str
    kind: str                       # int | float | bool | enum
    choices: Tuple[str, ...] = ()   # enum spellings
    attr: Optional[str] = None      # SiddhiAppContext attribute to set
    per_stream: bool = False        # accepts a `.{stream}` suffix

    def parse(self, raw):
        s = str(raw).strip()
        if self.kind == "int":
            try:
                return int(s)
            except ValueError:
                raise SiddhiAppValidationException(
                    f"{PREFIX}{self.key} must be an integer, got "
                    f"'{raw}'") from None
        if self.kind == "float":
            try:
                return float(s)
            except ValueError:
                raise SiddhiAppValidationException(
                    f"{PREFIX}{self.key} must be a number, got "
                    f"'{raw}'") from None
        if self.kind == "bool":
            low = s.lower()
            if low in _TRUE:
                return True
            if low in _FALSE:
                return False
            raise SiddhiAppValidationException(
                f"{PREFIX}{self.key} must be a boolean "
                f"({'/'.join(_TRUE + _FALSE)}), got '{raw}'")
        if self.kind == "enum":
            low = s.lower()
            if low in self.choices:
                return low
            raise SiddhiAppValidationException(
                f"{PREFIX}{self.key} must be one of "
                f"{'/'.join(repr(c) for c in self.choices)}, got '{raw}'")
        raise AssertionError(f"unknown knob kind {self.kind!r}")


def _declare(*knobs: Knob) -> Dict[str, Knob]:
    return {k.key: k for k in knobs}


# The registry. `attr` set => apply_app_knobs assigns the parsed value
# onto the SiddhiAppContext; attr None => the subsystem reads it via
# read_knob at its own wiring point (overload registration, shims).
KNOBS: Dict[str, Knob] = _declare(
    # capacity knobs (the original generic-int()-loop set)
    Knob("window_capacity", "int", attr="window_capacity"),
    Knob("partition_window_capacity", "int",
         attr="partition_window_capacity"),
    Knob("nfa_slots", "int", attr="nfa_slots"),
    Knob("initial_key_capacity", "int", attr="initial_key_capacity"),
    Knob("defer_meta", "int", attr="defer_meta"),
    Knob("pipeline_depth", "int", attr="pipeline_depth"),
    Knob("agg_shards", "int", attr="agg_shards"),
    Knob("agg_shard_wal", "int", attr="agg_shard_wal"),
    Knob("join_partitions", "int", attr="join_partitions"),
    Knob("join_partition_slack", "int", attr="join_partition_slack"),
    Knob("index_probe_width", "int", attr="index_probe_width"),
    # multicore ingest front door (core/stream/input/pack_pool.py):
    # ingest_pool = pack-pool worker count (0 = today's inline
    # single-thread pack, bit-identical); ingest_split = rows per
    # sequence-numbered sub-batch task — batches smaller than two
    # sub-batches stay inline. See MIGRATION.md round-10 notes.
    Knob("ingest_pool", "int", attr="ingest_pool"),
    Knob("ingest_split", "int", attr="ingest_split"),
    # booleans (each previously had its own — or no — spelling parser)
    Knob("join_partition_grow", "bool", attr="join_partition_grow"),
    Knob("fuse_fanout", "bool", attr="fuse_fanout"),
    # critical-path profiler (observability/journey.py, costmodel.py):
    # both flip PROCESS-wide collectors (refcounted per app runtime) —
    # journeys trace every batch's stage times, costs capture each
    # program's XLA cost/memory analysis at first compile (one extra
    # AOT compile per program). Defaults off; see MIGRATION.md.
    Knob("profile_journeys", "bool", attr="profile_journeys"),
    Knob("profile_costs", "bool", attr="profile_costs"),
    # process-global compiled-program cache (core/util/program_cache.py):
    # identical step programs (jaxpr text + embedded consts + output
    # tree + backend/sharding witness) compile once and share the
    # executable across tenant apps; per-app state pytrees stay private.
    # program_cache gates participation per app (default on; off =
    # every wrapper compiles privately, pre-round-15 behavior);
    # program_cache_max caps live cache entries (zero-ref entries evict
    # LRU-first at the cap; a cache full of live programs compiles
    # privately without caching). Env process defaults:
    # SIDDHI_TPU_PROGRAM_CACHE / SIDDHI_TPU_PROGRAM_CACHE_MAX.
    Knob("program_cache", "bool", attr="program_cache"),
    Knob("program_cache_max", "int", attr="program_cache_max"),
    # device telemetry plane (observability/instruments.py): instrument
    # slots ride the meta vector behind [overflow, notify, count] —
    # per-batch device truth (ring fill, join partition fill, NFA runs,
    # routed-row skew) at zero extra host transfers. Default ON; off =
    # pre-round-9 meta layouts bit-for-bit. See MIGRATION.md.
    Knob("profile_device_instruments", "bool",
         attr="profile_device_instruments"),
    # closed-loop controller (siddhi_tpu/autopilot/): observes the
    # critical-path report + telemetry gauges and actuates the live
    # knobs (pipeline depth, ingest pool size, join Wp, routed shard
    # count, admission caps, fan-out fusion). 'off' (default) keeps the
    # engine bit-identical; 'dry_run' decides and logs but never
    # actuates; 'on' actuates within per-knob bounds. See MIGRATION.md
    # round-12 notes.
    Knob("autopilot", "enum", choices=("off", "on", "dry_run"),
         attr="autopilot"),
    Knob("autopilot_interval_s", "float", attr="autopilot_interval_s"),
    Knob("autopilot_cooldown_s", "float", attr="autopilot_cooldown_s"),
    # autopilot reshard target bound: routed queries may be re-installed
    # up to this many shards (0 = all addressable devices)
    Knob("route_shards", "int", attr="route_shards"),
    # floats
    Knob("cluster_step_timeout", "float", attr="cluster_step_timeout"),
    # enums
    Knob("shard_exchange", "enum", choices=("all_to_all", "pallas_ring"),
         attr="shard_exchange"),
    Knob("join_engine", "enum", choices=("device", "legacy"),
         attr="join_engine"),
    # overload armor (resilience/overload.py) — applied by
    # app_runtime._overload_from_config, not as context attrs
    Knob("quota_queue_depth", "int", per_stream=True),
    Knob("shed_policy", "enum", choices=SHED_POLICIES, per_stream=True),
    Knob("quota_pipeline_depth", "int"),
    Knob("quota_memory_mb", "float"),
    Knob("quota_block_timeout_s", "float"),
    Knob("fair_weight", "float"),
    Knob("quota_query_cap", "int"),
    # cluster fabric (cluster/router.py): worker count, router-side WAL
    # bound per worker, link heartbeat period, auto-checkpoint period
    Knob("cluster_workers", "int"),
    Knob("cluster_wal_batches", "int"),
    Knob("cluster_heartbeat_s", "float"),
    Knob("cluster_checkpoint_s", "float"),
)


def read_knob(config_manager, key: str, stream: Optional[str] = None):
    """Read + type one declared knob from a ConfigManager. Returns None
    when unset. The ONE sanctioned ``get_property(\"siddhi_tpu.*\")``
    call site in the tree (graftlint R2)."""
    knob = KNOBS.get(key)
    if knob is None:
        raise KeyError(f"undeclared config knob '{key}' — add it to "
                       f"core/util/knobs.py KNOBS")
    if stream is not None and not knob.per_stream:
        raise KeyError(f"{PREFIX}{key} does not take a per-stream suffix")
    if config_manager is None:
        return None
    full = f"{PREFIX}{key}" + (f".{stream}" if stream is not None else "")
    raw = config_manager.get_property(full)
    if raw is None:
        return None
    try:
        return knob.parse(raw)
    except SiddhiAppValidationException as e:
        if stream is not None:
            # name the FULL per-stream key in the error
            raise SiddhiAppValidationException(
                str(e).replace(f"{PREFIX}{key}", full)) from None
        raise


def apply_app_knobs(config_manager, app_context) -> Dict[str, object]:
    """Apply every context-attribute knob present in the deployment
    config onto ``app_context``; returns ``{key: parsed}`` for the keys
    that were EXPLICITLY set (the defer_meta deprecation shim needs to
    know whether pipeline_depth was the user's own choice)."""
    explicit: Dict[str, object] = {}
    if config_manager is None:
        return explicit
    for key, knob in KNOBS.items():
        if knob.attr is None:
            continue
        val = read_knob(config_manager, key)
        if val is not None:
            setattr(app_context, knob.attr, val)
            explicit[key] = val
    return explicit


def env_knob(name: str, kind: str, default):
    """Typed read of a ``SIDDHI_TPU_*`` process-default env var; junk
    spellings raise naming the variable (same discipline as config
    keys)."""
    raw = os.environ.get(name)
    if raw is None or not str(raw).strip():
        return default
    knob = Knob(name, kind)
    try:
        return knob.parse(raw)
    except SiddhiAppValidationException:
        raise SiddhiAppValidationException(
            f"environment variable {name} must be {kind}, got "
            f"'{raw}'") from None
