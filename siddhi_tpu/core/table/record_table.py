"""External-store table SPI + caching front.

Mirror of reference ``table/record/AbstractRecordTable.java`` (the SPI the
RDBMS/Mongo/etc. table extensions implement) and ``table/CacheTable*.java``
(FIFO/LRU/LFU caches fronting a slow store). TPU-first inversion: the
engine pulls the store's rows into a columnar probe surface and evaluates
compiled conditions as masked broadcast compares — the external store only
needs add/read/delete/update, not a condition language.

Register implementations with ``SiddhiManager.set_extension('store:<type>',
cls)`` and attach with ``@store(type='<type>', ...)`` on a table
definition; add ``@cache(size='N', cache.policy='FIFO|LRU|LFU')`` inside
@store for a bounded read cache.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core.event import HostBatch
from siddhi_tpu.ops.expressions import TS_KEY, TYPE_KEY, VALID_KEY
from siddhi_tpu.query_api.definitions import AttrType, TableDefinition


class RecordTable:
    """External store SPI (reference AbstractRecordTable). Rows are plain
    lists in attribute order; string attributes arrive as Python strings."""

    def init(self, definition: TableDefinition, options: Dict[str, str]) -> None:
        self.definition = definition
        self.options = options

    def connect(self) -> None:
        pass

    def add(self, records: List[list]) -> None:
        raise NotImplementedError

    def read(self) -> List[list]:
        """Full scan: the engine filters/joins columnar-side."""
        raise NotImplementedError

    def delete(self, indices: List[int]) -> None:
        """Delete rows by their position in the last read()."""
        raise NotImplementedError

    def update(self, indices: List[int], rows: List[list]) -> None:
        raise NotImplementedError

    def disconnect(self) -> None:
        pass


class InMemoryRecordTable(RecordTable):
    """Reference implementation of the SPI (and the test double)."""

    def init(self, definition, options):
        super().init(definition, options)
        self.rows: List[list] = []

    def add(self, records):
        self.rows.extend([list(r) for r in records])

    def read(self):
        return [list(r) for r in self.rows]

    def delete(self, indices):
        for i in sorted(indices, reverse=True):
            del self.rows[i]

    def update(self, indices, rows):
        for i, r in zip(indices, rows):
            self.rows[i] = list(r)


class RowCache:
    """Bounded row cache with FIFO / LRU / LFU eviction (reference
    CacheTableFIFO / CacheTableLRU / CacheTableLFU) and optional
    retention-period expiry (reference ``util/cache/CacheExpirer.java``:
    rows carry a timestamp-added; a periodic sweep deletes rows older
    than ``retention.period``). ``now_fn`` is the app's event-aware clock
    (the reference expirer also reads TimestampGenerator.currentTime)."""

    def __init__(self, max_size: int, policy: str = "FIFO",
                 retention_ms: Optional[int] = None):
        policy = policy.upper()
        if policy not in ("FIFO", "LRU", "LFU"):
            raise ValueError(f"unknown cache policy '{policy}'")
        self.max_size = max_size
        self.policy = policy
        self.retention_ms = retention_ms
        self.purge_interval_ms = None  # sweep cadence (set by create_table)
        self.now_fn = None            # wired to the app clock at build
        self._rows: Dict[object, list] = {}
        self._order: List[object] = []        # FIFO/LRU order
        self._freq: Dict[object, int] = {}    # LFU
        self._added: Dict[object, int] = {}   # CACHE_TABLE_TIMESTAMP_ADDED

    def _now(self) -> int:
        if self.now_fn is not None:
            return int(self.now_fn())
        import time

        return int(time.time() * 1000)

    def __contains__(self, key):
        return key in self._rows

    def __len__(self):
        return len(self._rows)

    def get(self, key) -> Optional[list]:
        row = self._rows.get(key)
        if row is None:
            return None
        if (self.retention_ms is not None
                and self._now() - self._added.get(key, 0) > self.retention_ms):
            # expired-but-not-yet-swept rows must not serve stale data
            self.drop(key)
            return None
        if self.policy == "LRU":
            self._order.remove(key)
            self._order.append(key)
        elif self.policy == "LFU":
            self._freq[key] = self._freq.get(key, 0) + 1
        return row

    def put(self, key, row: list):
        if key in self._rows:
            self._rows[key] = row
            self._added[key] = self._now()
            return
        while len(self._rows) >= self.max_size:
            self._evict_one()
        self._rows[key] = row
        self._order.append(key)
        self._freq[key] = 0
        self._added[key] = self._now()

    def expire(self, now_ms: Optional[int] = None) -> int:
        """Drop every row older than the retention period; returns the
        count dropped (the CacheExpirer sweep body)."""
        if self.retention_ms is None:
            return 0
        now = int(now_ms) if now_ms is not None else self._now()
        victims = [k for k, t in self._added.items()
                   if now - t > self.retention_ms]
        for k in victims:
            self.drop(k)
        return len(victims)

    def _evict_one(self):
        if self.policy in ("FIFO", "LRU"):
            victim = self._order.pop(0)
        else:  # LFU
            victim = min(self._order, key=lambda k: self._freq.get(k, 0))
            self._order.remove(victim)
        self._rows.pop(victim, None)
        self._freq.pop(victim, None)

    def drop(self, key):
        if key in self._rows:
            self._rows.pop(key)
            self._order.remove(key)
            self._freq.pop(key, None)
            self._added.pop(key, None)

    def keys(self):
        return list(self._order)


class RecordTableAdapter:
    """Engine-facing adapter: same duck-typed surface as InMemoryTable
    (contents/insert/delete/update/all_events) over a RecordTable SPI
    implementation, with an optional primary-key row cache."""

    def __init__(self, record_table: RecordTable, definition: TableDefinition,
                 dictionary, cache: Optional[RowCache] = None,
                 primary_key: Optional[List[str]] = None):
        self.record = record_table
        self.definition = definition
        self.dictionary = dictionary
        self.cache = cache
        self.primary_key = primary_key or []
        self._lock = threading.RLock()
        from siddhi_tpu.ops.windows import window_col_specs

        self.col_specs = window_col_specs(definition)

    # ------------------------------------------------------------ row codec

    def _encode_rows(self, rows: List[list]) -> Tuple[dict, np.ndarray]:
        from siddhi_tpu.ops.types import dtype_of

        n = len(rows)
        cap = max(n, 1)
        cols = {TS_KEY: np.zeros(cap, np.int64),
                TYPE_KEY: np.zeros(cap, np.int8),
                VALID_KEY: np.zeros(cap, bool)}
        cols[VALID_KEY][:n] = True
        for pos, attr in enumerate(self.definition.attributes):
            arr = np.zeros(cap, dtype_of(attr.type))
            mask = np.zeros(cap, bool)
            for i, r in enumerate(rows):
                v = r[pos]
                if v is None:
                    mask[i] = True
                elif attr.type == AttrType.STRING:
                    arr[i] = self.dictionary.encode(v)
                else:
                    arr[i] = v
            cols[attr.name] = arr
            cols[attr.name + "?"] = mask
        return cols, cols[VALID_KEY]

    def _decode_batch(self, batch: HostBatch) -> List[list]:
        events = batch.to_events(
            [(a.name, a.type) for a in self.definition.attributes],
            self.dictionary)
        return [list(e.data) for e in events]

    def _pk_of(self, row: list):
        idx = [i for i, a in enumerate(self.definition.attributes)
               if a.name in self.primary_key]
        return tuple(row[i] for i in idx)

    # -------------------------------------------------------------- surface

    def contents(self):
        with self._lock:
            cols, valid = self._encode_rows(self.record.read())
            return cols, valid

    @property
    def count(self) -> int:
        return len(self.record.read())

    def insert(self, batch: HostBatch):
        with self._lock:
            rows = self._decode_batch(batch)
            self.record.add(rows)
            if self.cache is not None and self.primary_key:
                for r in rows:
                    self.cache.put(self._pk_of(r), r)

    def find_by_pk(self, key: tuple) -> Optional[list]:
        """Cache-first primary-key lookup (reference CacheTable read path:
        hit serves from memory, miss loads from the store)."""
        with self._lock:
            if self.cache is not None:
                row = self.cache.get(tuple(key))
                if row is not None:
                    return row
            for r in self.record.read():
                if self._pk_of(r) == tuple(key):
                    if self.cache is not None:
                        self.cache.put(tuple(key), r)
                    return r
            return None

    def _matching_indices(self, cond, batch: Optional[HostBatch]):
        import jax.numpy as jnp

        cols, valid = self.contents()
        ev = {}
        B = 1
        from siddhi_tpu.core.table.in_memory_table import EV_PREFIX, TBL_PREFIX

        if batch is not None:
            B = batch.cols[VALID_KEY].shape[0]
            for k, v in batch.cols.items():
                ev[EV_PREFIX + k] = jnp.asarray(v)[:, None]
        for k, v in cols.items():
            ev[TBL_PREFIX + k] = jnp.asarray(v)[None, :]
        ev[TS_KEY] = ev.get(EV_PREFIX + TS_KEY,
                            jnp.zeros((B, 1), jnp.int64))
        C = valid.shape[0]
        m = cond(ev, {"xp": jnp}) if cond is not None else jnp.ones((B, C), bool)
        m = jnp.broadcast_to(m, (B, C)) & jnp.asarray(valid)[None, :]
        if batch is not None:
            m = m & jnp.asarray(batch.cols[VALID_KEY], bool)[:, None]
        return np.nonzero(np.asarray(jnp.any(m, axis=0)))[0].tolist()

    def delete(self, cond, batch: Optional[HostBatch]):
        with self._lock:
            idx = self._matching_indices(cond, batch)
            if self.cache is not None:
                rows = self.record.read()
                for i in idx:
                    self.cache.drop(self._pk_of(rows[i]))
            self.record.delete(idx)

    def update(self, cond, assignments, batch: Optional[HostBatch]):
        """Row-at-a-time SPI update: matching rows re-read, assignment
        expressions evaluated per row, written back through the SPI."""
        import jax.numpy as jnp

        from siddhi_tpu.core.table.in_memory_table import EV_PREFIX, TBL_PREFIX

        with self._lock:
            idx = self._matching_indices(cond, batch)
            if not idx:
                return jnp.zeros((1, 1), bool)
            rows = self.record.read()
            cols, _valid = self._encode_rows(rows)
            ctx = {"xp": np}
            ev = {TBL_PREFIX + k: v for k, v in cols.items()}
            if batch is not None:
                # last event wins (chunk order) — evaluate with that event
                last = int(np.nonzero(np.asarray(batch.cols[VALID_KEY]))[0][-1])
                for k, v in batch.cols.items():
                    ev[EV_PREFIX + k] = np.asarray(v)[last: last + 1]
            ev[TS_KEY] = ev.get(EV_PREFIX + TS_KEY, np.zeros(1, np.int64))
            name_pos = {a.name: i for i, a in enumerate(self.definition.attributes)}
            new_rows = []
            for i in idx:
                row = list(rows[i])
                for col_name, fn, _t in assignments:
                    v, mk = fn(ev, ctx)
                    val = np.broadcast_to(np.asarray(v), cols[TS_KEY].shape)[i] \
                        if np.asarray(v).ndim else np.asarray(v)
                    attr = self.definition.attributes[name_pos[col_name]]
                    if attr.type == AttrType.STRING:
                        val = self.dictionary.decode(int(val))
                    elif attr.type in (AttrType.INT, AttrType.LONG):
                        val = int(val)
                    else:
                        val = val.item() if hasattr(val, "item") else val
                    row[name_pos[col_name]] = val
                new_rows.append(row)
                if self.cache is not None:
                    self.cache.drop(self._pk_of(rows[i]))
            self.record.update(idx, new_rows)
            return jnp.ones((1, 1), bool)

    def all_events(self):
        cols, valid = self.contents()
        cols[VALID_KEY] = valid
        cols[TYPE_KEY] = np.zeros(valid.shape[0], np.int8)
        return HostBatch(cols).to_events(
            [(a.name, a.type) for a in self.definition.attributes],
            self.dictionary)


def create_table(definition: TableDefinition, dictionary, extensions: Dict[str, type]):
    """Table factory: @store(type=...) resolves a RecordTable extension
    (with optional @cache); otherwise the dense in-memory table."""
    from siddhi_tpu.core.table.in_memory_table import InMemoryTable
    from siddhi_tpu.ops.expressions import resolve_in
    from siddhi_tpu.query_api.annotations import find_annotation

    store_ann = find_annotation(definition.annotations or [], "store")
    if store_ann is None:
        return InMemoryTable(definition, dictionary)
    opts = {k: v for k, v in store_ann.elements if k is not None}
    type_name = (opts.pop("type", None) or "").lower()
    cls = resolve_in(extensions, "store", type_name)
    if cls is None and type_name in ("inmemory", "memory"):
        cls = InMemoryRecordTable
    if cls is None:
        raise ValueError(f"unknown @store type '{type_name}'")
    record = cls()
    record.init(definition, opts)
    record.connect()

    pk_ann = find_annotation(definition.annotations or [], "primaryKey")
    primary_key = [v for _k, v in pk_ann.elements if v] if pk_ann else []

    cache = None
    cache_ann = store_ann.annotation("cache")
    if cache_ann is not None:
        copts = {k: v for k, v in cache_ann.elements if k is not None}
        size = int(copts.get("size", copts.get("max.size", 128)))
        policy = copts.get("cache.policy", copts.get("policy", "FIFO"))
        retention = copts.get("retention.period")
        retention_ms = None
        purge_interval_ms = None
        if retention is not None:
            from siddhi_tpu.core.aggregation.incremental import _parse_time_str

            # reference AbstractQueryableRecordTable.java:156-163: a
            # retention period implies expiry; purge.interval defaults to
            # the retention period itself when absent
            retention_ms = _parse_time_str(retention)
            purge_interval_ms = _parse_time_str(
                copts.get("purge.interval", retention))
        cache = RowCache(size, policy, retention_ms=retention_ms)
        cache.purge_interval_ms = purge_interval_ms
    return RecordTableAdapter(record, definition, dictionary, cache=cache,
                              primary_key=primary_key)
