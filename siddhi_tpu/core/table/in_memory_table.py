"""In-memory table: a device-resident columnar store.

Replaces the reference's ``InMemoryTable`` + ``IndexEventHolder`` (hash
primary-key map, per-attribute TreeMap indexes, compiled
``CollectionExecutor`` scans — ``table/holder/IndexEventHolder.java:60-80``,
``util/collection/executor/*.java``) with one dense ``[C]`` column set and
an occupancy mask: every lookup/update/delete evaluates its compiled
condition as a masked ``[B, C]`` broadcast compare — the vectorized
equivalent of an index probe, with no pointer-chasing. Capacity doubles by
prefix copy when full.

``@primaryKey`` adds uniqueness plus a host hash probe; ``@index`` adds
sub-linear equality probes: host value->slots hash maps for on-demand
queries (``index_candidates``) and a device sorted-column searchsorted
path for joins (``join_runtime`` — bounded [N, G] candidate windows
replace the [N, C] broadcast compare).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.event import CURRENT, Event, HostBatch, StringDictionary
from siddhi_tpu.ops.expressions import TS_KEY, TYPE_KEY, VALID_KEY, ColumnRef, CompileError, Resolver
from siddhi_tpu.query_api.definitions import AttrType, TableDefinition
from siddhi_tpu.query_api.expressions import Variable

TBL_PREFIX = "t__"
EV_PREFIX = "s__"


class TableConditionResolver(Resolver):
    """Resolve an `on` condition over (table row, triggering event).
    Unqualified names bind to the triggering event first (the reference
    test idiom is ``on StockTable.symbol == symbol`` — table side
    qualified, event side bare), then to the table (on-demand queries have
    no event side)."""

    def __init__(self, table_def, event_def, dictionary,
                 event_ref: Optional[str] = None):
        self.table_def = table_def
        self.event_def = event_def  # may be None (on-demand queries)
        self.dictionary = dictionary
        self.event_ref = event_ref

    def resolve(self, var: Variable) -> ColumnRef:
        sid = var.stream_id
        if sid == self.table_def.id:
            attr = self.table_def.attribute(var.attribute_name)
            return ColumnRef(TBL_PREFIX + attr.name, attr.type)
        if self.event_def is not None and (
            sid is None or sid in (self.event_def.id, self.event_ref)
        ):
            try:
                attr = self.event_def.attribute(var.attribute_name)
                return ColumnRef(EV_PREFIX + attr.name, attr.type)
            except Exception:
                if sid is not None:
                    raise
        if sid is None:
            attr = self.table_def.attribute(var.attribute_name)
            return ColumnRef(TBL_PREFIX + attr.name, attr.type)
        raise CompileError(
            f"cannot resolve '{(sid + '.') if sid else ''}{var.attribute_name}' "
            f"in table condition"
        )

    def encode_string(self, s: str) -> int:
        return self.dictionary.encode(s)


class InMemoryTable:
    def __init__(self, definition: TableDefinition, dictionary: StringDictionary,
                 capacity: int = 1024):
        from siddhi_tpu.ops.windows import window_col_specs
        self.definition = definition
        self.dictionary = dictionary
        self.col_specs = window_col_specs(definition)
        self.capacity = capacity
        self.state = self._zero_state(capacity)
        self._lock = threading.RLock()
        # owning app context, wired by SiddhiAppRuntime after construction
        # (the overload layer's device-memory budget gates _ensure_room)
        self.app_context = None
        # @primaryKey: uniqueness + host hash probe (the dense-array analog
        # of reference IndexEventHolder's primary-key map,
        # table/holder/IndexEventHolder.java:60-80)
        from siddhi_tpu.compiler.errors import SiddhiAppValidationException
        from siddhi_tpu.query_api.annotations import find_annotations

        names = {a.name for a in definition.attributes}
        pk_anns = find_annotations(definition.annotations or [], "primaryKey")
        if len(pk_anns) > 1:
            # reference DuplicateAnnotationException
            # (AnnotationHelper.validateAnnotation)
            raise SiddhiAppValidationException(
                f"table '{definition.id}': duplicate @PrimaryKey annotation")
        pk_ann = pk_anns[0] if pk_anns else None
        self.primary_key: List[str] = []
        if pk_ann is not None:
            self.primary_key = [v for _k, v in pk_ann.elements if v]
            if not self.primary_key:
                raise SiddhiAppValidationException(
                    f"table '{definition.id}': @PrimaryKey needs at least "
                    "one attribute")
            for a in self.primary_key:
                if a not in names:
                    # reference AttributeNotExistException (case-sensitive)
                    raise SiddhiAppValidationException(
                        f"table '{definition.id}': @PrimaryKey attribute "
                        f"'{a}' is not defined in the table")
        self._pk_map: Dict[tuple, int] = {}
        self._pk_dirty = False
        # @index: secondary per-attribute probes (the dense analog of the
        # reference's per-attribute TreeMap indexes,
        # IndexEventHolder.java:60-80). Host side: value -> slots hash maps
        # (on-demand queries); device side: joins sort the probe column
        # once per batch and searchsorted into it (join_runtime).
        self.indexes: List[str] = []
        for ann in find_annotations(definition.annotations or [], "index"):
            vals = [v for _k, v in ann.elements]
            if len(vals) != 1:
                # reference: one attribute per @Index annotation
                # (IndexTableTestCase.java indexTableTest31)
                raise SiddhiAppValidationException(
                    f"table '{definition.id}': @Index supports exactly one "
                    "attribute per annotation")
            a = vals[0]
            if a in self.indexes:
                raise SiddhiAppValidationException(
                    f"table '{definition.id}': duplicate @Index('{a}')")
            if not a or a not in names:
                raise SiddhiAppValidationException(
                    f"table '{definition.id}': @Index attribute '{a}' is "
                    "not defined in the table")
            self.indexes.append(a)
        self._idx_maps: Dict[str, Dict[object, np.ndarray]] = {}
        self._idx_dirty = True
        # incremental-snapshot op log: inserted rows since the last
        # checkpoint; deletes/updates force a full capture. Journaling is
        # off until persistence is in use (PersistenceManager enables it)
        # so non-persisted apps pay no copy or memory cost.
        self.journal_enabled = False
        self._journal: List[dict] = []
        self._journal_full = False

    # ------------------------------------------------------- primary key map

    def _pk_of_host(self, host_cols: dict, i: int) -> tuple:
        return tuple(host_cols[a][i].item() for a in self.primary_key)

    def _rebuild_pk_map(self):
        host = {a: np.asarray(self.state["cols"][a]) for a in self.primary_key}
        valid = np.asarray(self.state["valid"])
        self._pk_map = {
            tuple(host[a][i].item() for a in self.primary_key): int(i)
            for i in np.nonzero(valid)[0]
        }
        self._pk_dirty = False

    def find_by_pk(self, key: tuple) -> Optional[int]:
        """Slot of the row with this primary-key tuple (hash probe — no
        scan). String components must be dictionary-encoded ints."""
        if not self.primary_key:
            return None
        with self._lock:
            if self._pk_dirty:
                self._rebuild_pk_map()
            return self._pk_map.get(tuple(key))

    # --------------------------------------------------- secondary indexes

    def probe_attrs(self) -> List[str]:
        """Attributes with a sub-linear equality probe: @index attrs plus
        a single-attribute @primaryKey."""
        out = list(self.indexes)
        if len(self.primary_key) == 1 and self.primary_key[0] not in out:
            out.append(self.primary_key[0])
        return out

    def _rebuild_idx_maps(self):
        valid = np.asarray(self.state["valid"])
        live = np.nonzero(valid)[0]
        self._idx_maps = {}
        for a in self.probe_attrs():
            # vectorized group-by-value: one stable sort + split (no
            # per-row Python loop even at 10^5+ rows)
            col = np.asarray(self.state["cols"][a])[live]
            nm = np.asarray(self.state["cols"][a + "?"])[live]
            ok = ~nm
            vals, slots = col[ok], live[ok].astype(np.int64)
            order = np.argsort(vals, kind="stable")
            sv, ss = vals[order], slots[order]
            uniq, starts = np.unique(sv, return_index=True)
            parts = np.split(ss, starts[1:])
            self._idx_maps[a] = {k.item(): p for k, p in zip(uniq, parts)}
        self._idx_dirty = False

    def index_candidates(self, attr: str, value) -> Optional[np.ndarray]:
        """Slots whose ``attr`` equals ``value`` (hash probe, no scan).
        None when the attribute has no index; [] when no row matches.
        String values must be dictionary-encoded ints. The value must
        already fit the column dtype — the probe compilers only take this
        path for non-narrowing types (see _probe_type_safe)."""
        if attr not in self.probe_attrs():
            return None
        with self._lock:
            if self._idx_dirty:
                self._rebuild_idx_maps()
            key = self.state["cols"][attr].dtype.type(value).item()
            hits = self._idx_maps.get(attr, {}).get(key)
            return hits if hits is not None else np.empty(0, np.int64)

    def _zero_state(self, cap: int) -> dict:
        return {
            "cols": {n: jnp.zeros((cap,), dt) for n, dt in self.col_specs.items()},
            "valid": jnp.zeros((cap,), bool),
        }

    # ----------------------------------------------------------- capacity

    @property
    def count(self) -> int:
        return int(np.asarray(self.state["valid"]).sum())

    def _row_bytes(self) -> int:
        return sum(np.dtype(dt).itemsize
                   for dt in self.col_specs.values()) + 1   # + valid flag

    def _ensure_room(self, n: int):
        needed = self.count + n
        cap = self.capacity
        if needed <= cap:
            return
        while cap < needed:
            cap *= 2
        ctx = self.app_context
        if ctx is not None and getattr(ctx, "overload", None) is not None:
            # device-memory budget gate (resilience/overload.py): deny the
            # doubled allocation BEFORE it happens
            from siddhi_tpu.resilience.overload import (
                charge_memory,
                ensure_memory_budget,
            )

            comp = f"table.{self.definition.id}"
            ensure_memory_budget(
                ctx, comp, cap * self._row_bytes(),
                what=f"table '{self.definition.id}' capacity growth "
                     f"({self.capacity}->{cap} rows)")
            charge_memory(ctx, comp, cap * self._row_bytes())
        new = self._zero_state(cap)
        new["cols"] = {
            n_: new["cols"][n_].at[: self.capacity].set(self.state["cols"][n_])
            for n_ in new["cols"]
        }
        new["valid"] = new["valid"].at[: self.capacity].set(self.state["valid"])
        self.state = new
        self.capacity = cap

    # ------------------------------------------------------- contents/probe

    def contents(self) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
        with self._lock:
            return dict(self.state["cols"]), self.state["valid"]

    # ------------------------------------------------------------- actions

    def insert(self, batch: HostBatch):
        """Insert the batch's valid rows into free slots (arrival order).
        With @primaryKey, rows duplicating an existing key are dropped
        (reference IndexEventHolder rejects primary-key collisions)."""
        with self._lock:
            n = batch.size
            if n == 0:
                return
            if self.primary_key:
                if self._pk_dirty:
                    self._rebuild_pk_map()
                host = {a: np.asarray(batch.cols[a]) for a in self.primary_key}
                valid_h = np.asarray(batch.cols[VALID_KEY], bool).copy()
                seen = set(self._pk_map)
                for i in np.nonzero(valid_h)[0]:
                    key = self._pk_of_host(host, int(i))
                    if key in seen:
                        valid_h[i] = False       # duplicate: drop
                    else:
                        seen.add(key)
                batch.cols[VALID_KEY] = valid_h
                self._pk_dirty = True
            self._idx_dirty = True
            n = batch.size
            if n == 0:
                return
            self._ensure_room(n)
            cols, valid, st = batch.cols, batch.cols[VALID_KEY], self.state
            C = self.capacity
            free = ~st["valid"]
            fs = jnp.argsort(jnp.where(free, jnp.arange(C), C + jnp.arange(C)))
            rank = jnp.cumsum(np.asarray(valid, bool)) - 1
            slot = jnp.where(valid, fs[jnp.clip(rank, 0, C - 1)], C)
            new_cols = {}
            journal = self.journal_enabled and not self._journal_full
            journal_rows = {}
            vidx = np.nonzero(np.asarray(valid, bool))[0] if journal else None
            for name in st["cols"]:
                src = cols.get(name)
                if src is None:
                    src = np.zeros(valid.shape[0], self.col_specs[name])
                if journal:
                    journal_rows[name] = np.asarray(src)[vidx].copy()
                new_cols[name] = st["cols"][name].at[slot].set(jnp.asarray(src), mode="drop")
            if journal and vidx.size:
                self._journal.append(journal_rows)
            self.state = {
                "cols": new_cols,
                "valid": st["valid"].at[slot].set(True, mode="drop"),
            }

    def _match(self, cond: Optional[Callable], ev_cols: Optional[dict], ctx: dict):
        """[B, C] match matrix of condition over (event, table row)."""
        tcols, tvalid = self.contents()
        ev = {}
        B = 1
        if ev_cols is not None:
            B = ev_cols[VALID_KEY].shape[0]
            for k, v in ev_cols.items():
                ev[EV_PREFIX + k] = jnp.asarray(v)[:, None]
        for k, v in tcols.items():
            ev[TBL_PREFIX + k] = v[None, :]
        ev[TS_KEY] = ev.get(EV_PREFIX + TS_KEY, jnp.zeros((B, 1), jnp.int64))
        C = tvalid.shape[0]
        m = cond(ev, ctx) if cond is not None else jnp.ones((B, C), bool)
        m = jnp.broadcast_to(m, (B, C)) & tvalid[None, :]
        if ev_cols is not None:
            m = m & jnp.asarray(ev_cols[VALID_KEY], bool)[:, None]
        return m

    def delete(self, cond: Optional[Callable], batch: Optional[HostBatch]):
        with self._lock:
            ctx = {"xp": jnp}
            m = self._match(cond, batch.cols if batch is not None else None, ctx)
            self.state = {
                "cols": self.state["cols"],
                "valid": self.state["valid"] & ~jnp.any(m, axis=0),
            }
            self._pk_dirty = True
            self._idx_dirty = True
            self._journal_full = True

    def update(self, cond: Optional[Callable], assignments, batch: Optional[HostBatch]):
        """assignments: [(table col name, compiled expr over ev/table cols)].
        When several events match one row, the last event wins (reference
        processes the chunk in order)."""
        with self._lock:
            ctx = {"xp": jnp}
            ev_cols = batch.cols if batch is not None else None
            m = self._match(cond, ev_cols, ctx)
            B, C = m.shape
            ev = {}
            if ev_cols is not None:
                for k, v in ev_cols.items():
                    ev[EV_PREFIX + k] = jnp.asarray(v)[:, None]
            for k, v in self.state["cols"].items():
                ev[TBL_PREFIX + k] = v[None, :]
            mats = []     # (col_name, values [B,C], mask [B,C] or None)
            for col_name, fn, _t in assignments:
                v, mask = fn(ev, ctx)
                mats.append((col_name,
                             jnp.broadcast_to(jnp.asarray(v), (B, C)),
                             None if mask is None else
                             jnp.broadcast_to(jnp.asarray(mask), (B, C))))

            pk_touched = self.primary_key and any(
                col in self.primary_key for col, _f, _t in assignments)
            if not pk_touched:
                # winning (last matching) event per table row; B when none
                ridx = jnp.arange(B, dtype=jnp.int32)
                win = jnp.max(jnp.where(m, ridx[:, None] + 1, 0), axis=0) - 1
                hit = win >= 0
            else:
                # primary-key assignments follow the reference's SEQUENTIAL
                # chunk walk: events apply in order. For a SINGLE-key PK
                # the reference first SIMULATES the whole event's key
                # rewrites against a snapshot of the current key set — any
                # collision drops the ENTIRE updating event (all its
                # matched rows, non-PK columns included):
                # IndexOperator.java:117-161 (`keys.remove(old);
                # if (!keys.add(new)) fail`). Composite keys keep per-row
                # drops (the reference skips the simulation there).
                live = np.asarray(self.state["valid"], bool)
                m_h = np.asarray(m, bool) & live[None, :]
                pk_vals = {col: np.asarray(v)
                           for col, v, _mk in mats if col in self.primary_key}
                if self._pk_dirty:
                    self._rebuild_pk_map()
                keys = dict(self._pk_map)
                old_k = {a: np.asarray(self.state["cols"][a])
                         for a in self.primary_key}
                cur_key = {int(c): self._pk_of_host(old_k, int(c))
                           for c in np.nonzero(live)[0]}
                win2 = np.full(C, -1, np.int64)
                single_pk = len(self.primary_key) == 1
                kset = set(keys) if single_pk else None

                def new_key(b, c):
                    return tuple(
                        pk_vals[a][b, c].item() if a in pk_vals
                        else cur_key[c][i]
                        for i, a in enumerate(self.primary_key))

                for b in range(B):
                    rows = [int(c) for c in np.nonzero(m_h[b])[0]]
                    if not rows:
                        continue
                    if single_pk:
                        # simulate against the live key set, logging this
                        # event's moves so a collision can undo them —
                        # O(rows) per event, not O(table)
                        moves = []
                        ok = True
                        for c in rows:
                            nk = new_key(b, c)
                            if nk != cur_key[c]:
                                kset.discard(cur_key[c])
                                if nk in kset:
                                    kset.add(cur_key[c])
                                    ok = False
                                    break
                                kset.add(nk)
                                moves.append((c, cur_key[c], nk))
                        if not ok:
                            for c, old, nk in reversed(moves):
                                kset.discard(nk)
                                kset.add(old)
                            continue       # whole updating event dropped
                        for c, old, nk in moves:
                            del keys[old]
                            keys[nk] = c
                            cur_key[c] = nk
                        for c in rows:
                            win2[c] = b
                    else:
                        for c in rows:
                            nk = new_key(b, c)
                            if nk != cur_key[c] and keys.get(nk, c) != c:
                                continue   # violation: row dropped
                            if nk != cur_key[c]:
                                del keys[cur_key[c]]
                                keys[nk] = c
                                cur_key[c] = nk
                            win2[c] = b
                win = jnp.asarray(win2, jnp.int32)
                hit = win >= 0

            wsafe = jnp.clip(win, 0, B - 1)
            new_cols = dict(self.state["cols"])
            for col_name, v, mask in mats:
                val = v[wsafe, jnp.arange(C)]
                new_cols[col_name] = jnp.where(hit, val, new_cols[col_name])
                if mask is not None:
                    mk = mask[wsafe, jnp.arange(C)]
                else:
                    mk = jnp.zeros(C, bool)
                new_cols[col_name + "?"] = jnp.where(
                    hit, mk, new_cols[col_name + "?"])
            self.state = {"cols": new_cols, "valid": self.state["valid"]}
            self._pk_dirty = True
            self._idx_dirty = True
            self._journal_full = True
            return m

    def update_or_insert(self, cond, assignments, batch: HostBatch,
                         insert_mapping=None):
        """Sequential semantics per event: an inserted row is visible to the
        later events of the same chunk (reference UpdateOrInsertReducer
        processes the chunk in order). The vectorized update handles the
        common all-match case; only unmatched events fall back to
        one-at-a-time processing. ``insert_mapping`` is the positional
        (table attr <- event col) pairing used when an unmatched event is
        inserted (reference inserts by position, like `insert into`)."""
        with self._lock:
            m = self.update(cond, assignments, batch)
            unmatched = ~np.asarray(jnp.any(m, axis=1)) & np.asarray(
                batch.cols[VALID_KEY], bool)
            if not unmatched.any():
                return
            host = {k: np.asarray(v) for k, v in batch.cols.items()}
            for i in np.nonzero(unmatched)[0]:
                row = {k: v[i : i + 1] for k, v in host.items()}
                row[VALID_KEY] = np.ones(1, bool)
                single = HostBatch(row)
                m1 = self.update(cond, assignments, single)
                if not bool(np.asarray(jnp.any(m1))):
                    if insert_mapping is not None:
                        ins = {TS_KEY: row[TS_KEY], TYPE_KEY: row.get(TYPE_KEY, np.zeros(1, np.int8)),
                               VALID_KEY: row[VALID_KEY]}
                        for table_attr, ev_col in insert_mapping:
                            if ev_col is None:
                                # partial upsert output set: absent table
                                # columns insert as NULL
                                dt = self.col_specs[table_attr]
                                ins[table_attr] = np.zeros(1, dt)
                                ins[table_attr + "?"] = np.ones(1, bool)
                                continue
                            ins[table_attr] = row[ev_col]
                            ins[table_attr + "?"] = row.get(ev_col + "?", np.zeros(1, bool))
                        single = HostBatch(ins)
                    self.insert(single)

    # ----------------------------------------------- incremental snapshots

    def incremental_snapshot(self) -> dict:
        """Insert journal since the last checkpoint, or the full state when
        a delete/update invalidated the op log. Pure capture — cleared via
        ``clear_oplog`` only after the checkpoint is durably saved."""
        with self._lock:
            if self._journal_full:
                return {"full": {
                    "cols": {k: np.asarray(v) for k, v in self.state["cols"].items()},
                    "valid": np.asarray(self.state["valid"]),
                }, "capacity": self.capacity}
            return {"journal": list(self._journal)}

    def clear_oplog(self):
        with self._lock:
            self._journal = []
            self._journal_full = False

    def apply_increment(self, snap: dict):
        if "full" in snap:
            with self._lock:
                self.state = {
                    "cols": {k: jnp.asarray(v) for k, v in snap["full"]["cols"].items()},
                    "valid": jnp.asarray(snap["full"]["valid"]),
                }
                self.capacity = snap["capacity"]
                self._pk_dirty = True
            self._idx_dirty = True
            return
        # replay without re-journaling (the restored chain already holds
        # these rows — journaling them would duplicate on the NEXT restore)
        was = self.journal_enabled
        self.journal_enabled = False
        try:
            for rows in snap.get("journal", []):
                n = len(next(iter(rows.values()))) if rows else 0
                if n == 0:
                    continue
                cols = {k: v.copy() for k, v in rows.items()}
                cols[VALID_KEY] = np.ones(n, bool)
                self.insert(HostBatch(cols))
        finally:
            self.journal_enabled = was

    # ------------------------------------------------------------ decoding

    def all_events(self) -> List[Event]:
        cols, valid = self.contents()
        host = {k: np.asarray(v) for k, v in cols.items()}
        host[VALID_KEY] = np.asarray(valid)
        host[TYPE_KEY] = np.zeros(valid.shape[0], np.int8)
        batch = HostBatch(host)
        return batch.to_events(
            [(a.name, a.type) for a in self.definition.attributes], self.dictionary)
