from siddhi_tpu.core.table.in_memory_table import (
    InMemoryTable,
    TableConditionResolver,
)

__all__ = ["InMemoryTable", "TableConditionResolver"]
