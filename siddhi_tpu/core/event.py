"""Event model: user-facing Event rows and columnar batches.

Replaces the reference's pooled row objects and linked-list chunks
(``core/event/Event.java``, ``event/stream/StreamEvent.java:37-57``,
``event/ComplexEventChunk.java:62-232``) with a struct-of-arrays design:
each stream batch is one numpy (host) / jax (device) array per attribute
plus timestamp, event-type and validity columns. The linked-list surgery of
``ComplexEventChunk`` becomes mask updates; the CURRENT/EXPIRED/TIMER/RESET
event types (``ComplexEvent.Type``) become an i8 column.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from siddhi_tpu.ops.expressions import TS_KEY, TYPE_KEY, VALID_KEY
from siddhi_tpu.ops.types import dtype_of
from siddhi_tpu.query_api.definitions import AbstractDefinition, AttrType

# ComplexEvent.Type (reference event/ComplexEvent.java)
CURRENT = 0
EXPIRED = 1
TIMER = 2
RESET = 3

TYPE_NAMES = {CURRENT: "CURRENT", EXPIRED: "EXPIRED", TIMER: "TIMER", RESET: "RESET"}


@dataclass
class Event:
    """User-facing event (reference ``core/event/Event.java``)."""

    timestamp: int = -1
    data: Sequence = field(default_factory=list)
    is_expired: bool = False  # kept for API parity with the reference
    # partition-key id for events flowing through inner '#streams' (the
    # analog of the reference's ThreadLocal partition flow id,
    # SiddhiAppContext.java:55). None outside partitions.
    pk: Optional[int] = None

    def __repr__(self):
        return f"Event{{timestamp={self.timestamp}, data={list(self.data)}, isExpired={self.is_expired}}}"


class StringDictionary:
    """App-global string <-> int32 id dictionary.

    Strings never reach the device: group keys, symbols etc. travel as dense
    ids (the TPU answer to per-event string group-key building in reference
    ``GroupByKeyGenerator.java:37``). The dictionary only grows, so encoded
    ids (including ones baked into compiled constants) stay stable.
    """

    NULL_ID = -1

    def __init__(self):
        self._to_id: Dict[str, int] = {}
        self._to_str: List[str] = []

    def encode(self, s: Optional[str]) -> int:
        if s is None:
            return self.NULL_ID
        i = self._to_id.get(s)
        if i is None:
            i = len(self._to_str)
            self._to_id[s] = i
            self._to_str.append(s)
        return i

    def decode(self, i: int) -> Optional[str]:
        if i < 0:
            return None
        return self._to_str[i]

    def __len__(self):
        return len(self._to_str)


def _pad_len(n: int, minimum: int = 8) -> int:
    """Pad batch length to a power of two to bound jit recompiles."""
    b = minimum
    while b < n:
        b *= 2
    return b


class HostBatch:
    """Columnar batch on host (numpy), convertible to device cols dict.

    Column keys: attribute names (optionally prefixed by the planner), plus
    reserved ``__ts__`` (i64), ``__type__`` (i8), ``__valid__`` (bool) and
    per-attribute null masks under ``<key>?``.
    """

    def __init__(self, cols: Dict[str, np.ndarray]):
        self.cols = cols

    @property
    def size(self) -> int:
        return int(self.cols[VALID_KEY].sum())

    @property
    def capacity(self) -> int:
        return self.cols[VALID_KEY].shape[0]

    @staticmethod
    def from_events(
        events: Sequence[Event],
        definition: AbstractDefinition,
        dictionary: StringDictionary,
        pad_to: Optional[int] = None,
        event_type: int = CURRENT,
    ) -> "HostBatch":
        n = len(events)
        b = pad_to if pad_to is not None else _pad_len(n)
        cols: Dict[str, np.ndarray] = {
            TS_KEY: np.zeros(b, np.int64),
            TYPE_KEY: np.full(b, event_type, np.int8),
            VALID_KEY: np.zeros(b, bool),
        }
        cols[VALID_KEY][:n] = True
        for i, ev in enumerate(events):
            cols[TS_KEY][i] = ev.timestamp
            if ev.is_expired:
                cols[TYPE_KEY][i] = EXPIRED
        for pos, attr in enumerate(definition.attributes):
            dtype = dtype_of(attr.type)
            arr = np.zeros(b, dtype)
            # null masks are always present so device column sets (and jit
            # shapes) stay static whether or not a batch contains nulls
            mask = np.zeros(b, bool)
            for i, ev in enumerate(events):
                v = ev.data[pos]
                if v is None:
                    mask[i] = True
                elif attr.type == AttrType.STRING:
                    arr[i] = dictionary.encode(v)
                else:
                    arr[i] = v
            cols[attr.name] = arr
            cols[attr.name + "?"] = mask
        return HostBatch(cols)

    def to_events(
        self,
        attr_order: Sequence[tuple],  # [(key, AttrType), ...]
        dictionary: StringDictionary,
        types_wanted: Optional[Sequence[int]] = None,
        pk_key: Optional[str] = None,
    ) -> List[Event]:
        """Decode valid rows into Events (optionally filtered by type).
        ``pk_key`` names a partition-id column to attach as Event.pk."""
        valid = self.cols[VALID_KEY]
        types = self.cols[TYPE_KEY]
        ts = self.cols[TS_KEY]
        pk_col = self.cols.get(pk_key) if pk_key is not None else None
        out: List[Event] = []
        idx = np.nonzero(valid)[0]
        for i in idx:
            t = int(types[i])
            if types_wanted is not None and t not in types_wanted:
                continue
            data = []
            for key, attr_type in attr_order:
                mask = self.cols.get(key + "?")
                if mask is not None and mask[i]:
                    data.append(None)
                    continue
                v = self.cols[key][i]
                if attr_type == AttrType.STRING:
                    data.append(dictionary.decode(int(v)))
                elif attr_type == AttrType.BOOL:
                    data.append(bool(v))
                elif attr_type in (AttrType.INT, AttrType.LONG):
                    data.append(int(v))
                else:
                    data.append(float(v))
            ev = Event(timestamp=int(ts[i]), data=data, is_expired=(t == EXPIRED))
            if pk_col is not None:
                ev.pk = int(pk_col[i])
            out.append(ev)
        return out
