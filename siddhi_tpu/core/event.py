"""Event model: user-facing Event rows and columnar batches.

Replaces the reference's pooled row objects and linked-list chunks
(``core/event/Event.java``, ``event/stream/StreamEvent.java:37-57``,
``event/ComplexEventChunk.java:62-232``) with a struct-of-arrays design:
each stream batch is one numpy (host) / jax (device) array per attribute
plus timestamp, event-type and validity columns. The linked-list surgery of
``ComplexEventChunk`` becomes mask updates; the CURRENT/EXPIRED/TIMER/RESET
event types (``ComplexEvent.Type``) become an i8 column.
"""

from __future__ import annotations

import ctypes
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from siddhi_tpu.observability import journey
from siddhi_tpu.ops.expressions import TS_KEY, TYPE_KEY, VALID_KEY
from siddhi_tpu.ops.types import dtype_of
from siddhi_tpu.query_api.definitions import AbstractDefinition, AttrType

# ComplexEvent.Type (reference event/ComplexEvent.java)
CURRENT = 0
EXPIRED = 1
TIMER = 2
RESET = 3

TYPE_NAMES = {CURRENT: "CURRENT", EXPIRED: "EXPIRED", TIMER: "TIMER", RESET: "RESET"}


@dataclass
class Event:
    """User-facing event (reference ``core/event/Event.java``)."""

    timestamp: int = -1
    data: Sequence = field(default_factory=list)
    is_expired: bool = False  # kept for API parity with the reference
    # partition-key id for events flowing through inner '#streams' (the
    # analog of the reference's ThreadLocal partition flow id,
    # SiddhiAppContext.java:55). None outside partitions.
    pk: Optional[int] = None
    # dense group-key id (GroupedComplexEvent.getGroupKey analog) — attached
    # only when a grouped rate limiter needs a key that isn't projected
    gk: Optional[int] = None

    def __repr__(self):
        return f"Event{{timestamp={self.timestamp}, data={list(self.data)}, isExpired={self.is_expired}}}"


class StringDictionary:
    """App-global string <-> int32 id dictionary.

    Strings never reach the device: group keys, symbols etc. travel as dense
    ids (the TPU answer to per-event string group-key building in reference
    ``GroupByKeyGenerator.java:37``). The dictionary only grows, so encoded
    ids (including ones baked into compiled constants) stay stable.
    """

    NULL_ID = -1

    def __init__(self):
        self._to_id: Dict[str, int] = {}
        self._to_str: List[str] = []
        # insert guard: id assignment is check-then-append, and the wire
        # front door (ThreadingHTTPServer threads in decode_frame) plus
        # multiple @Async producers can insert concurrently — without
        # this, the same NEW string can win two different ids and split
        # one group key in two. Reads stay lock-free (GIL-atomic dict
        # probe); only the rare miss pays the lock.
        self._insert_lock = threading.Lock()
        # native accelerator (strdict.cpp): a C++ mirror of _to_id probed
        # once per string by encode_array. Python stays authoritative for
        # the id space — the mirror only ever holds (string, id) pairs
        # that already exist in _to_id. Lazily created on first bulk
        # encode; None when the native lib is unavailable.
        self._native = None
        self._native_lib = None

    def encode(self, s: Optional[str]) -> int:
        if s is None:
            return self.NULL_ID
        i = self._to_id.get(s)
        if i is None:
            with self._insert_lock:
                i = self._to_id.get(s)     # double-check under the lock
                if i is None:
                    i = len(self._to_str)
                    self._to_str.append(s)
                    if self._native is not None:
                        self._mirror_insert(s, i)
                    # publish the id LAST: a lock-free reader that sees
                    # the dict entry must find _to_str[i] present
                    self._to_id[s] = i
        return i

    def _mirror_insert(self, s: str, i: int):
        try:
            b = s.encode("utf-8")
        except UnicodeEncodeError:
            # lone surrogates (surrogateescape-decoded transport bytes)
            # can't round-trip utf-8; they stay on the Python slow path
            # (strdict_encode marks them misses anyway)
            return
        self._native_lib.strdict_insert(self._native, b, len(b), i)

    def restore_strings(self, strings: List[str]):
        """Replace the id space wholesale (snapshot restore) — rebuilds the
        native mirror, which would otherwise serve ids from the discarded
        space."""
        with self._insert_lock:
            self._to_str = list(strings)
            self._to_id = {s: i for i, s in enumerate(strings)}
            if self._native is not None:
                self._native_lib.strdict_clear(self._native)
                for i, s in enumerate(strings):
                    self._mirror_insert(s, i)

    def __del__(self):
        try:
            if self._native is not None:
                self._native_lib.strdict_free(self._native)
        except Exception:
            pass

    def decode(self, i: int) -> Optional[str]:
        if i < 0:
            return None
        return self._to_str[i]

    def rank_table(self, min_capacity: int = 16) -> np.ndarray:
        """Lexicographic rank per id, padded to a pow2 capacity so growth
        rarely changes the array SHAPE (ids are assigned in arrival order,
        so `order by` on a string column must sort by rank, not id —
        OrderByLimitTestCase limitTest2). Cached per dictionary size."""
        n = len(self._to_str)
        cap = max(min_capacity, 16)
        while cap < n + 1:   # keep >= one pad slot: id -1 wraps to table[-1]
            cap *= 2
        cached = getattr(self, "_rank_cache", None)
        if cached is not None and cached[0] == n and len(cached[1]) == cap:
            return cached[1]
        # padding (including the wrapped null id -1) ranks AFTER every
        # real string, so nulls sort last
        table = np.full(cap, n, np.int32)
        if n:
            order = sorted(range(n), key=lambda i: self._to_str[i])
            for r, i in enumerate(order):
                table[i] = r
        self._rank_cache = (n, table)
        return table

    _MISS = -2

    def _ensure_native(self):
        """Lazy native-mirror build, guarded so concurrent first probes
        (ingest pack-pool workers) build it exactly once."""
        if self._native is not None or self._native_lib is not None:
            return
        with _NATIVE_INIT_LOCK:
            if self._native is not None or self._native_lib is not None:
                return
            from siddhi_tpu.native import strdict_lib

            lib = strdict_lib()
            if lib is None:
                self._native_lib = False   # failed: never re-probe the lib
            else:
                self._native_lib = lib
                self._native = ctypes.c_void_p(lib.strdict_new())
                # backfill from a SNAPSHOT (a concurrent encode() insert
                # would otherwise mutate the dict mid-iteration); a pair
                # inserted twice — here and by that racing encode — is
                # idempotent, and a pair the snapshot missed at worst
                # probes as an extra _MISS, resolved correctly by the
                # serial phase; never a wrong id
                with self._insert_lock:
                    items = list(self._to_id.items())
                for s, i in items:
                    self._mirror_insert(s, i)

    def probe_array(self, values: np.ndarray) -> np.ndarray:
        """Read-only bulk probe: ids for known strings, ``_MISS`` markers
        for everything else (new strings, Nones, non-str values) —
        NOTHING is inserted, so concurrent probes from ingest pack-pool
        workers are safe. Callers resolve the markers serially (in row
        order) via :meth:`resolve_missing` so the id ASSIGNMENT order —
        which snapshots and rank tables observe — stays identical to the
        single-threaded encode."""
        arr = np.asarray(values, object)
        if not arr.flags.c_contiguous:
            arr = np.ascontiguousarray(arr)
        out = np.empty(len(arr), np.int64)
        self._ensure_native()
        if self._native is not None:
            self._native_lib.strdict_encode(
                self._native, arr.ctypes.data_as(ctypes.c_void_p), len(arr),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                self.NULL_ID, self._MISS)
        else:
            get = self._to_id.get
            out = np.fromiter((get(v, self._MISS) for v in arr),
                              np.int64, len(arr))
        return out

    def resolve_missing(self, ids: np.ndarray, value_of) -> None:
        """Serial second phase of a bulk encode: replace every ``_MISS``
        marker in ``ids`` (in index order) by encoding ``value_of(i)`` —
        the ONLY place a bulk path inserts new strings, so parallel
        probes stay deterministic."""
        miss_idx = np.nonzero(ids == self._MISS)[0]
        for i in miss_idx:
            v = value_of(int(i))
            ids[i] = (self.NULL_ID if v is None
                      else self.encode(v if type(v) is str else str(v)))

    def encode_array(self, values: np.ndarray) -> np.ndarray:
        """Bulk dictionary encoding — the batched answer to per-event
        string keys (``GroupByKeyGenerator.java:37``). Fast path: ONE call
        into the native open-addressing map (strdict.cpp; ~10x the Python
        dict loop at 65k-row batches); only misses (NEW strings, Nones,
        non-str values) take the per-element Python path
        (``resolve_missing``), which also inserts new pairs into the
        native mirror via ``encode``. Falls back to a per-string Python
        dict probe when the native lib can't build. Nones encode to
        NULL_ID."""
        arr = np.asarray(values, object)
        if not arr.flags.c_contiguous:
            arr = np.ascontiguousarray(arr)
        out = self.probe_array(arr)
        self.resolve_missing(out, lambda i: arr[i])
        return out

    def __len__(self):
        return len(self._to_str)


def encode_key_tuples(arrays, rows: np.ndarray, id_of) -> np.ndarray:
    """Dense ids for key tuples taken row-wise from ``arrays`` at ``rows``:
    structured-array ``np.unique`` preserves each column's dtype, and the
    Python dictionary (``id_of``) is probed once per *unique* tuple — the
    shared batched keying used by GroupKeyer and ValuePartitionKeyer."""
    B = arrays[0].shape[0]
    rec = np.empty(B, dtype=[(f"k{i}", a.dtype) for i, a in enumerate(arrays)])
    for i, a in enumerate(arrays):
        rec[f"k{i}"] = a
    uniq, inv = np.unique(rec[rows], return_inverse=True)
    lut = np.empty(len(uniq), np.int32)
    for u_i in range(len(uniq)):
        lut[u_i] = id_of(tuple(x.item() for x in uniq[u_i]))
    return lut[inv]


# vectorized None-scan over object columns (HostBatch.from_events): one
# ufunc sweep instead of a per-row `is None` list comprehension
_NONE_MASK = np.frompyfunc(lambda v: v is None, 1, 1)

# one-shot native strdict bootstrap guard (StringDictionary._ensure_native):
# plain Lock, not make_lock — held only around the ctypes constructor, no
# ranked lock is ever taken under it
_NATIVE_INIT_LOCK = threading.Lock()


def pack_pool_of(app_context):
    """The app's ingest pack pool, or None (pool size 0 / no context) —
    the one accessor every pack call site uses, so the inline path stays
    a single getattr (``core/stream/input/pack_pool.py``)."""
    if app_context is None:
        return None
    return getattr(app_context, "ingest_pack_pool", None)


def _journey_t0() -> Optional[float]:
    """Pack-stage stamp: perf_counter at pack start when batch-journey
    tracing is on, else None — one module-flag check per BATCH pack
    (observability/journey.py; maybe_delay is the tests' planted-pack-
    bottleneck injection point, a no-op unless armed)."""
    if not journey.enabled():
        return None
    t0 = time.perf_counter()
    journey.maybe_delay("pack")   # inside the timed window by design
    return t0


def _pad_len(n: int, minimum: int = 8) -> int:
    """Pad batch length to a power of two to bound jit recompiles."""
    b = minimum
    while b < n:
        b *= 2
    return b


class LazyColumns(dict):
    """Column dict whose device-array values materialize to numpy on first
    access. Device->host transfer through the axon tunnel costs a ~70 ms
    round trip PER PULL regardless of size, but ``jax.device_get`` batches
    arbitrarily many arrays into one round trip — so the first touched
    device column pulls every remaining device column in one RPC, and
    consumers that never read data columns (output counters served by the
    ``__meta__`` size hint) pull nothing."""

    def __getitem__(self, k):
        v = super().__getitem__(k)
        if not isinstance(v, np.ndarray):
            self._materialize_all()
            v = super().__getitem__(k)
        return v

    def _materialize_all(self):
        import jax

        pending = [(key, val) for key, val in super().items()
                   if not isinstance(val, np.ndarray)]
        if not pending:
            return
        pulled = jax.device_get([v for _k, v in pending])
        for (key, _v), arr in zip(pending, pulled):
            super().__setitem__(key, np.asarray(arr))

    def get(self, k, default=None):
        if k in self:
            return self[k]
        return default

    def pop(self, k, *default):
        # pops materialize ONLY the popped value (control scalars like
        # __meta__ must not drag every data column across the link);
        # explicit device_get — this IS a sanctioned pull point, and the
        # SIDDHI_TPU_SANITIZE transfer guard rejects implicit transfers
        if k in self:
            v = super().__getitem__(k)
            dict.pop(self, k)
            if not isinstance(v, np.ndarray):
                import jax

                v = np.asarray(jax.device_get(v))
            return v
        if default:
            return default[0]
        raise KeyError(k)


class HostBatch:
    """Columnar batch on host (numpy), convertible to device cols dict.

    Column keys: attribute names (optionally prefixed by the planner), plus
    reserved ``__ts__`` (i64), ``__type__`` (i8), ``__valid__`` (bool) and
    per-attribute null masks under ``<key>?``. Columns may be lazily-held
    device arrays (``LazyColumns``) that pull on first read.
    """

    def __init__(self, cols: Dict[str, np.ndarray], size: Optional[int] = None):
        self.cols = cols
        self._size = size        # known valid-row count (avoids a pull)

    @property
    def size(self) -> int:
        if self._size is None:
            self._size = int(np.asarray(self.cols[VALID_KEY]).sum())
        return self._size

    @property
    def capacity(self) -> int:
        return self.cols[VALID_KEY].shape[0]

    # per-batch journey trace context (observability/journey.py): stamped
    # at pack when journey tracing is on, forked per receiving query
    journey = None

    @staticmethod
    def from_events(
        events: Sequence[Event],
        definition: AbstractDefinition,
        dictionary: StringDictionary,
        pad_to: Optional[int] = None,
        event_type: int = CURRENT,
        pool=None,
    ) -> "HostBatch":
        if pool is not None:
            chunks = pool.plan_events(len(events), definition)
            if chunks is not None:
                # multicore ingest (core/stream/input/pack_pool.py): the
                # encode work runs as sequence-numbered sub-batch tasks
                # on the pool; the ordered merge keeps outputs AND
                # dictionary id assignment bit-identical to this inline
                # path. The plan is computed ONCE and threaded through —
                # a pool state flip between two plan calls must not
                # strand the batch between paths.
                return _parallel_from_events(pool, chunks, events,
                                             definition, dictionary,
                                             pad_to, event_type)
        t0 = _journey_t0()
        n = len(events)
        b = pad_to if pad_to is not None else _pad_len(n)
        cols: Dict[str, np.ndarray] = {
            TS_KEY: np.zeros(b, np.int64),
            TYPE_KEY: np.full(b, event_type, np.int8),
            VALID_KEY: np.zeros(b, bool),
        }
        cols[VALID_KEY][:n] = True
        if n:
            cols[TS_KEY][:n] = np.fromiter(
                (ev.timestamp for ev in events), np.int64, n)
            expired = np.fromiter((ev.is_expired for ev in events), bool, n)
            if expired.any():
                cols[TYPE_KEY][:n][expired] = EXPIRED
        rows = [ev.data for ev in events]
        for pos, attr in enumerate(definition.attributes):
            dtype = dtype_of(attr.type)
            arr = np.zeros(b, dtype)
            # null masks are always present so device column sets (and jit
            # shapes) stay static whether or not a batch contains nulls
            mask = np.zeros(b, bool)
            if n:
                if attr.type == AttrType.OBJECT:
                    # set ingestion. Element codes follow the stream's
                    # recorded element type (see encode_set_value); the
                    # representation follows its multi/singleton register:
                    # a MULTI attr (unionSet output) re-encodes as live
                    # count + '#set'/'#setm' companions, a singleton as its
                    # element code.
                    from siddhi_tpu.ops.expressions import encode_set_value

                    elem_t = (getattr(definition, "object_elem_types", None)
                              or {}).get(attr.name)
                    multi = attr.name in (getattr(
                        definition, "object_multi_attrs", None) or set())
                    as_sets = []
                    nulls = []
                    for i, r in enumerate(rows):
                        val = r[pos]
                        if val is None:
                            nulls.append(i)
                            as_sets.append(frozenset())
                        elif isinstance(val, (set, frozenset)):
                            as_sets.append(val)
                        else:
                            as_sets.append(frozenset([val]))
                    if multi:
                        H = max(1, max((len(s) for s in as_sets), default=1))
                        snap = np.zeros((b, H), np.int64)
                        snapm = np.zeros((b, H), bool)
                        for i, s in enumerate(as_sets):
                            for j, el in enumerate(s):
                                snap[i, j] = encode_set_value(
                                    el, elem_t, dictionary)
                                snapm[i, j] = True
                            arr[i] = len(s)
                        cols[attr.name + "#set"] = snap
                        cols[attr.name + "#setm"] = snapm
                    else:
                        for i, s in enumerate(as_sets):
                            if len(s) > 1:
                                raise ValueError(
                                    f"attribute '{attr.name}' carries "
                                    "singleton sets (createSet transport); "
                                    "got a multi-element set")
                            if s:
                                arr[i] = encode_set_value(
                                    next(iter(s)), elem_t, dictionary)
                    if nulls:
                        mask[nulls] = True
                elif attr.type == AttrType.STRING:
                    # ONE bulk dictionary pass over the column (native
                    # strdict fast path; Nones encode to NULL_ID there)
                    # instead of a per-row Python encode() probe
                    col = np.fromiter((r[pos] for r in rows), object, n)
                    ids = dictionary.encode_array(col)
                    mask[:n] = ids == StringDictionary.NULL_ID
                    arr[:n] = np.where(mask[:n], 0, ids)
                else:
                    zero = False if attr.type == AttrType.BOOL else 0
                    col = np.fromiter((r[pos] for r in rows), object, n)
                    nulls = _NONE_MASK(col).astype(bool)
                    if nulls.any():
                        mask[:n] = nulls
                        arr[:n] = np.where(nulls, zero, col)
                    else:
                        arr[:n] = col
            cols[attr.name] = arr
            cols[attr.name + "?"] = mask
        batch = HostBatch(cols)
        if t0 is not None:
            journey.stamp_pack(batch, t0)
        return batch

    @staticmethod
    def from_columns(
        data: Dict[str, np.ndarray],
        definition: AbstractDefinition,
        dictionary: StringDictionary,
        timestamps: Optional[np.ndarray] = None,
        default_ts: int = 0,
        pad_to: Optional[int] = None,
        pool=None,
    ) -> "HostBatch":
        """Zero-copy-ish columnar ingestion — the TPU-native fast path that
        skips per-event objects entirely. ``data`` maps attribute names to
        arrays (strings may be numpy object/str arrays, encoded here, or
        pre-encoded int ids). ``<name>?`` null-mask arrays are optional."""
        if pool is not None:
            chunks = pool.plan_columns(data, definition)
            if chunks is not None:
                return _parallel_from_columns(pool, chunks, data,
                                              definition, dictionary,
                                              timestamps, default_ts,
                                              pad_to)
        t0 = _journey_t0()
        first = next(iter(data.values()))
        n = len(first)
        b = pad_to if pad_to is not None else _pad_len(n)
        cols: Dict[str, np.ndarray] = {
            TYPE_KEY: np.full(b, CURRENT, np.int8),
            VALID_KEY: np.zeros(b, bool),
        }
        cols[VALID_KEY][:n] = True
        ts = np.zeros(b, np.int64)
        if timestamps is not None:
            ts[:n] = np.asarray(timestamps, np.int64)[:n]
        else:
            ts[:n] = default_ts
        cols[TS_KEY] = ts
        for attr in definition.attributes:
            if attr.name not in data:
                raise KeyError(f"column '{attr.name}' missing from batch")
            src = np.asarray(data[attr.name])
            dtype = dtype_of(attr.type)
            arr = np.zeros(b, dtype)
            mask = np.zeros(b, bool)
            if attr.type == AttrType.STRING and not np.issubdtype(src.dtype, np.integer):
                ids = dictionary.encode_array(src)[:n]
                mask[:n] = ids == StringDictionary.NULL_ID
                arr[:n] = np.where(mask[:n], 0, ids)
            elif attr.type == AttrType.STRING:
                ids = np.asarray(src[:n], np.int64)
                mask[:n] = ids < 0  # pre-encoded: negative = null
                arr[:n] = np.where(mask[:n], 0, ids)
            else:
                arr[:n] = src[:n]
            user_mask = data.get(attr.name + "?")
            if user_mask is not None:
                mask[:n] |= np.asarray(user_mask, bool)[:n]
            cols[attr.name] = arr
            cols[attr.name + "?"] = mask
        batch = HostBatch(cols)
        if t0 is not None:
            journey.stamp_pack(batch, t0)
        return batch

    def to_events(
        self,
        attr_order: Sequence[tuple],  # [(key, AttrType), ...]
        dictionary: StringDictionary,
        types_wanted: Optional[Sequence[int]] = None,
        pk_key: Optional[str] = None,
        gk_key: Optional[str] = None,
        object_meta: Optional[Dict[str, object]] = None,
        object_multi: Optional[set] = None,
    ) -> List[Event]:
        """Decode valid rows into Events (optionally filtered by type).
        ``pk_key`` names a partition-id column to attach as Event.pk;
        ``gk_key`` a group-id column to attach as Event.gk.
        ``object_meta`` maps OBJECT (set-valued) attr names to their
        element AttrType (raw int codes without it); ``object_multi``
        names the attrs that are MULTI-element sets — decoding one whose
        '#set' companions were dropped raises instead of emitting the
        live count as a bogus singleton."""
        valid = np.asarray(self.cols[VALID_KEY])
        types = np.asarray(self.cols[TYPE_KEY])
        ts = np.asarray(self.cols[TS_KEY])
        pk_col = self.cols.get(pk_key) if pk_key is not None else None
        gk_col = self.cols.get(gk_key) if gk_key is not None else None
        keep = valid
        if types_wanted is not None:
            keep = keep & np.isin(types, list(types_wanted))
        idx = np.nonzero(keep)[0]
        if idx.size == 0:
            return []
        # decode per column (vectorized), then zip rows — no per-cell
        # dispatch on dtype inside the row loop
        col_lists: List[list] = []
        for key, attr_type in attr_order:
            vals = np.asarray(self.cols[key])[idx]
            if attr_type == AttrType.OBJECT:
                # set values: '#set'/'#setm' companions hold the elements
                # (unionSet snapshots); a bare column is a singleton set
                # whose value IS the element code (createSet transport)
                from siddhi_tpu.ops.expressions import decode_set_element

                elem_t = (object_meta or {}).get(key)
                snap = self.cols.get(key + "#set")
                if snap is not None:
                    sv = np.asarray(snap)[idx]
                    sm = np.asarray(self.cols[key + "#setm"])[idx]
                    lst = [frozenset(decode_set_element(c, elem_t, dictionary)
                                     for c in row_v[row_m])
                           for row_v, row_m in zip(sv, sm)]
                elif object_multi and key in object_multi:
                    # the base column of a multi set is its live COUNT —
                    # decoding it as an element would be silent garbage
                    # (mirrors the unionSet arg_is_multi guard)
                    raise ValueError(
                        f"multi-element set attribute '{key}' lost its "
                        f"'#set' element snapshot (a window buffers only "
                        f"the base column); project it before windowing")
                else:
                    lst = [frozenset([decode_set_element(v, elem_t, dictionary)])
                           for v in vals]
                mask = self.cols.get(key + "?")
                if mask is not None:
                    mvals = np.asarray(mask)[idx]
                    if mvals.any():
                        lst = [None if m else v for v, m in zip(lst, mvals)]
                col_lists.append(lst)
                continue
            if attr_type == AttrType.STRING:
                lst = [dictionary.decode(int(v)) for v in vals]
            elif attr_type == AttrType.BOOL:
                lst = [bool(v) for v in vals]
            elif attr_type in (AttrType.INT, AttrType.LONG):
                lst = vals.astype(np.int64).tolist()
            else:
                lst = vals.astype(np.float64).tolist()
            mask = self.cols.get(key + "?")
            if mask is not None:
                mvals = np.asarray(mask)[idx]
                if mvals.any():
                    lst = [None if m else v for v, m in zip(lst, mvals)]
            col_lists.append(lst)
        ts_l = ts[idx].tolist()
        exp_l = (types[idx] == EXPIRED).tolist()
        rows = zip(*col_lists) if col_lists else ([] for _ in idx)
        out = [
            Event(timestamp=t, data=list(r), is_expired=e)
            for t, e, r in zip(ts_l, exp_l, rows)
        ]
        if pk_col is not None:
            pks = np.asarray(pk_col)[idx].tolist()
            for ev, p in zip(out, pks):
                ev.pk = int(p)
        if gk_col is not None:
            gks = np.asarray(gk_col)[idx].tolist()
            for ev, g in zip(out, gks):
                ev.gk = int(g)
        return out


# ------------------------------------------------------ parallel ordered pack
#
# The multicore half of HostBatch.from_events / from_columns ("Scaling
# Ordered Stream Processing on Shared-Memory Multicores", PAPERS.md): the
# encode work of ONE batch is split into sequence-numbered row-range
# sub-batches that run on the app's IngestPackPool workers, each writing a
# disjoint slice of the pre-allocated output columns. The ordered merge —
# waiting the sub-batches out in sequence order, then resolving every NEW
# string serially in attribute-major row order — keeps the produced arrays
# AND the dictionary's id-assignment order bit-identical to the inline
# path, so emission order, WAL records, snapshots and rank tables cannot
# tell the paths apart. Journey pack attribution follows the PR-11
# max-not-sum rule: concurrent sub-batch service counts once (the slowest
# packer), plus the serial merge.

def _parallel_from_events(pool, chunks, events, definition, dictionary,
                          pad_to, event_type) -> "HostBatch":
    jt = journey.enabled()
    n = len(events)
    b = pad_to if pad_to is not None else _pad_len(n)
    cols: Dict[str, np.ndarray] = {
        TS_KEY: np.zeros(b, np.int64),
        TYPE_KEY: np.full(b, event_type, np.int8),
        VALID_KEY: np.zeros(b, bool),
    }
    cols[VALID_KEY][:n] = True
    attrs = definition.attributes
    arrs: Dict[str, np.ndarray] = {}
    masks: Dict[str, np.ndarray] = {}
    scratch: Dict[str, np.ndarray] = {}   # string probe ids (_MISS marked)
    positions = {}
    for pos, attr in enumerate(attrs):
        arrs[attr.name] = np.zeros(b, dtype_of(attr.type))
        masks[attr.name] = np.zeros(b, bool)
        positions[attr.name] = pos
        if attr.type == AttrType.STRING:
            scratch[attr.name] = np.empty(n, np.int64)

    def pack_chunk(lo: int, hi: int) -> None:
        if jt:
            journey.maybe_delay("pack")   # planted-bottleneck injection
        m = hi - lo
        sub = events[lo:hi]
        cols[TS_KEY][lo:hi] = np.fromiter(
            (ev.timestamp for ev in sub), np.int64, m)
        expired = np.fromiter((ev.is_expired for ev in sub), bool, m)
        if expired.any():
            cols[TYPE_KEY][lo:hi][expired] = EXPIRED
        rows = [ev.data for ev in sub]
        for pos, attr in enumerate(attrs):
            if attr.type == AttrType.STRING:
                col = np.fromiter((r[pos] for r in rows), object, m)
                # probe only — new strings stay _MISS markers for the
                # serial merge (deterministic id assignment)
                scratch[attr.name][lo:hi] = dictionary.probe_array(col)
            else:
                zero = False if attr.type == AttrType.BOOL else 0
                col = np.fromiter((r[pos] for r in rows), object, m)
                nulls = _NONE_MASK(col).astype(bool)
                if nulls.any():
                    masks[attr.name][lo:hi] = nulls
                    arrs[attr.name][lo:hi] = np.where(nulls, zero, col)
                else:
                    arrs[attr.name][lo:hi] = col

    chunk_ms = pool.run_ordered(chunks, pack_chunk)
    t_merge = time.perf_counter()
    for attr in attrs:
        if attr.type == AttrType.STRING:
            ids = scratch[attr.name]
            pos = positions[attr.name]
            # serial miss resolution in row order, attributes in
            # declaration order — the exact insertion order the inline
            # per-attribute encode_array produces
            dictionary.resolve_missing(
                ids, lambda i, _p=pos: events[i].data[_p])
            mask = ids == StringDictionary.NULL_ID
            masks[attr.name][:n] = mask
            arrs[attr.name][:n] = np.where(mask, 0, ids)
        cols[attr.name] = arrs[attr.name]
        cols[attr.name + "?"] = masks[attr.name]
    batch = HostBatch(cols)
    merge_ms = (time.perf_counter() - t_merge) * 1000.0
    pool.record_merge(merge_ms)
    if jt:
        # max-not-sum: sub-batches packed concurrently — the pack stage's
        # service is the slowest packer plus the serial merge
        journey.stamp_pack_ms(batch, max(chunk_ms, default=0.0) + merge_ms)
    return batch


def _parallel_from_columns(pool, chunks, data, definition, dictionary,
                           timestamps, default_ts, pad_to) -> "HostBatch":
    jt = journey.enabled()
    first = next(iter(data.values()))
    n = len(first)
    b = pad_to if pad_to is not None else _pad_len(n)
    cols: Dict[str, np.ndarray] = {
        TYPE_KEY: np.full(b, CURRENT, np.int8),
        VALID_KEY: np.zeros(b, bool),
    }
    cols[VALID_KEY][:n] = True
    ts = np.zeros(b, np.int64)
    if timestamps is not None:
        ts_src = np.asarray(timestamps, np.int64)
    else:
        ts_src = None
        ts[:n] = default_ts
    cols[TS_KEY] = ts
    attrs = definition.attributes
    for attr in attrs:
        if attr.name not in data:
            raise KeyError(f"column '{attr.name}' missing from batch")
    arrs: Dict[str, np.ndarray] = {}
    masks: Dict[str, np.ndarray] = {}
    scratch: Dict[str, np.ndarray] = {}
    srcs = {attr.name: np.asarray(data[attr.name]) for attr in attrs}
    str_obj = {attr.name: (attr.type == AttrType.STRING
                           and not np.issubdtype(srcs[attr.name].dtype,
                                                 np.integer))
               for attr in attrs}
    for attr in attrs:
        arrs[attr.name] = np.zeros(b, dtype_of(attr.type))
        masks[attr.name] = np.zeros(b, bool)
        if str_obj[attr.name]:
            scratch[attr.name] = np.empty(n, np.int64)

    def pack_chunk(lo: int, hi: int) -> None:
        if jt:
            journey.maybe_delay("pack")
        if ts_src is not None:
            ts[lo:hi] = ts_src[lo:hi]
        for attr in attrs:
            src = srcs[attr.name]
            if str_obj[attr.name]:
                scratch[attr.name][lo:hi] = dictionary.probe_array(
                    src[lo:hi])
            elif attr.type == AttrType.STRING:
                ids = np.asarray(src[lo:hi], np.int64)
                m = ids < 0           # pre-encoded: negative = null
                masks[attr.name][lo:hi] = m
                arrs[attr.name][lo:hi] = np.where(m, 0, ids)
            else:
                arrs[attr.name][lo:hi] = src[lo:hi]

    chunk_ms = pool.run_ordered(chunks, pack_chunk)
    t_merge = time.perf_counter()
    for attr in attrs:
        if str_obj[attr.name]:
            ids = scratch[attr.name]
            src = srcs[attr.name]
            dictionary.resolve_missing(ids, lambda i, _s=src: _s[i])
            mask = ids == StringDictionary.NULL_ID
            masks[attr.name][:n] = mask
            arrs[attr.name][:n] = np.where(mask, 0, ids)
        user_mask = data.get(attr.name + "?")
        if user_mask is not None:
            masks[attr.name][:n] |= np.asarray(user_mask, bool)[:n]
        cols[attr.name] = arrs[attr.name]
        cols[attr.name + "?"] = masks[attr.name]
    batch = HostBatch(cols)
    merge_ms = (time.perf_counter() - t_merge) * 1000.0
    pool.record_merge(merge_ms)
    if jt:
        journey.stamp_pack_ms(batch, max(chunk_ms, default=0.0) + merge_ms)
    return batch
