"""SiddhiDebugger: query IN/OUT breakpoints with an event callback.

Mirror of reference ``core/debugger/SiddhiDebugger.java`` +
``SiddhiDebuggerCallback``: breakpoints attach at a query's input (before
the step processes a chunk) or output (before callbacks fire). The
callback runs synchronously on the pump thread — the batch does not
proceed until it returns (the columnar analog of the reference's
acquire/next/play lock-stepping; there is no separate suspended-thread
state to resume because the pump is already synchronous).

Usage::

    debugger = runtime.debug()
    debugger.set_debugger_callback(cb)          # cb(events, qname, terminal, dbg)
    debugger.acquire_break_point('query1', SiddhiDebugger.QueryTerminal.IN)
"""

from __future__ import annotations

import enum
from typing import Callable, Dict, List, Optional, Tuple


class SiddhiDebugger:
    class QueryTerminal(enum.Enum):
        IN = "IN"
        OUT = "OUT"

    def __init__(self, app_runtime):
        self.app_runtime = app_runtime
        self._callback: Optional[Callable] = None
        self._wrapped: Dict[Tuple[str, "SiddhiDebugger.QueryTerminal"], tuple] = {}

    def set_debugger_callback(self, callback: Callable):
        """callback(events, query_name, terminal, debugger)."""
        self._callback = callback

    # ------------------------------------------------------------ breakpoints

    def acquire_break_point(self, query_name: str, terminal: "SiddhiDebugger.QueryTerminal"):
        rt = self.app_runtime.query_runtimes.get(query_name)
        if rt is None:
            raise KeyError(f"unknown query '{query_name}'")
        key = (query_name, terminal)
        if key in self._wrapped:
            return
        dbg = self

        if terminal == SiddhiDebugger.QueryTerminal.IN:
            targets = [n for n in ("receive_batch", "process_stream_batch",
                                   "process_side_batch", "process_batch")
                       if hasattr(rt, n)]
            originals = []
            for name in targets:
                orig = getattr(rt, name)

                def wrapper(*args, _orig=orig, _rt=rt, **kw):
                    from siddhi_tpu.core.event import HostBatch

                    batch = next((a for a in args if isinstance(a, HostBatch)), None)
                    dbg._fire(_decode(batch, _rt), query_name, terminal)
                    return _orig(*args, **kw)

                setattr(rt, name, wrapper)
                originals.append((name, orig))
            self._wrapped[key] = tuple(originals)
        else:
            orig = rt._emit

            def out_wrapper(out_batch, _orig=orig, _rt=rt):
                dbg._fire(_decode(out_batch, _rt, output=True), query_name, terminal)
                return _orig(out_batch)

            rt._emit = out_wrapper
            self._wrapped[key] = (("_emit", orig),)

    def release_break_point(self, query_name: str, terminal: "SiddhiDebugger.QueryTerminal"):
        key = (query_name, terminal)
        originals = self._wrapped.pop(key, ())
        rt = self.app_runtime.query_runtimes.get(query_name)
        if rt is None:
            return
        for name, orig in originals:
            setattr(rt, name, orig)

    def release_all_break_points(self):
        for qname, terminal in list(self._wrapped):
            self.release_break_point(qname, terminal)

    # ---------------------------------------------------------------- fire

    def _fire(self, events: List, query_name: str, terminal):
        if self._callback is not None and events:
            self._callback(events, f"{query_name}:{terminal.value}", terminal, self)


def _decode(batch, rt, output: bool = False) -> List:
    from siddhi_tpu.core.event import HostBatch

    if not isinstance(batch, HostBatch):
        return []
    try:
        if output:
            return batch.to_events(rt.output_attrs, rt.dictionary)
        defn = rt.input_definition
        if defn is None:    # NFA/join inputs: per-stream definitions differ
            return []
        return batch.to_events(
            [(a.name, a.type) for a in defn.attributes], rt.dictionary)
    except Exception:
        return []
