"""SiddhiDebugger: query IN/OUT breakpoints with suspend/step semantics.

Mirror of reference ``core/debugger/SiddhiDebugger.java`` +
``SiddhiDebuggerCallback``: breakpoints attach at a query's input (before
the step processes a chunk) or output (before callbacks fire). When a
batch hits an acquired breakpoint (or a pending ``next()``), the callback
fires and the pump thread BLOCKS on a semaphore until ``next()`` or
``play()`` releases it (``SiddhiDebugger.java:182-190``
checkBreakPoint/next/play):

- ``play()``  — resume; run until the next ACQUIRED breakpoint.
- ``next()``  — resume; the released thread breaks again at the very
  next checkpoint it reaches, acquired or not (single-step). The flag is
  thread-local, like the reference's ``threadLocalNextFlag``.

Calling ``next()``/``play()`` from inside the callback is supported (the
reference test idiom): the semaphore permit accumulates, so the
subsequent ``acquire`` returns immediately.

Usage::

    debugger = runtime.debug()
    debugger.set_debugger_callback(cb)          # cb(events, qname, terminal, dbg)
    debugger.acquire_break_point('query1', SiddhiDebugger.QueryTerminal.IN)
    ...
    debugger.next()   # from the callback or another thread
"""

from __future__ import annotations

import enum
import threading
from typing import Callable, Dict, List, Optional, Tuple


class SiddhiDebugger:
    class QueryTerminal(enum.Enum):
        IN = "IN"
        OUT = "OUT"

    def __init__(self, app_runtime):
        self.app_runtime = app_runtime
        self._callback: Optional[Callable] = None
        self._wrapped: Dict[Tuple[str, "SiddhiDebugger.QueryTerminal"], tuple] = {}
        self._active: set = set()                 # acquired breakpoints
        # suspend/step machinery (SiddhiDebugger.java:56-69):
        self._bp_lock = threading.Semaphore(0)    # breakPointLock
        self._enable_next = False                 # enableNext (cross-thread)
        self._tls = threading.local()             # threadLocalNextFlag
        # every query terminal is a checkpoint (the reference calls
        # checkBreakPoint unconditionally from each query valve), so a
        # next() can single-step into queries with no acquired breakpoint
        for qname in app_runtime.query_runtimes:
            for terminal in SiddhiDebugger.QueryTerminal:
                self._instrument(qname, terminal)

    def set_debugger_callback(self, callback: Callable):
        """callback(events, query_name, terminal, debugger)."""
        self._callback = callback

    # ------------------------------------------------------------- stepping

    def next(self):
        """Release the suspended pump thread and break again at the NEXT
        checkpoint it reaches, whether or not a breakpoint is acquired
        there (reference ``next()``)."""
        self._enable_next = True
        self._bp_lock.release()

    def play(self):
        """Release the suspended pump thread; it runs until the next
        ACQUIRED breakpoint (reference ``play()``)."""
        self._bp_lock.release()

    def get_query_state(self, query_name: str):
        """Live state snapshot of one query (reference ``getQueryState``
        via SnapshotService.queryState). Safe from the debugger callback
        (the pump thread already holds the query's RLock) AND from a
        controller thread while the pump is SUSPENDED at an OUT
        breakpoint — there the pump holds the lock across the suspension,
        so a blocking acquire would deadlock the suspend-inspect-resume
        workflow; after a short timeout we read without the lock (the
        suspended pump is quiescent: its state update already finished)."""
        from siddhi_tpu.core.util.snapshot import _to_host

        q = self.app_runtime.query_runtimes.get(query_name)
        if q is None:
            raise KeyError(f"unknown query '{query_name}'")
        locked = q._lock.acquire(timeout=1.0)
        try:
            return {
                "state": _to_host(q._state) if q._state is not None else None,
                "host_window": (q.host_window.snapshot()
                                if q.host_window is not None else None),
            }
        finally:
            if locked:
                q._lock.release()

    # ------------------------------------------------------------ breakpoints

    def acquire_break_point(self, query_name: str, terminal: "SiddhiDebugger.QueryTerminal"):
        if query_name not in self.app_runtime.query_runtimes:
            raise KeyError(f"unknown query '{query_name}'")
        self._active.add((query_name, terminal))

    def _instrument(self, query_name: str, terminal: "SiddhiDebugger.QueryTerminal"):
        rt = self.app_runtime.query_runtimes.get(query_name)
        if rt is None:
            raise KeyError(f"unknown query '{query_name}'")
        key = (query_name, terminal)
        if key in self._wrapped:
            return
        dbg = self

        if terminal == SiddhiDebugger.QueryTerminal.IN:
            targets = [n for n in ("receive_batch", "process_stream_batch",
                                   "process_side_batch", "process_batch")
                       if hasattr(rt, n)]
            originals = []
            for name in targets:
                orig = getattr(rt, name)

                def wrapper(*args, _orig=orig, _rt=rt, **kw):
                    from siddhi_tpu.core.event import HostBatch

                    batch = next((a for a in args if isinstance(a, HostBatch)), None)
                    dbg._checkpoint(lambda: _decode(batch, _rt),
                                    query_name, terminal)
                    return _orig(*args, **kw)

                setattr(rt, name, wrapper)
                originals.append((name, orig))
            self._wrapped[key] = tuple(originals)
        else:
            orig = rt._emit

            def out_wrapper(out_batch, _orig=orig, _rt=rt):
                dbg._checkpoint(lambda: _decode(out_batch, _rt, output=True),
                                query_name, terminal)
                return _orig(out_batch)

            rt._emit = out_wrapper
            self._wrapped[key] = (("_emit", orig),)

    def release_break_point(self, query_name: str, terminal: "SiddhiDebugger.QueryTerminal"):
        self._active.discard((query_name, terminal))

    def release_all_break_points(self):
        self._active.clear()

    def detach(self):
        """Remove the checkpoint instrumentation entirely (not part of the
        reference surface — its checkpoints are compiled in permanently)."""
        self._active.clear()
        for (qname, _terminal), originals in self._wrapped.items():
            rt = self.app_runtime.query_runtimes.get(qname)
            if rt is None:
                continue
            for name, orig in originals:
                setattr(rt, name, orig)
        self._wrapped.clear()

    # ---------------------------------------------------------------- fire

    def _checkpoint(self, decode: Callable[[], List], query_name: str, terminal):
        """Reference ``checkBreakPoint``: a checkpoint is "hit" when its
        breakpoint is acquired OR this thread was released with ``next()``.
        On a hit: decode the batch, fire the callback, then suspend the
        pump thread until next()/play() releases it."""
        is_next = getattr(self._tls, "next", False)
        hit = (query_name, terminal) in self._active or is_next
        if not hit:
            return
        events = decode()
        if not events:
            return
        if is_next:
            self._tls.next = False
        if self._callback is not None:
            self._callback(events, f"{query_name}:{terminal.value}", terminal, self)
        self._bp_lock.acquire()
        if self._enable_next:
            # must be set from the released thread itself (the reference
            # keeps this out of next()/play() for the same reason)
            self._tls.next = True
            self._enable_next = False


def _decode(batch, rt, output: bool = False) -> List:
    from siddhi_tpu.core.event import HostBatch

    if not isinstance(batch, HostBatch):
        return []
    try:
        if output:
            return batch.to_events(rt.output_attrs, rt.dictionary)
        defn = rt.input_definition
        if defn is None:    # NFA/join inputs: per-stream definitions differ
            return []
        return batch.to_events(
            [(a.name, a.type) for a in defn.attributes], rt.dictionary)
    except Exception:
        return []
