"""Variable resolvers: map query-api Variables to batch column keys.

The analog of meta-event attribute position resolution in the reference
(``QueryParserHelper.reduceMetaComplexEvent/updateVariablePosition``,
``MetaStreamEvent.java:34-41``) — but instead of (stream, segment, index)
positions, attributes resolve to named columns of the batch.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from siddhi_tpu.core.event import StringDictionary
from siddhi_tpu.ops.expressions import ColumnRef, CompileError, Resolver
from siddhi_tpu.query_api.definitions import AbstractDefinition, AttrType
from siddhi_tpu.query_api.expressions import Variable


class SingleStreamResolver(Resolver):
    """Resolve against one stream definition (+ synthetic columns such as
    aggregator outputs), with an app-global string dictionary."""

    def __init__(
        self,
        definition: AbstractDefinition,
        dictionary: StringDictionary,
        ref_id: Optional[str] = None,
        prefix: str = "",
        synthetic: Optional[Dict[str, AttrType]] = None,
    ):
        self.definition = definition
        self.dictionary = dictionary
        self.ref_id = ref_id
        self.prefix = prefix
        self.synthetic = synthetic or {}

    def accepts_stream(self, stream_id: Optional[str]) -> bool:
        return stream_id is None or stream_id == self.definition.id or stream_id == self.ref_id

    def resolve(self, var: Variable) -> ColumnRef:
        if var.attribute_name in self.synthetic:
            return ColumnRef(var.attribute_name, self.synthetic[var.attribute_name])
        if not self.accepts_stream(var.stream_id):
            raise CompileError(
                f"'{var.stream_id}.{var.attribute_name}' does not match stream "
                f"'{self.definition.id}'"
            )
        attr = self.definition.attribute(var.attribute_name)
        return ColumnRef(self.prefix + attr.name, attr.type)

    def encode_string(self, s: str) -> int:
        return self.dictionary.encode(s)


class OutputColsResolver(Resolver):
    """Resolve against the selector's output columns (for `having`,
    `order by`), falling back to another resolver for raw input attrs —
    matching the reference where having executes on the projected event."""

    def __init__(self, outputs: List[Tuple[str, AttrType]], dictionary: StringDictionary,
                 fallback: Optional[Resolver] = None):
        self.outputs = dict(outputs)
        self.dictionary = dictionary
        self.fallback = fallback

    def resolve(self, var: Variable) -> ColumnRef:
        if var.stream_id is None and var.attribute_name in self.outputs:
            return ColumnRef(var.attribute_name, self.outputs[var.attribute_name])
        if self.fallback is not None:
            return self.fallback.resolve(var)
        raise CompileError(f"unknown attribute '{var.attribute_name}' in having/order by")

    def encode_string(self, s: str) -> int:
        return self.dictionary.encode(s)
