"""Selector planning: select/group by/having/order by/limit -> device stage.

The compile-time analog of reference ``SelectorParser.java`` +
``QuerySelector.java``: aggregator call sites in the selection are split out
(reference ``ExpressionParser`` detects aggregators via extension holders),
computed by segmented scans (``ops/aggregators.py``), and the remaining
scalar expressions become fused projections.

Semantics reproduced (``QuerySelector.processGroupBy``/``processInBatch*``):
- every CURRENT/EXPIRED row updates aggregators and yields an output row;
- RESET rows reset all group states and yield nothing;
- TIMER rows are dropped;
- currentOn/expiredOn filtering, then `having`;
- batch chunks (from batch windows) keep only the last row per group
  (``processInBatchGroupBy``) or overall (``processInBatchNoGroupBy``);
- order by / offset / limit apply per output chunk.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.plan.resolvers import OutputColsResolver
from siddhi_tpu.ops import aggregators as agg_ops
from siddhi_tpu.ops.expressions import (
    OKEY_KEY,
    PK_KEY,
    RIDX_KEY,
    TS_KEY,
    TYPE_KEY,
    VALID_KEY,
    CompileError,
    Resolver,
    compile_condition,
    compile_expr,
)
from siddhi_tpu.query_api.definitions import AttrType
from siddhi_tpu.query_api.execution import Selector
from siddhi_tpu.query_api.expressions import (
    Add,
    And,
    AttributeFunction,
    Compare,
    Divide,
    Expression,
    IsNull,
    Mod,
    Multiply,
    Not,
    Or,
    Subtract,
    Variable,
)

CURRENT, EXPIRED, TIMER, RESET = 0, 1, 2, 3
GK_KEY = "__gk__"
FLUSH_KEY = "__flush__"
STR_RANK = "__strrank__"   # [dict_capacity] lexicographic rank per string id


def _rewrite_aggregators(expr: Expression, specs: List[agg_ops.AggSpec], resolver: Resolver) -> Expression:
    """Replace aggregator calls with synthetic Variables bound to scan
    output columns (the split the reference does in ExpressionParser when it
    routes AttributeFunctions to AttributeAggregatorExecutors)."""
    if isinstance(expr, AttributeFunction) and not expr.namespace \
            and expr.name.lower() in agg_ops.supported_aggregators():
        kind = expr.name.lower()
        # arity/type validation mirroring the reference executors'
        # @ParameterOverload contracts (e.g. SumAttributeAggregatorExecutor
        # accepts exactly one numeric attribute; extra or string arguments
        # fail app creation)
        if kind == "count":
            if len(expr.parameters) > 1:
                raise CompileError("count() accepts at most one argument")
        elif len(expr.parameters) != 1:
            raise CompileError(f"{kind}() expects exactly one argument, "
                               f"found {len(expr.parameters)}")
        if expr.parameters:
            arg_f, arg_t = compile_expr(expr.parameters[0], resolver)
        else:
            arg_f, arg_t = None, None
        if kind != "count" and arg_f is None:
            raise CompileError(f"{kind}() requires an argument")
        if kind in ("sum", "avg", "stddev", "min", "max",
                    "minforever", "maxforever") and arg_t not in (
                AttrType.INT, AttrType.LONG, AttrType.FLOAT, AttrType.DOUBLE):
            raise CompileError(
                f"{kind}() expects a numeric attribute but found "
                f"{arg_t.value if arg_t else None}")
        if kind in ("and", "or") and arg_t != AttrType.BOOL:
            raise CompileError(
                f"{kind}() expects a bool attribute but found "
                f"{arg_t.value if arg_t else None}")
        out_key = f"__agg{len(specs)}__"
        out_type = agg_ops.agg_result_type(kind, arg_t)
        spec = agg_ops.AggSpec(kind=kind, arg_fn=arg_f, arg_type=arg_t,
                               out_key=out_key, out_type=out_type)
        if kind == "unionset":
            from siddhi_tpu.ops.expressions import take_object_elem_marker

            if arg_t != AttrType.OBJECT:
                raise CompileError(
                    "Parameter passed to unionSet aggregator should be of "
                    f"type object but found: {arg_t.value if arg_t else None}")
            # element type for decode: a nested createSet() marks it; a
            # bare set attribute carries it on its stream definition (and
            # its column key locates '#set' companions for re-union)
            spec.elem_type = take_object_elem_marker()
            param = expr.parameters[0]
            if isinstance(param, Variable):
                spec.arg_key = resolver.resolve(param).key
                spec.arg_is_multi = _is_multi(resolver, param)
                if spec.elem_type is None:
                    spec.elem_type = _elem_type_of(resolver, param)
        specs.append(spec)
        return Variable(attribute_name=out_key)
    for attr_name in ("left", "right", "expression"):
        child = getattr(expr, attr_name, None)
        if isinstance(child, Expression):
            setattr(expr, attr_name, _rewrite_aggregators(child, specs, resolver))
    if isinstance(expr, AttributeFunction):
        expr.parameters = [_rewrite_aggregators(p, specs, resolver) for p in expr.parameters]
    return expr


def _elem_type_of(resolver, var: Variable):
    """Set-element type of an object attribute, recorded on its stream
    definition by the app assembler (best effort; None = decode raw)."""
    defn = getattr(resolver, "definition", None)
    meta = getattr(defn, "object_elem_types", None) if defn is not None else None
    if meta:
        return meta.get(var.attribute_name)
    return None


def _is_multi(resolver, var: Variable) -> bool:
    """Whether an object attribute is a MULTI-element set (unionSet
    output), per its stream definition's assembler metadata."""
    defn = getattr(resolver, "definition", None)
    multi = getattr(defn, "object_multi_attrs", None) if defn is not None else None
    return bool(multi) and var.attribute_name in multi


@dataclass
class SelectorPlan:
    """Compiled selector; `apply` is traced inside the query step."""

    @property
    def needs_str_rank(self) -> bool:
        """True when an order-by key is a string column — the runtime must
        inject the dictionary's lexicographic rank table as cols[STR_RANK]."""
        return any(is_str for _c, _d, is_str in self.order_by)

    specs: List[agg_ops.AggSpec]
    projections: List[Tuple[str, Callable, AttrType]]  # (out name, fn, type)
    output_attrs: List[Tuple[str, AttrType]]
    having_fn: Optional[Callable]
    group_by: bool
    group_key_exprs: List
    current_on: bool
    expired_on: bool
    batch_mode: bool          # upstream emits batch chunks (batch windows)
    order_by: List[Tuple[str, bool, bool]]  # (out col, descending, is_str)
    limit: Optional[int]
    offset: Optional[int]
    num_keys: int = 16
    # a fused upstream stage (ops/fused_agg.py) already computed the
    # aggregate columns; skip the scans and just project/filter
    precomputed: bool = False
    # output columns whose value is a host-generated UUID per row (the
    # device step emits placeholders; QueryRuntime._emit fills them)
    uuid_cols: List[str] = field(default_factory=list)
    # OBJECT set outputs: (out name, source column key) pairs whose
    # '#set'/'#setm' companions must ride along, and out name -> element
    # AttrType for event decode (None = raw int codes)
    set_cols: List[Tuple[str, str]] = field(default_factory=list)
    object_meta: Dict[str, Optional[AttrType]] = field(default_factory=dict)
    # outputs that are MULTI-element sets (unionSet results): their base
    # column is the live COUNT; singletons' base column is the element code
    object_multi: List[str] = field(default_factory=list)
    # output positions whose projection contains an aggregator call —
    # drives snapshot-limiter variant selection
    # (WrappedSnapshotOutputRateLimiter.java:67-74)
    agg_positions: List[int] = field(default_factory=list)

    @property
    def contains_aggregator(self) -> bool:
        return bool(self.specs)

    def init_state(self) -> dict:
        if self.precomputed:
            return {}
        return agg_ops.init_agg_state(self.specs, self.num_keys)

    def apply(self, state: dict, cols: dict, ctx: dict):
        xp = ctx["xp"]
        if self.specs and not self.precomputed:
            state, cols = agg_ops.apply_aggregators(self.specs, state, cols, ctx, self.num_keys)

        out: Dict[str, object] = {
            TS_KEY: cols[TS_KEY],
            TYPE_KEY: cols[TYPE_KEY],
            VALID_KEY: cols[VALID_KEY],
            GK_KEY: cols.get(GK_KEY, jnp.zeros_like(cols[TS_KEY], dtype=jnp.int32)),
        }
        if FLUSH_KEY in cols:
            out[FLUSH_KEY] = cols[FLUSH_KEY]
        if "__agg_overflow__" in cols:
            # distinctCount value-table saturation rides the meta channel
            out["__overflow__"] = cols["__agg_overflow__"]
        if PK_KEY in cols:
            out[PK_KEY] = cols[PK_KEY]  # partition id rides along to the edge
        if OKEY_KEY in cols:
            # device-routed sharding: the window's emission-order key rides
            # to the route wrapper's cross-shard merge
            out[OKEY_KEY] = cols[OKEY_KEY]
        elif RIDX_KEY in cols:
            # no window stage: rows are input-aligned, so the original
            # batch position IS the emission order
            out[OKEY_KEY] = cols[RIDX_KEY]
        B = cols[TS_KEY].shape[0]
        for name, fn, _t in self.projections:
            v, m = fn(cols, ctx)
            v = xp.asarray(v)
            if v.ndim == 0:
                v = xp.broadcast_to(v, (B,))
            out[name] = v
            if m is not None:
                m = xp.asarray(m)
                if m.ndim == 0:
                    # scalar masks (typed null literals) must take row
                    # shape: to_events indexes mask columns per row
                    m = xp.broadcast_to(m, (B,))
                out[name + "?"] = m
        for name, src in self.set_cols:
            # a set-valued output's element snapshot rides beside its count
            for suf in ("#set", "#setm"):
                if src + suf in cols:
                    out[name + suf] = cols[src + suf]

        types = cols[TYPE_KEY]
        valid = cols[VALID_KEY]
        type_ok = ((types == CURRENT) & self.current_on) | ((types == EXPIRED) & self.expired_on)
        valid = valid & type_ok
        if self.having_fn is not None:
            valid = valid & self.having_fn(out, ctx)

        if self.batch_mode and (self.contains_aggregator or self.group_by):
            # keep only the last valid row per (flush epoch, group) — GK is
            # the partition id for keyless partitioned queries, so per-key
            # flushes in one multi-key chunk stay distinct
            gk = out[GK_KEY]
            flush = out.get(FLUSH_KEY, jnp.zeros(B, jnp.int32))
            combo = flush.astype(jnp.int64) * jnp.int64(self.num_keys + 1) + gk.astype(jnp.int64)
            combo = jnp.where(valid, combo, jnp.int64(2**62))  # invalid rows last
            order = jnp.argsort(combo, stable=True)
            combo_sorted = combo[order]
            seg_last = jnp.concatenate([combo_sorted[1:] != combo_sorted[:-1], jnp.ones(1, bool)])
            is_last_sorted = valid[order] & seg_last
            valid = jnp.zeros(B, bool).at[order].set(is_last_sorted)

        out[VALID_KEY] = valid

        def _apply_limit(v):
            rank = jnp.cumsum(v.astype(jnp.int32)) - 1
            lo = self.offset or 0
            keep = rank >= lo
            if self.limit is not None:
                keep = keep & (rank < lo + self.limit)
            return v & keep

        has_limit = self.limit is not None or self.offset is not None
        if self.order_by:
            # jnp.lexsort: last key is the primary sort key
            scalar_ov = out.pop("__overflow__", None)  # 0-d: not row-shaped
            keys = []
            for col, desc, is_str in reversed(self.order_by):
                # order-by may name a non-projected INPUT column (reference
                # `order by AGG_TIMESTAMP` without selecting it) — input
                # rows are index-aligned with the outputs
                k = out[col] if col in out else cols[col]
                if is_str and STR_RANK in cols:
                    # dictionary ids -> lexicographic ranks (nulls, id -1,
                    # wrap to the table's end and sort last among equals)
                    k = cols[STR_RANK][jnp.asarray(k, jnp.int32)]
                if k.dtype == jnp.bool_:
                    k = k.astype(jnp.int32)
                keys.append(-k if desc else k)
            keys.append(jnp.where(valid, 0, 1))  # valid rows first (primary)
            order = jnp.lexsort(keys)
            out = {k: v[order] for k, v in out.items()}
            valid = out[VALID_KEY]
            if scalar_ov is not None:
                out["__overflow__"] = scalar_ov

        # sort-then-limit, store queries included: QuerySelector always
        # orders the chunk before offset/limit (QuerySelector.java:192-198)
        if has_limit:
            out[VALID_KEY] = _apply_limit(valid)

        return state, out


def _lexsort(keys):
    order = jnp.argsort(keys[-1], stable=True)
    for k in reversed(keys[:-1]):
        order = order[jnp.argsort(k[order], stable=True)]
    return order


def plan_selector(
    selector: Selector,
    input_attrs: List[Tuple[str, AttrType]],
    resolver: Resolver,
    output_event_type: str,
    batch_mode: bool,
    dictionary,
    app_context=None,
    internal_names=frozenset(),
) -> SelectorPlan:
    specs: List[agg_ops.AggSpec] = []

    selections: List[Tuple[str, Expression]] = []
    if selector.select_all or not selector.selection_list:
        for name, _t in input_attrs:
            if name in internal_names:
                # synthetic planner internals (the `<cond> in Table`
                # exists-probe column, string-cast LUT columns) never reach
                # `select *` output — the reference's in-condition is a
                # plain filter expression
                continue
            selections.append((name, Variable(attribute_name=name)))
    else:
        for oa in selector.selection_list:
            selections.append((oa.name, oa.expression))

    from siddhi_tpu.ops.expressions import take_uuid_marker

    from siddhi_tpu.ops.expressions import take_object_elem_marker

    take_uuid_marker()  # clear any stale flag
    take_object_elem_marker()
    projections = []
    output_attrs: List[Tuple[str, AttrType]] = []
    uuid_cols: List[str] = []
    set_cols: List[Tuple[str, str]] = []
    object_meta: Dict[str, Optional[AttrType]] = {}
    object_multi: List[str] = []
    agg_positions: List[int] = []
    for name, expr in selections:
        n_specs = len(specs)
        rewritten = _rewrite_aggregators(expr, specs, resolver)
        if (len(specs) > n_specs and isinstance(rewritten, Variable)
                and rewritten.attribute_name.startswith("__agg")):
            # only TOP-LEVEL aggregator projections count — `sum(v)+0` is a
            # non-aggregate output to the snapshot-variant chooser
            # (WrappedSnapshotOutputRateLimiter.java:70 checks the outermost
            # executor's type)
            agg_positions.append(len(output_attrs))
        # synthetic agg columns resolve through the same resolver
        _augment_synthetic(resolver, specs)
        fn, t = compile_expr(rewritten, resolver)
        if take_uuid_marker():
            uuid_cols.append(name)  # host fills fresh UUIDs post-step
        if t == AttrType.OBJECT:
            # set-valued output: record element type (for decode) and the
            # source column (for '#set' companion pass-through)
            elem = take_object_elem_marker()     # createSet in this expr
            if isinstance(rewritten, Variable):
                src = resolver.resolve(rewritten).key
                for s in specs[n_specs:]:
                    if s.out_key == src and s.kind == "unionset":
                        elem = s.elem_type
                        object_multi.append(name)
                set_cols.append((name, src))
                if elem is None:
                    elem = _elem_type_of(resolver, rewritten)
                if name not in object_multi and _is_multi(resolver, rewritten):
                    object_multi.append(name)   # pass-through of a multi set
            object_meta[name] = elem
        projections.append((name, fn, t))
        output_attrs.append((name, t))

    having_fn = None
    out_resolver = OutputColsResolver(output_attrs, dictionary, fallback=resolver)
    if selector.having is not None:
        having = _rewrite_aggregators(selector.having, specs, resolver)
        _augment_synthetic(resolver, specs)
        having_fn = compile_condition(having, out_resolver)

    order_by = []
    for ob in selector.order_by_list:
        ref = out_resolver.resolve(ob.variable)
        # string keys are dictionary ids (arrival order) — sort them by
        # the lexicographic rank table the runtime injects per batch
        order_by.append((ref.key, ob.order == "desc",
                         ref.type == AttrType.STRING))

    current_on = output_event_type in ("current", "all")
    expired_on = output_event_type in ("expired", "all")

    if app_context is not None:
        for spec in specs:
            if spec.kind in ("distinctcount", "unionset"):
                spec.distinct_capacity = getattr(
                    app_context, "distinct_values_capacity", 64)

    return SelectorPlan(
        specs=specs,
        projections=projections,
        output_attrs=output_attrs,
        having_fn=having_fn,
        group_by=bool(selector.group_by_list),
        group_key_exprs=list(selector.group_by_list),
        current_on=current_on,
        expired_on=expired_on,
        batch_mode=batch_mode,
        order_by=order_by,
        limit=selector.limit,
        offset=selector.offset,
        uuid_cols=uuid_cols,
        set_cols=set_cols,
        object_meta=object_meta,
        object_multi=object_multi,
        agg_positions=agg_positions,
    )


def _augment_synthetic(resolver, specs):
    synthetic = getattr(resolver, "synthetic", None)
    if synthetic is not None:
        for s in specs:
            synthetic[s.out_key] = s.out_type
