"""Fan-out fusion planning: which sibling queries of one junction may fuse.

Multi-query sharing over a common scan is the classic fan-out
amortization (PAPERS.md: "On the Semantic Overlap of Operators in Stream
Processing Engines"); here the shared scan is the junction's packed
columnar batch and the shared computation is ONE ``jax.jit`` step
covering every sibling query (``core/query/fused_fanout.py``) — N
queries subscribed to one stream pay one device dispatch and one
``__meta__`` round trip per batch instead of N of each. This module
decides WHICH subscribers may join a fused group; everything else keeps
its own ``QueryRuntime`` delivery unchanged.

Eligibility (``fusion_ineligibility`` returns the reason for the first
miss, or None):

- a plain single-stream ``QueryRuntime`` — joins and patterns subscribe
  proxy receivers, never the runtime itself, so they are excluded by
  construction; the explicit type check also excludes their runtimes'
  subclasses defensively;
- not partitioned (per-key flows carry pk protocol the group does not);
- device-only: no host window, no host-side transform chain, no #log
  taps (all three run host stages per member between pack and step);
- no scheduler-driven window (time/timeBatch/... windows need their
  per-batch ``__notify__`` handled through their own timer re-entry);
- not already sharded over a mesh (``parallel/mesh.py`` owns that step;
  sharding an already-fused member releases it from its group).

Groups are formed from CONTIGUOUS runs of eligible receivers, so
delivery order relative to every other subscriber (stream callbacks,
sinks, aggregations) is exactly the unfused subscription order, and the
members of one group emit in their subscription order.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from siddhi_tpu.query_api.expressions import Variable


def fusion_ineligibility(q) -> Optional[str]:
    """Why ``q`` cannot join a fused fan-out group (None = eligible,
    else a ``core.eligibility.Reason`` — text + stable ``.code``)."""
    from siddhi_tpu.core.eligibility import ReasonCode as RC
    from siddhi_tpu.core.eligibility import reason
    from siddhi_tpu.core.query.join_runtime import JoinSideProxy
    from siddhi_tpu.core.query.runtime import QueryRuntime

    if isinstance(q, JoinSideProxy):
        # a device-engine join side is a pure (state, cols, now) member
        # like any other: its insert+probe folds into the junction's one
        # fused step (the proxy implements the member protocol and owns
        # its own eligibility rules)
        return q.fusion_ineligibility()
    if type(q) is not QueryRuntime:
        return reason(RC.NOT_PLAIN_RUNTIME,
                      f"not a plain single-stream runtime "
                      f"({type(q).__name__})")
    if q.partition_ctx is not None:
        return reason(RC.PARTITIONED, "partitioned")
    if q.host_window is not None:
        return reason(RC.HOST_WINDOW, "host-mode window")
    if q.host_transforms:
        return reason(RC.HOST_TRANSFORM, "host-side transform chain")
    if q.log_stages:
        return reason(RC.LOG_TAPS, "#log() host taps")
    if q.window_stage is not None and getattr(
            q.window_stage, "needs_scheduler", False):
        return reason(RC.SCHEDULER_WINDOW, "scheduler-driven window")
    if q._shard_mesh is not None:
        return reason(RC.SHARDED, "sharded over a mesh")
    return None


def keyer_signature(q) -> Optional[Tuple]:
    """Identity of a query's group-key computation, used to deduplicate
    ``GroupKeyer`` work inside a fused group (the common ``group by
    symbol`` fan-out runs the keyer ONCE for the whole group). Only plain
    attribute references are comparable; anything else returns None
    (= never share)."""
    if q.keyer is None:
        return ()
    sig = []
    for var in q.selector_plan.group_key_exprs:
        if type(var) is not Variable:
            return None
        sig.append((var.attribute_name, var.stream_id))
    return tuple(sig)


def plan_junction_groups(junction) -> List:
    """Group ONE junction's contiguous runs of eligible sibling queries
    into ``FusedFanoutRuntime``s (wired in place of the members in the
    junction's receiver list). Factored out of
    :func:`plan_fanout_groups` so the autopilot's fusion actuator can
    re-form groups per junction, on the delivering thread, at a batch
    boundary."""
    from siddhi_tpu.core.query.fused_fanout import FusedFanoutRuntime

    groups: List = []
    run: List = []

    def close_run():
        if len(run) >= 2:
            groups.append(FusedFanoutRuntime(junction, list(run)))
        run.clear()

    for r in list(junction.receivers):
        if fusion_ineligibility(r) is None:
            run.append(r)
        else:
            close_run()
    close_run()
    return groups


def plan_fanout_groups(app_runtime) -> List:
    """Group each junction's contiguous runs of eligible sibling queries
    into ``FusedFanoutRuntime``s. Returns the groups; respects the
    ``app_context.fuse_fanout`` opt-out knob."""
    groups: List = []
    if not getattr(app_runtime.app_context, "fuse_fanout", True):
        return groups
    for junction in app_runtime.junctions.values():
        groups.extend(plan_junction_groups(junction))
    return groups
