"""Query planner: query-api Query -> QueryRuntime with a jitted step.

The compile-time counterpart of reference ``util/parser/QueryParser.java:90``
+ ``SingleInputStreamParser.java:82-160`` (handler chain assembly) — but the
"chain" here is a fused device function, not linked processor objects.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from siddhi_tpu.core.context import SiddhiAppContext
from siddhi_tpu.core.plan.resolvers import SingleStreamResolver
from siddhi_tpu.core.plan.selector_plan import plan_selector
from siddhi_tpu.core.query.runtime import GroupKeyer, QueryRuntime
from siddhi_tpu.compiler.errors import SiddhiAppValidationException
from siddhi_tpu.ops.expressions import CompileError, compile_condition, compile_expr
from siddhi_tpu.query_api.definitions import StreamDefinition
from siddhi_tpu.query_api.execution import (
    EventTrigger,
    Filter,
    JoinInputStream,
    JoinType,
    Query,
    SingleInputStream,
    SnapshotOutputRate,
    StateInputStream,
    StreamFunction,
    Window,
)


def _plan_stream_function_handler(handler, resolver, query_name, filters,
                                  transforms, ext_def, base_def):
    """Plan one ``#name(args)`` handler (shared by the single-stream and
    join-side paths): returns ``(log_stage_or_None, ext_def)``. Transform
    stages are appended to ``transforms`` in place, their output attributes
    registered as resolver synthetics and folded into the (copy-on-write)
    extended definition."""
    from siddhi_tpu.ops.stream_functions import LogStage, plan_stream_function

    stage = plan_stream_function(
        handler, resolver, query_name, len(filters), len(transforms))
    if isinstance(stage, LogStage):
        return stage, ext_def
    taken = {a.name for a in ext_def.attributes}
    for a in stage.out_attrs:
        if a.name in taken:
            raise CompileError(
                f"stream function '{handler.name}' output attribute "
                f"'{a.name}' collides with an existing attribute")
        resolver.synthetic[a.name] = a.type
    if ext_def is base_def:
        ext_def = StreamDefinition(base_def.id, list(base_def.attributes))
    ext_def.attributes = ext_def.attributes + stage.out_attrs
    transforms.append(stage)
    return None, ext_def


def _rewrite_string_casts(expr, input_def, resolver, transforms, ext_state,
                          dictionary):
    """Replace ``cast/convert(<string attr>, '<numeric>')`` nodes with
    synthetic Variables backed by a host parse-LUT transform (strings are
    dictionary ids — parsing happens host-side once per new dictionary
    entry, the device sees a numeric column)."""
    from siddhi_tpu.query_api.definitions import AttrType
    from siddhi_tpu.query_api.expressions import (
        AttributeFunction,
        Constant,
        Expression,
        Variable,
    )

    if not isinstance(expr, Expression):
        return expr
    for attr in ("left", "right", "expression"):
        child = getattr(expr, attr, None)
        if isinstance(child, Expression):
            setattr(expr, attr, _rewrite_string_casts(
                child, input_def, resolver, transforms, ext_state, dictionary))
    if isinstance(expr, AttributeFunction):
        expr.parameters = [
            _rewrite_string_casts(p, input_def, resolver, transforms,
                                  ext_state, dictionary)
            for p in expr.parameters]
        from siddhi_tpu.ops.expressions import _TYPE_NAMES

        # every castable target except string (those go the other way)
        numeric = {k: v for k, v in _TYPE_NAMES.items()
                   if v != AttrType.STRING}
        if (not expr.namespace and expr.name.lower() in ("cast", "convert")
                and len(expr.parameters) == 2
                and isinstance(expr.parameters[1], Constant)
                and isinstance(expr.parameters[1].value, str)
                and isinstance(expr.parameters[0], Variable)):
            tname = expr.parameters[1].value.lower()
            var = expr.parameters[0]
            try:
                src = input_def.attribute(var.attribute_name)
            except Exception:
                return expr
            if not resolver.accepts_stream(var.stream_id):
                return expr
            stage = None
            if src.type == AttrType.STRING and tname in numeric:
                target = numeric[tname]
                key = (src.name, target)
                name = ext_state["casts"].get(key)
                if name is None:
                    from siddhi_tpu.ops.stream_functions import StringParseCastStage

                    name = f"__cast{len(ext_state['casts'])}__"
                    stage = StringParseCastStage(name, src.name, target,
                                                 dictionary)
                    resolver.synthetic[name] = target
            elif (src.type != AttrType.STRING and tname == "string"
                  and src.type != AttrType.OBJECT):
                key = (src.name, AttrType.STRING)
                name = ext_state["casts"].get(key)
                if name is None:
                    from siddhi_tpu.ops.stream_functions import (
                        NumericFormatCastStage,
                    )

                    name = f"__cast{len(ext_state['casts'])}__"
                    stage = NumericFormatCastStage(name, src.name, src.type,
                                                   dictionary)
                    resolver.synthetic[name] = AttrType.STRING
            else:
                return expr
            if stage is not None:
                ext_state["casts"][key] = name
                transforms.append(stage)
                ext_state["attrs"].extend(stage.out_attrs)
                ext_state.setdefault("internal", set()).add(name)
            return Variable(attribute_name=name)
    return expr


def _rewrite_in_conditions(expr, input_def, ref_id, resolver, app_context,
                           transforms, ext_state):
    """Replace ``<cond> in Table`` nodes with synthetic bool Variables
    backed by a host exists-probe over the table's contents
    (InConditionExpressionExecutor). The inner condition compiles with the
    table's own resolver/probe machinery (TableConditionResolver +
    InMemoryTable._match), sharing the join/update binding rules."""
    from siddhi_tpu.query_api.expressions import (
        AttributeFunction,
        Expression,
        InOp,
        Variable,
    )

    if not isinstance(expr, Expression):
        return expr
    for attr in ("left", "right", "expression"):
        child = getattr(expr, attr, None)
        if isinstance(child, Expression) and not isinstance(expr, InOp):
            setattr(expr, attr, _rewrite_in_conditions(
                child, input_def, ref_id, resolver, app_context,
                transforms, ext_state))
    if isinstance(expr, AttributeFunction):
        expr.parameters = [
            _rewrite_in_conditions(p, input_def, ref_id, resolver,
                                   app_context, transforms, ext_state)
            for p in expr.parameters]
    if isinstance(expr, InOp):
        from siddhi_tpu.core.table.in_memory_table import TableConditionResolver
        from siddhi_tpu.ops.stream_functions import InProbeStage
        from siddhi_tpu.query_api.definitions import AttrType

        table = getattr(app_context, "tables", {}).get(expr.source_id)
        if table is None:
            raise CompileError(
                f"'{expr.source_id}' in an `in` condition is not a defined table")
        pair = TableConditionResolver(
            table.definition, input_def, app_context.string_dictionary,
            event_ref=ref_id)
        cond = compile_condition(expr.expression, pair)
        name = f"__in{len(transforms)}__"
        stage = InProbeStage(name, table, cond)
        resolver.synthetic[name] = AttrType.BOOL
        transforms.append(stage)
        ext_state["attrs"].extend(stage.out_attrs)
        ext_state.setdefault("internal", set()).add(name)
        return Variable(attribute_name=name)
    return expr


def _selector_has_aggregator(selector) -> bool:
    """Does any selection/having expression call an attribute aggregator?
    (the detection ExpressionParser does via extension holders)."""
    from siddhi_tpu.ops.aggregators import supported_aggregators
    from siddhi_tpu.query_api.expressions import AttributeFunction, Expression

    names = supported_aggregators()

    def scan(expr) -> bool:
        if not isinstance(expr, Expression):
            return False
        if (isinstance(expr, AttributeFunction) and not expr.namespace
                and expr.name.lower() in names):
            return True
        for attr in ("left", "right", "expression"):
            child = getattr(expr, attr, None)
            if isinstance(child, Expression) and scan(child):
                return True
        if isinstance(expr, AttributeFunction):
            return any(scan(p) for p in expr.parameters)
        return False

    exprs = [oa.expression for oa in (selector.selection_list or [])]
    if selector.having is not None:
        exprs.append(selector.having)
    return any(scan(e) for e in exprs)


def _probe_type_safe(attr_t, val_t) -> bool:
    """An index probe casts the value into the COLUMN dtype; allow it only
    when that cast cannot change equality semantics vs the promoted
    broadcast compare (same type, or a widening numeric cast)."""
    from siddhi_tpu.ops import types as T

    if attr_t == val_t:
        return True
    if T.is_numeric(attr_t) and T.is_numeric(val_t):
        try:
            return T.promote(attr_t, val_t) == attr_t
        except Exception:
            return False
    return False


def _extract_join_index_probe(on_expr, left, right, resolver):
    """Detect ``T.attr == <expr over the opposite side>`` (possibly one
    conjunct of a top-level And) where T is an InMemoryTable join side
    with attr in ``probe_attrs()``. Returns a dict for
    JoinQueryRuntime.index_probe or None."""
    from siddhi_tpu.core.table.in_memory_table import InMemoryTable
    from siddhi_tpu.query_api.expressions import (
        And,
        AttributeFunction,
        Compare,
        Variable,
    )

    def vars_of(e, out):
        if isinstance(e, Variable):
            out.append(e)
        for name in ("left", "right", "expression"):
            c = getattr(e, name, None)
            if c is not None and not isinstance(c, (str, int, float, bool)):
                vars_of(c, out)
        if isinstance(e, AttributeFunction):
            for p in e.parameters:
                vars_of(p, out)
        return out

    def side_ids(s):
        return {s.stream_id, s.ref_id} - {None}

    def try_eq(e):
        if not isinstance(e, Compare) or e.operator != "==":
            return None
        for store_side in (left, right):
            store = store_side.store
            if not isinstance(store, InMemoryTable):
                continue
            other_side = right if store_side is left else left
            probe_attrs = store.probe_attrs()
            for tvar, vexpr in ((e.left, e.right), (e.right, e.left)):
                if not (isinstance(tvar, Variable)
                        and tvar.stream_id in side_ids(store_side)
                        and tvar.attribute_name in probe_attrs):
                    continue
                # the value expr must reference ONLY the other side
                vs = vars_of(vexpr, [])
                if not vs or any(
                        v.stream_id is None
                        or v.stream_id in side_ids(store_side) for v in vs):
                    continue
                if any(v.stream_id not in side_ids(other_side) for v in vs):
                    continue
                val_fn, val_t = compile_expr(vexpr, resolver)
                attr_t = store.definition.attribute(tvar.attribute_name).type
                if not _probe_type_safe(attr_t, val_t):
                    # casting the probe value into the column dtype would
                    # NARROW it (e.g. double -> long truncates), and the
                    # indexed path skips re-evaluating the equality — fall
                    # back to the broadcast compare
                    continue
                return {"store_side": store_side.key, "attr": tvar.attribute_name,
                        "val_fn": val_fn, "residual_fn": None}
        return None

    hit = try_eq(on_expr)
    if hit is not None:
        return hit
    if isinstance(on_expr, And):
        for this, rest in ((on_expr.left, on_expr.right),
                           (on_expr.right, on_expr.left)):
            hit = try_eq(this)
            if hit is not None:
                hit["residual_fn"] = compile_condition(rest, resolver)
                return hit
    return None


def plan_join_query(
    query: Query,
    query_name: str,
    app_context: SiddhiAppContext,
    definitions: Dict[str, StreamDefinition],
    partition_ctx=None,
):
    """Plan a two-stream window join (reference
    ``JoinInputStreamParser.java:200-348`` + ``JoinProcessor.java``)."""
    from siddhi_tpu.core.query.join_runtime import (
        AggregationJoinStore,
        JoinQueryRuntime,
        JoinResolver,
        JoinSide,
    )
    from siddhi_tpu.ops.windows import PassthroughWindowStage, create_window_stage

    join: JoinInputStream = query.input_stream
    dictionary = app_context.string_dictionary
    # outputExpectsExpiredEvents (JoinInputStreamParser): `insert into`
    # joins never drain batch windows' findable queues, so probes keep
    # seeing the last non-empty batch across empty timer flushes
    _oet = (query.output_stream.output_event_type
            if query.output_stream else "current")
    side_expired_needed = _oet != "current"
    # EmptyWindowProcessor semantics (per-event [CURRENT, EXPIRED?, RESET])
    # only matter when the selector aggregates or groups — the RESET rows
    # exist solely to restart per-trigger aggregate state, and a RESET from
    # a NON-triggering side would wrongly wipe it, so plain passthrough is
    # kept for non-triggering or non-aggregating cases
    _needs_reset = bool(query.selector.group_by_list) or _selector_has_aggregator(
        query.selector)

    def _side_triggers(key: str) -> bool:
        return (join.trigger == EventTrigger.ALL
                or (join.trigger == EventTrigger.LEFT and key == "left")
                or (join.trigger == EventTrigger.RIGHT and key == "right"))

    def build_side(key: str, s: SingleInputStream) -> JoinSide:
        sid = s.unique_stream_id
        tables = getattr(app_context, "tables", {})
        named_windows = getattr(app_context, "named_windows", {})
        aggregations = getattr(app_context, "aggregations", {})
        if sid in aggregations:
            # aggregation join side: stitched buckets as the probe store
            # (AggregationRuntime.java:331-357 + join `within ... per ...`)
            agg = aggregations[sid]
            if s.handlers:
                raise CompileError(
                    f"query '{query_name}': handlers on the aggregation join "
                    f"side '{sid}' are not supported")
            duration, within, dyn = _agg_join_range(join, query_name)
            store = AggregationJoinStore(agg, duration, within)
            store.dynamic_raw = dyn
            return JoinSide(
                key=key, stream_id=sid, ref_id=s.stream_reference_id,
                definition=store.definition, window_stage=None, filters=[],
                triggers=False, outer=False, store=store,
            )
        if sid in tables or sid in named_windows:
            # shared store side (reference TableWindowProcessor /
            # WindowWindowProcessor as the findable join side); named
            # windows also trigger with their emission stream, tables can't
            store = tables.get(sid) or named_windows[sid]
            sdef = store.definition
            if s.handlers:
                raise CompileError(
                    f"query '{query_name}': handlers on the {sid} store join "
                    f"side are not supported"
                )
            is_window = sid in named_windows
            stage = None
            if is_window:
                from siddhi_tpu.ops.windows import (
                    PassthroughWindowStage as _PT,
                    window_col_specs as _wcs,
                )

                stage = _PT(_wcs(sdef), pass_expired=True)
            triggers = is_window and (
                join.trigger == EventTrigger.ALL
                or (join.trigger == EventTrigger.LEFT and key == "left")
                or (join.trigger == EventTrigger.RIGHT and key == "right")
            )
            return JoinSide(
                key=key, stream_id=sid, ref_id=s.stream_reference_id,
                definition=sdef, window_stage=stage, filters=[],
                triggers=triggers, outer=False, store=store,
            )
        if sid not in definitions:
            raise CompileError(f"query '{query_name}': stream '{sid}' is not defined")
        sdef = definitions[sid]
        resolver = SingleStreamResolver(sdef, dictionary, ref_id=s.stream_reference_id)
        # inside a partition EVERY join side keeps per-key window state —
        # including a GLOBAL (non-partitioned) stream side: the reference
        # instantiates the whole query per key, so each instance holds its
        # OWN copy of the global stream's window, fed only with events
        # that arrived while the instance existed (JoinPartitionTestCase
        # test10: a late-created instance's twitter window starts empty).
        # Global-side ingestion broadcasts each event into every ACTIVE
        # key (join_runtime.process_side_batch).
        side_keyed = partition_ctx is not None
        side_global = partition_ctx is not None and not (
            s.is_inner_stream
            or sid in partition_ctx.keyers
            or sid in getattr(partition_ctx, "local_streams", ()))
        filters = []
        post_filters = []
        window_stage = None
        host_window = None
        transforms = []
        ext_sdef = sdef  # grows as stream functions append attributes
        for h in s.handlers:
            if isinstance(h, Filter):
                if window_stage is not None:
                    post_filters.append(compile_condition(h.expression, resolver))
                else:
                    filters.append(compile_condition(h.expression, resolver))
            elif isinstance(h, Window):
                if window_stage is not None:
                    raise CompileError("only one #window per join side is allowed")
                if side_keyed:
                    from siddhi_tpu.ops.keyed_windows import create_keyed_window_stage

                    window_stage = create_keyed_window_stage(
                        h, ext_sdef, resolver, app_context,
                        expired_needed=side_expired_needed)
                    if not getattr(window_stage, "keyed", False):
                        raise CompileError(
                            f"window '{h.name}' cannot be a join side inside "
                            f"a partition (no per-key probe surface)")
                else:
                    window_stage = create_window_stage(
                        h, ext_sdef, resolver, app_context,
                        expired_needed=side_expired_needed)
                if getattr(window_stage, "host_mode", False):
                    # sort/frequent/... run host-side; emissions trigger the
                    # join, contents() is the probe surface
                    host_window = window_stage
                    from siddhi_tpu.ops.windows import window_col_specs

                    window_stage = PassthroughWindowStage(
                        window_col_specs(ext_sdef), pass_expired=True)
            else:
                if window_stage is not None:
                    raise CompileError(
                        "post-window stream functions on join sides are not supported")
                log_stage, ext_sdef = _plan_stream_function_handler(
                    h, resolver, query_name, filters, transforms, ext_sdef, sdef)
                if log_stage is not None:
                    raise CompileError("#log() on a join side is not supported")
        if window_stage is None:
            if partition_ctx is not None:
                raise CompileError(
                    f"query '{query_name}': joins inside partitions need an "
                    f"explicit #window on stream side '{sid}'")
            from siddhi_tpu.ops.windows import window_col_specs

            window_stage = PassthroughWindowStage(
                window_col_specs(ext_sdef),
                empty_window=(_needs_reset or side_expired_needed)
                and _side_triggers(key),
                expired_needed=side_expired_needed,
                emit_reset=_needs_reset)
        keyer = None
        if partition_ctx is not None and sid in partition_ctx.keyers:
            keyer = partition_ctx.keyers[sid]
        triggers = (
            join.trigger == EventTrigger.ALL
            or (join.trigger == EventTrigger.LEFT and key == "left")
            or (join.trigger == EventTrigger.RIGHT and key == "right")
        )
        outer = (
            (join.type == JoinType.LEFT_OUTER_JOIN and key == "left")
            or (join.type == JoinType.RIGHT_OUTER_JOIN and key == "right")
            or join.type == JoinType.FULL_OUTER_JOIN
        )
        return JoinSide(
            key=key,
            stream_id=sdef.id,
            ref_id=s.stream_reference_id,
            definition=ext_sdef,
            window_stage=window_stage,
            filters=filters,
            triggers=triggers,
            outer=outer,
            host_window=host_window,
            keyer=keyer,
            transforms=transforms,
            input_definition=sdef if ext_sdef is not sdef else None,
            post_filters=post_filters,
            global_side=side_global,
            carried_pk=partition_ctx is not None and (
                s.is_inner_stream
                or sid in getattr(partition_ctx, "local_streams", ())),
        )

    left = build_side("left", join.left)
    right = build_side("right", join.right)
    for sd in (left, right):
        if getattr(sd, "global_side", False) and sd.outer:
            raise CompileError(
                f"query '{query_name}': outer join on the non-partitioned "
                f"side '{sd.stream_id}' inside a partition is not supported")
    if (join.within is not None or join.per is not None) and not any(
        isinstance(s.store, AggregationJoinStore) for s in (left, right)
    ):
        raise CompileError(
            f"query '{query_name}': `within`/`per` join clauses need an "
            f"aggregation join side")
    if left.window_stage is None and right.window_stage is None:
        raise CompileError(
            f"query '{query_name}': a join needs an event-driven side — both "
            f"'{left.stream_id}' and '{right.stream_id}' are tables"
        )
    if not (left.triggers or right.triggers):
        # e.g. `unidirectional` pointing at a table side: compiles in the
        # reference only because tables can't trigger there either — here we
        # reject instead of building a query that can never emit
        raise CompileError(
            f"query '{query_name}': no join side can trigger output — the "
            f"unidirectional/trigger side must be a stream or named window"
        )
    for _sd, _ot in ((left, right), (right, left)):
        if (isinstance(_sd.store, AggregationJoinStore)
                and getattr(_sd.store, "dynamic_raw", None)):
            _compile_dynamic_agg_range(_sd.store, _ot, dictionary)
    resolver = JoinResolver(left, right, dictionary)

    on_cond = None
    if join.on_compare is not None:
        on_cond = compile_condition(join.on_compare, resolver)

    # @index/@primaryKey equality probe: `on T.attr == <expr over the
    # other side>` against an indexed table side compiles to a device
    # searchsorted over the sorted probe column instead of the [N, W]
    # broadcast compare (the reference's IndexedEventHolder probe,
    # OverwriteTableIndexOperator/CollectionExecutor path)
    index_probe = None
    if join.on_compare is not None and partition_ctx is None:
        index_probe = _extract_join_index_probe(
            join.on_compare, left, right, resolver)

    if query.selector.select_all or not query.selector.selection_list:
        raise CompileError(
            f"query '{query_name}': join queries need an explicit select list"
        )

    output_event_type = query.output_stream.output_event_type if query.output_stream else "current"
    # every reference chunk is batch-processed by QuerySelector (isBatch()
    # is hardwired true, ComplexEventChunk.java:267); JoinProcessor builds
    # one chunk per trigger event, so grouped/aggregated joins collapse to
    # the last row per (trigger event, group) — JoinTableTestCase query9.
    # The join step stamps FLUSH_KEY with the trigger row index.
    selector_plan = plan_selector(
        selector=query.selector,
        input_attrs=[],
        resolver=resolver,
        output_event_type=output_event_type,
        batch_mode=True,
        dictionary=dictionary,
        app_context=app_context,
    )
    selector_plan.num_keys = app_context.initial_key_capacity

    group_keyer = None
    if query.selector.group_by_list:
        fns = []
        for var in query.selector.group_by_list:
            fn, t = compile_expr(var, resolver)
            fns.append((fn, t))
        group_keyer = GroupKeyer(fns)

    rt = JoinQueryRuntime(
        name=query_name,
        app_context=app_context,
        left=left,
        right=right,
        on_cond=on_cond,
        selector_plan=selector_plan,
        dictionary=dictionary,
        partition_ctx=partition_ctx,
        group_keyer=group_keyer,
    )
    rt.index_probe = index_probe
    # classify + attach the device join engine (core/join/): eligible
    # stream-stream window joins get the PanJoin-style partitioned probe
    # engine (pipeline/fusion-eligible); everything else keeps the legacy
    # probe path with the reason recorded on the runtime
    from siddhi_tpu.core.join import attach_join_engine

    attach_join_engine(rt, join.on_compare)
    return rt


def _agg_join_range(join: JoinInputStream, query_name: str):
    """Parse `within .. per ..` of an aggregation join into (Duration | None,
    (start, end) | None, dynamic_raw | None). Constants (unix-ms longs,
    'yyyy-MM-dd HH:mm:ss' strings, single wildcard patterns) resolve at
    plan time; expressions over the stream side (``per i.perValue``) are
    returned raw for per-event resolution (reference AggregationRuntime's
    startTimeEndTime/per executors run per matching event)."""
    from siddhi_tpu.core.aggregation.incremental import parse_duration_name
    from siddhi_tpu.core.aggregation.within_time import (
        WithinFormatError, resolve_within_pair, single_within_range)
    from siddhi_tpu.query_api.expressions import Constant, TimeConstant

    dynamic: dict = {}
    if join.per is None:
        raise CompileError(
            f"query '{query_name}': an aggregation join needs `per '<duration>'`")
    if isinstance(join.per, Constant) and isinstance(join.per.value, str):
        duration = parse_duration_name(join.per.value)
    else:
        duration = None
        dynamic["per"] = join.per

    def _const(x):
        return x.value if isinstance(x, (Constant, TimeConstant)) else None

    w = join.within
    within = None
    try:
        if w is None:
            pass
        elif isinstance(w, tuple):
            a, b = _const(w[0]), _const(w[1])
            if a is None or b is None:
                dynamic["within"] = w
            else:
                within = resolve_within_pair(a, b)
        elif isinstance(w, Constant) and isinstance(w.value, str):
            # single wildcard pattern: the whole calendar unit it names
            within = single_within_range(w.value)
        elif isinstance(w, (Constant, TimeConstant)):
            # single-bound within must be a date-pattern STRING (reference
            # startTimeEndTime single-arg validation — test36)
            raise CompileError(
                f"query '{query_name}': a single within bound must be a "
                f"date-pattern string ('**' wildcards allowed)")
        else:
            dynamic["within"] = (w,)
    except WithinFormatError as e:
        raise CompileError(f"query '{query_name}': {e}") from None
    return duration, within, (dynamic or None)


def _compile_dynamic_agg_range(store, stream_side, dictionary):
    """Compile per-event `within`/`per` expressions of an aggregation join
    against the STREAM side's row columns; the store resolves them per
    trigger event at probe time (reference AggregationRuntime per-event
    startTimeEndTime/per executors — Aggregation1TestCase test6's
    ``within i.startTime, i.endTime per i.perValue``). The compiled
    closures return RAW per-row values (strings decoded from the
    dictionary); parsing happens per row in the store so one bad row
    can't void a whole batch."""
    from siddhi_tpu.ops.expressions import VALID_KEY, compile_expr
    from siddhi_tpu.query_api.definitions import AttrType

    resolver = SingleStreamResolver(
        stream_side.definition, dictionary, ref_id=stream_side.ref_id)

    def host_values(expr):
        fn, t = compile_expr(expr, resolver)
        is_str = t == AttrType.STRING

        def values(cols, ctx):
            v, _m = fn(cols, ctx)
            # constant sub-expressions compile to 0-d scalars — broadcast
            # against the batch before iterating per row
            v = np.broadcast_to(np.asarray(v), np.shape(cols[VALID_KEY]))
            if is_str:
                return [dictionary.decode(int(i)) for i in v]
            return [int(x) for x in v]

        return values, t

    raw = store.dynamic_raw
    per_of = None
    if raw.get("per") is not None:
        per_of, _t = host_values(raw["per"])
    within_of = None
    w = raw.get("within")
    if w is not None:
        if isinstance(w, tuple) and len(w) == 2:
            (b0, _t0), (b1, _t1) = host_values(w[0]), host_values(w[1])

            def within_of(cols, ctx):
                return list(zip(b0(cols, ctx), b1(cols, ctx)))
        else:
            bv, t = host_values(w[0] if isinstance(w, tuple) else w)
            if t != AttrType.STRING:
                # same single-bound rule as the static path: must be a
                # date-pattern string (startTimeEndTime single-arg)
                raise CompileError(
                    "a single within bound must be a date-pattern string "
                    "('**' wildcards allowed)")

            def within_of(cols, ctx):
                return bv(cols, ctx)
    store.dynamic = (per_of, within_of)


def plan_nfa_query(
    query: Query,
    query_name: str,
    app_context: SiddhiAppContext,
    definitions: Dict[str, StreamDefinition],
    partition_ctx=None,
):
    """Plan a pattern/sequence query: linearized NFA plan + compiled side
    filters + selector over capture columns (reference
    ``StateInputStreamParser.java:76-210`` + ``SelectorParser``)."""
    from siddhi_tpu.core.query.nfa_runtime import NFAQueryRuntime
    from siddhi_tpu.ops.expressions import compile_condition
    from siddhi_tpu.ops.nfa import (
        NFAOutputResolver,
        NFASideResolver,
        NFAStage,
        assign_indexed_captures,
        build_nfa_plan,
    )

    state_stream: StateInputStream = query.input_stream
    dictionary = app_context.string_dictionary
    plan = build_nfa_plan(state_stream, definitions, app_context.nfa_slots)

    if query.selector.select_all or not query.selector.selection_list:
        # `select *` on a pattern expands to every attribute of every
        # pattern element in order (reference SelectorParser over the
        # MetaStateEvent) — sides without captures (pure absent steps)
        # project null columns. Duplicate names reject, as the reference's
        # output-definition validation would.
        from siddhi_tpu.query_api.execution import OutputAttribute
        from siddhi_tpu.query_api.expressions import Constant, Variable

        seen_refs = {}
        for st in plan.steps:
            for side in st.sides:
                if side.capture is not None and side.capture.ref_id:
                    key = side.capture.ref_id      # one entry per ref
                else:
                    # capture-less (absent) elements are distinct per
                    # STEP: two `not A` elements must both expand (and
                    # then hit the duplicate-name rejection below, as the
                    # reference's output-definition validation would)
                    key = (st.index, side.stream_id)
                seen_refs.setdefault(key, (side.stream_id,
                                           side.capture is not None))
        selection = []
        names = set()
        for ref, (sid, has_cap) in seen_refs.items():
            for attr in definitions[sid].attributes:
                if attr.name in names:
                    raise CompileError(
                        f"query '{query_name}': select * is ambiguous — "
                        f"attribute '{attr.name}' appears in more than one "
                        f"pattern element; use an explicit select list")
                names.add(attr.name)
                # capture-less elements (pure absent steps) project null
                expr = (Variable(attribute_name=attr.name, stream_id=ref)
                        if has_cap else Constant(value=None, type=attr.type))
                selection.append(OutputAttribute(rename=attr.name,
                                                 expression=expr))
        query.selector.selection_list = selection
        query.selector.select_all = False

    # size indexed capture storage (e1[i].attr) from every expression that
    # can reference captures: side filters, selections, having
    idx_exprs = [e for st in plan.steps for side in st.sides for e in side.filter_exprs]
    idx_exprs += [oa.expression for oa in query.selector.selection_list]
    if query.selector.having is not None:
        idx_exprs.append(query.selector.having)
    idx_exprs += list(query.selector.group_by_list)
    assign_indexed_captures(plan, idx_exprs)

    for st in plan.steps:
        for side in st.sides:
            if side.filter_exprs:
                resolver = NFASideResolver(side, plan, dictionary)
                conds = [compile_condition(e, resolver) for e in side.filter_exprs]

                def combined(ev, ctx, _conds=conds):
                    r = _conds[0](ev, ctx)
                    for c in _conds[1:]:
                        r = r & c(ev, ctx)
                    return r

                side.cond = combined

    out_resolver = NFAOutputResolver(plan, dictionary)
    output_event_type = query.output_stream.output_event_type if query.output_stream else "current"
    selector_plan = plan_selector(
        selector=query.selector,
        input_attrs=[],
        resolver=out_resolver,
        output_event_type=output_event_type,
        batch_mode=False,
        dictionary=dictionary,
        app_context=app_context,
    )
    selector_plan.num_keys = app_context.initial_key_capacity

    stream_keyers = {}
    if partition_ctx is not None:
        for sid in plan.stream_ids:
            if sid not in partition_ctx.keyers:
                raise CompileError(
                    f"query '{query_name}': pattern stream '{sid}' is consumed "
                    f"inside a partition but has no partition-with clause"
                )
            stream_keyers[sid] = partition_ctx.keyers[sid]

    # group-by over capture columns: a host keyer runs between the NFA
    # emission and the selector step (GroupByKeyGenerator.java:37)
    out_keyer = None
    if query.selector.group_by_list:
        fns = []
        for var in query.selector.group_by_list:
            fn, t = compile_expr(var, out_resolver)
            fns.append((fn, t))
        out_keyer = GroupKeyer(fns)

    return NFAQueryRuntime(
        name=query_name,
        app_context=app_context,
        stage=NFAStage(plan),
        input_defs={sid: definitions[sid] for sid in plan.stream_ids},
        stream_keyers=stream_keyers,
        selector_plan=selector_plan,
        dictionary=dictionary,
        partition_ctx=partition_ctx,
        out_keyer=out_keyer,
    )


def plan_query(
    query: Query,
    query_name: str,
    app_context: SiddhiAppContext,
    definitions: Dict[str, StreamDefinition],
    partition_ctx=None,
) -> QueryRuntime:
    input_stream = query.input_stream
    if isinstance(query.output_rate, SnapshotOutputRate):
        # snapshot rate limiting requires `insert all events` on EVERY query
        # shape — single stream, join, pattern (QueryParser.java:120-128)
        oet = (query.output_stream.output_event_type
               if query.output_stream else "current")
        if oet != "all":
            raise SiddhiAppValidationException(
                "As the query is performing snapshot rate limiting, it can "
                "only insert 'ALL_EVENTS' but it is inserting "
                f"'{oet.upper()}_EVENTS'!")
    if isinstance(input_stream, StateInputStream):
        return plan_nfa_query(query, query_name, app_context, definitions, partition_ctx)
    if isinstance(input_stream, JoinInputStream):
        return plan_join_query(query, query_name, app_context, definitions, partition_ctx)
    if not isinstance(input_stream, SingleInputStream):
        raise CompileError(
            f"query '{query_name}': unsupported input stream "
            f"{type(input_stream).__name__}"
        )
    stream_id = input_stream.unique_stream_id
    if stream_id not in definitions:
        raise CompileError(f"query '{query_name}': stream '{stream_id}' is not defined")
    input_def = definitions[stream_id]
    dictionary = app_context.string_dictionary
    resolver = SingleStreamResolver(
        input_def, dictionary, ref_id=input_stream.stream_reference_id, synthetic={}
    )

    partition_keyer = None
    carried_pk = False
    if partition_ctx is not None:
        if input_stream.is_inner_stream:
            carried_pk = True  # '#stream' rows carry their pk id
        elif stream_id in partition_ctx.keyers:
            partition_keyer = partition_ctx.keyers[stream_id]
        elif stream_id in getattr(partition_ctx, "local_streams", ()):
            # produced by a query in the SAME partition: its events carry
            # the producing instance's pk (reference partition flow ids)
            carried_pk = True
        else:
            raise CompileError(
                f"query '{query_name}': stream '{stream_id}' is consumed inside a "
                f"partition but has no partition-with clause and is not an inner stream"
            )

    filters = []
    post_filters = []   # after the window: mask emitted rows (FilterProcessor downstream of a WindowProcessor)
    post_pipeline = []  # ordered post-window stages: ("f", cond) | ("t", transform)
    window_stage = None
    host_window = None
    batch_mode = False
    transforms = []
    log_stages = []
    ext_def = input_def  # grows as stream functions append attributes

    # string -> numeric casts become host parse-LUT transforms feeding the
    # device a synthetic numeric column (rewrites filter + selector ASTs)
    cast_state = {"casts": {}, "attrs": []}
    seen_window = False
    for handler in input_stream.handlers:
        if isinstance(handler, Window):
            seen_window = True
        if isinstance(handler, Filter):
            handler.expression = _rewrite_string_casts(
                handler.expression, input_def, resolver, transforms,
                cast_state, dictionary)
            if not seen_window:
                # post-window `in` probes would bake ingestion-time table
                # state into buffered rows — unsupported (compile_expr
                # raises a clear error if one survives here)
                handler.expression = _rewrite_in_conditions(
                    handler.expression, input_def,
                    input_stream.stream_reference_id, resolver, app_context,
                    transforms, cast_state)
    if query.selector is not None:
        for sel in getattr(query.selector, "selection_list", []) or []:
            sel.expression = _rewrite_string_casts(
                sel.expression, input_def, resolver, transforms,
                cast_state, dictionary)
        if query.selector.having is not None:
            query.selector.having = _rewrite_string_casts(
                query.selector.having, input_def, resolver,
                transforms, cast_state, dictionary)
    if cast_state["attrs"]:
        ext_def = StreamDefinition(input_def.id, list(input_def.attributes))
        ext_def.attributes = ext_def.attributes + cast_state["attrs"]

    for handler in input_stream.handlers:
        if isinstance(handler, Filter):
            if window_stage is not None or host_window is not None:
                f = compile_condition(handler.expression, resolver)
                post_filters.append(f)
                post_pipeline.append(("f", f))
            else:
                filters.append(compile_condition(handler.expression, resolver))
        elif isinstance(handler, Window):
            if window_stage is not None or host_window is not None:
                raise CompileError("only one #window per stream is allowed")
            if partition_ctx is not None:
                from siddhi_tpu.ops.keyed_windows import create_keyed_window_stage

                window_stage = create_keyed_window_stage(handler, ext_def, resolver, app_context)
            else:
                from siddhi_tpu.ops.windows import create_window_stage  # cycle-free

                window_stage = create_window_stage(handler, ext_def, resolver, app_context)
            batch_mode = window_stage.batch_mode
            if getattr(window_stage, "host_mode", False):
                host_window = window_stage
                window_stage = None
        elif isinstance(handler, StreamFunction):
            if window_stage is not None or host_window is not None:
                # post-window stream functions transform the window's
                # EMITTED rows (their outputs are not buffered)
                post_transforms = []
                log_stage, ext_def = _plan_stream_function_handler(
                    handler, resolver, query_name, filters, post_transforms,
                    ext_def, input_def)
                if log_stage is not None:
                    raise CompileError(
                        "#log() after a window is not supported")
                post_pipeline.extend(("t", t) for t in post_transforms)
            else:
                log_stage, ext_def = _plan_stream_function_handler(
                    handler, resolver, query_name, filters, transforms,
                    ext_def, input_def)
                if log_stage is not None:
                    log_stages.append(log_stage)

    if (window_stage is None and host_window is None
            and stream_id in getattr(app_context, "named_windows", {})):
        # a consumer of a BATCH-type named window receives its flush chunks:
        # the selector collapses aggregates per chunk exactly like reading
        # the batch window directly (CustomJoinWindowTestCase
        # testMultipleStreamsToWindow: one output per lengthBatch flush)
        w = app_context.named_windows[stream_id]
        batch_mode = bool(getattr(w.stage, "batch_mode", False))

    output_event_type = query.output_stream.output_event_type if query.output_stream else "current"
    if isinstance(query.output_rate, SnapshotOutputRate):
        # snapshot rate limiting disables the selector's batch collapse
        # (QueryParser.java:221-223; `insert all events` is validated at
        # the plan_query entry for every query shape)
        batch_mode = False
    selector_plan = plan_selector(
        selector=query.selector,
        input_attrs=[(a.name, a.type) for a in ext_def.attributes],
        resolver=resolver,
        output_event_type=output_event_type,
        batch_mode=batch_mode,
        dictionary=dictionary,
        app_context=app_context,
        internal_names=cast_state.get("internal", frozenset()),
    )
    selector_plan.num_keys = app_context.initial_key_capacity

    keyer = None
    # host-only stages (parse-LUT casts, table exists-probes) force the
    # whole transform chain host-side (stream-function transforms handle
    # xp=np equally)
    host_transforms = bool(cast_state["casts"]) or any(
        getattr(t, "host_only", False) for t in transforms)
    if selector_plan.group_by:
        fns = []
        for var in query.selector.group_by_list:
            fn, t = compile_expr(var, resolver)
            fns.append((fn, t))
            # group key on a stream-function output: the host keyer needs
            # the synthetic columns, so transforms must run host-side
            if getattr(var, "attribute_name", None) in resolver.synthetic:
                host_transforms = True
        keyer = GroupKeyer(fns)

    # fuse window eviction into invertible aggregator deltas when the query
    # shape qualifies (plain stream input, CURRENT-only output) — the hot
    # path for windowed aggregation (see ops/fused_agg.py)
    if (
        window_stage is not None
        and not post_pipeline  # fused stages never materialize emitted rows
        and partition_ctx is None
        and getattr(app_context, "enable_fusion", True)
        and stream_id not in getattr(app_context, "named_windows", {})
    ):
        from siddhi_tpu.ops.fused_agg import plan_fused_window
        from siddhi_tpu.ops.windows import LengthWindowStage

        if isinstance(window_stage, LengthWindowStage):
            fused = plan_fused_window(
                "length", [window_stage.length], selector_plan, app_context)
            if fused is not None:
                window_stage = fused

    runtime = QueryRuntime(
        name=query_name,
        app_context=app_context,
        input_definition=input_def,
        filters=filters,
        window_stage=window_stage,
        selector_plan=selector_plan,
        keyer=keyer,
        dictionary=dictionary,
        partition_ctx=partition_ctx,
        partition_keyer=partition_keyer,
        carried_pk=carried_pk,
        transforms=transforms,
        log_stages=log_stages,
        post_filters=post_filters,
        post_pipeline=post_pipeline,
    )
    runtime.host_transforms = host_transforms
    runtime.host_window = host_window
    return runtime
