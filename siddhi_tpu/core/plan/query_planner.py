"""Query planner: query-api Query -> QueryRuntime with a jitted step.

The compile-time counterpart of reference ``util/parser/QueryParser.java:90``
+ ``SingleInputStreamParser.java:82-160`` (handler chain assembly) — but the
"chain" here is a fused device function, not linked processor objects.
"""

from __future__ import annotations

from typing import Dict

from siddhi_tpu.core.context import SiddhiAppContext
from siddhi_tpu.core.plan.resolvers import SingleStreamResolver
from siddhi_tpu.core.plan.selector_plan import plan_selector
from siddhi_tpu.core.query.runtime import GroupKeyer, QueryRuntime
from siddhi_tpu.ops.expressions import CompileError, compile_condition, compile_expr
from siddhi_tpu.query_api.definitions import StreamDefinition
from siddhi_tpu.query_api.execution import (
    Filter,
    Query,
    SingleInputStream,
    StreamFunction,
    Window,
)


def plan_query(
    query: Query,
    query_name: str,
    app_context: SiddhiAppContext,
    definitions: Dict[str, StreamDefinition],
    partition_ctx=None,
) -> QueryRuntime:
    input_stream = query.input_stream
    if not isinstance(input_stream, SingleInputStream):
        raise CompileError(
            f"query '{query_name}': join/pattern/sequence planning lands in M4/M5 "
            f"(got {type(input_stream).__name__})"
        )
    stream_id = input_stream.unique_stream_id
    if stream_id not in definitions:
        raise CompileError(f"query '{query_name}': stream '{stream_id}' is not defined")
    input_def = definitions[stream_id]
    dictionary = app_context.string_dictionary
    resolver = SingleStreamResolver(
        input_def, dictionary, ref_id=input_stream.stream_reference_id, synthetic={}
    )

    partition_keyer = None
    carried_pk = False
    if partition_ctx is not None:
        if input_stream.is_inner_stream:
            carried_pk = True  # '#stream' rows carry their pk id
        elif stream_id in partition_ctx.keyers:
            partition_keyer = partition_ctx.keyers[stream_id]
        else:
            raise CompileError(
                f"query '{query_name}': stream '{stream_id}' is consumed inside a "
                f"partition but has no partition-with clause and is not an inner stream"
            )

    filters = []
    window_stage = None
    batch_mode = False
    for handler in input_stream.handlers:
        if isinstance(handler, Filter):
            if window_stage is not None:
                raise CompileError("post-window filters land with window support (M2)")
            filters.append(compile_condition(handler.expression, resolver))
        elif isinstance(handler, Window):
            if window_stage is not None:
                raise CompileError("only one #window per stream is allowed")
            if partition_ctx is not None:
                from siddhi_tpu.ops.keyed_windows import create_keyed_window_stage

                window_stage = create_keyed_window_stage(handler, input_def, resolver, app_context)
            else:
                from siddhi_tpu.ops.windows import create_window_stage  # cycle-free

                window_stage = create_window_stage(handler, input_def, resolver, app_context)
            batch_mode = window_stage.batch_mode
        elif isinstance(handler, StreamFunction):
            raise CompileError(f"stream function '{handler.name}' not yet implemented")

    output_event_type = query.output_stream.output_event_type if query.output_stream else "current"
    selector_plan = plan_selector(
        selector=query.selector,
        input_attrs=[(a.name, a.type) for a in input_def.attributes],
        resolver=resolver,
        output_event_type=output_event_type,
        batch_mode=batch_mode,
        dictionary=dictionary,
    )
    selector_plan.num_keys = app_context.initial_key_capacity

    keyer = None
    if selector_plan.group_by:
        fns = []
        for var in query.selector.group_by_list:
            fn, t = compile_expr(var, resolver)
            fns.append((fn, t))
        keyer = GroupKeyer(fns)

    runtime = QueryRuntime(
        name=query_name,
        app_context=app_context,
        input_definition=input_def,
        filters=filters,
        window_stage=window_stage,
        selector_plan=selector_plan,
        keyer=keyer,
        dictionary=dictionary,
        partition_ctx=partition_ctx,
        partition_keyer=partition_keyer,
        carried_pk=carried_pk,
    )
    return runtime
