"""SiddhiManager: top-level API — app registry + shared context.

Mirror of reference ``core/SiddhiManager.java:49`` (createSiddhiAppRuntime
:80-96, setExtension:213, persistence-store injection:167, shutdown:270-300).
"""

from __future__ import annotations

from typing import Dict, Optional, Union

from siddhi_tpu.compiler import SiddhiCompiler
from siddhi_tpu.core.app_runtime import SiddhiAppRuntime
from siddhi_tpu.core.context import SiddhiContext
from siddhi_tpu.query_api.siddhi_app import SiddhiApp


def _strip_transports(app: SiddhiApp) -> SiddhiApp:
    """Sandbox filter (reference ``SiddhiManager.
    removeSourceSinkAndStoreAnnotations``): drop every @source/@sink whose
    type is not inMemory from stream definitions, and every @store from
    table definitions. Definitions are shallow-copied so a caller-owned
    SiddhiApp object is not mutated."""
    import dataclasses

    def keep_stream_ann(a) -> bool:
        if a.name.lower() not in ("source", "sink"):
            return True
        t = (a.element("type") or "").lower()
        return t in ("inmemory", "memory")

    streams = {}
    for sid, sdef in app.stream_definitions.items():
        if any(not keep_stream_ann(a) for a in sdef.annotations or []):
            sdef = dataclasses.replace(
                sdef, annotations=[a for a in sdef.annotations
                                   if keep_stream_ann(a)])
        streams[sid] = sdef
    tables = {}
    for tid, tdef in app.table_definitions.items():
        if any(a.name.lower() == "store" for a in tdef.annotations or []):
            tdef = dataclasses.replace(
                tdef, annotations=[a for a in tdef.annotations
                                   if a.name.lower() != "store"])
        tables[tid] = tdef
    return dataclasses.replace(
        app, stream_definitions=streams, table_definitions=tables)


class SiddhiManager:
    def __init__(self):
        self.siddhi_context = SiddhiContext()
        self.app_runtimes: Dict[str, SiddhiAppRuntime] = {}

    def create_siddhi_app_runtime(self, app: Union[str, SiddhiApp]) -> SiddhiAppRuntime:
        from siddhi_tpu.observability.tracing import span

        if isinstance(app, str):
            with span("compile", chars=len(app)):
                app = SiddhiCompiler.parse(SiddhiCompiler.update_variables(app))
        # Not auto-started: callers attach callbacks first, then start()
        # (reference flow); InputManager starts lazily on first handler use.
        with span("assemble", app=app.name or ""):
            runtime = SiddhiAppRuntime(app, self.siddhi_context)
        self.app_runtimes[runtime.name] = runtime
        return runtime

    createSiddhiAppRuntime = create_siddhi_app_runtime

    def validate_siddhi_app(self, app: Union[str, SiddhiApp]) -> None:
        """Parse and fully build the app, then discard it — creation-time
        errors surface, nothing is registered or started (reference
        ``SiddhiManager.validateSiddhiApp``)."""
        if isinstance(app, str):
            app = SiddhiCompiler.parse(SiddhiCompiler.update_variables(app))
        runtime = SiddhiAppRuntime(app, self.siddhi_context)
        runtime.shutdown()

    validateSiddhiApp = validate_siddhi_app

    def create_sandbox_siddhi_app_runtime(
            self, app: Union[str, SiddhiApp]) -> SiddhiAppRuntime:
        """Create a runtime with external transports/stores stripped for
        testing (reference ``SiddhiManager.createSandboxSiddhiAppRuntime``
        :104-116 + ``removeSourceSinkAndStoreAnnotations``): every
        non-inMemory @source/@sink on a stream and every @store on a table
        is removed, so the app runs fully in-process — feed it with
        InputHandlers/InMemoryBroker, observe with callbacks."""
        if isinstance(app, str):
            app = SiddhiCompiler.parse(SiddhiCompiler.update_variables(app))
        app = _strip_transports(app)
        runtime = SiddhiAppRuntime(app, self.siddhi_context)
        self.app_runtimes[runtime.name] = runtime
        return runtime

    createSandboxSiddhiAppRuntime = create_sandbox_siddhi_app_runtime

    def get_siddhi_app_runtime(self, name: str) -> Optional[SiddhiAppRuntime]:
        return self.app_runtimes.get(name)

    def set_extension(self, name: str, clazz: type):
        """Register a custom extension (reference SiddhiManager.java:213)."""
        self.siddhi_context.extensions[name] = clazz

    setExtension = set_extension

    def set_persistence_store(self, store):
        self.siddhi_context.persistence_store = store

    setPersistenceStore = set_persistence_store

    def set_config_manager(self, config_manager):
        self.siddhi_context.config_manager = config_manager

    def persist_all(self):
        """Persist every app (reference SiddhiManager.persist)."""
        for rt in self.app_runtimes.values():
            rt.persist()

    persistAll = persist_all

    def restore_last_state(self):
        """Restore every app from its last revision (reference
        SiddhiManager.restoreLastState:292-300)."""
        for rt in self.app_runtimes.values():
            rt.restore_last_revision()

    restoreLastState = restore_last_state

    def shutdown(self):
        for rt in list(self.app_runtimes.values()):
            rt.shutdown()
        self.app_runtimes.clear()
