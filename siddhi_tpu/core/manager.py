"""SiddhiManager: top-level API — app registry + shared context.

Mirror of reference ``core/SiddhiManager.java:49`` (createSiddhiAppRuntime
:80-96, setExtension:213, persistence-store injection:167, shutdown:270-300).
"""

from __future__ import annotations

from typing import Dict, Optional, Union

from siddhi_tpu.compiler import SiddhiCompiler
from siddhi_tpu.core.app_runtime import SiddhiAppRuntime
from siddhi_tpu.core.context import SiddhiContext
from siddhi_tpu.query_api.siddhi_app import SiddhiApp


class SiddhiManager:
    def __init__(self):
        self.siddhi_context = SiddhiContext()
        self.app_runtimes: Dict[str, SiddhiAppRuntime] = {}

    def create_siddhi_app_runtime(self, app: Union[str, SiddhiApp]) -> SiddhiAppRuntime:
        if isinstance(app, str):
            app = SiddhiCompiler.parse(SiddhiCompiler.update_variables(app))
        # Not auto-started: callers attach callbacks first, then start()
        # (reference flow); InputManager starts lazily on first handler use.
        runtime = SiddhiAppRuntime(app, self.siddhi_context)
        self.app_runtimes[runtime.name] = runtime
        return runtime

    createSiddhiAppRuntime = create_siddhi_app_runtime

    def get_siddhi_app_runtime(self, name: str) -> Optional[SiddhiAppRuntime]:
        return self.app_runtimes.get(name)

    def set_extension(self, name: str, clazz: type):
        """Register a custom extension (reference SiddhiManager.java:213)."""
        self.siddhi_context.extensions[name] = clazz

    setExtension = set_extension

    def set_persistence_store(self, store):
        self.siddhi_context.persistence_store = store

    setPersistenceStore = set_persistence_store

    def set_config_manager(self, config_manager):
        self.siddhi_context.config_manager = config_manager

    def persist_all(self):
        """Persist every app (reference SiddhiManager.persist)."""
        for rt in self.app_runtimes.values():
            rt.persist()

    persistAll = persist_all

    def restore_last_state(self):
        """Restore every app from its last revision (reference
        SiddhiManager.restoreLastState:292-300)."""
        for rt in self.app_runtimes.values():
            rt.restore_last_revision()

    restoreLastState = restore_last_state

    def shutdown(self):
        for rt in list(self.app_runtimes.values()):
            rt.shutdown()
        self.app_runtimes.clear()
