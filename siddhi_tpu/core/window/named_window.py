"""Named windows: ``define window W (...) <window>(...)``.

Mirror of reference ``core/window/Window.java:65``: one shared window
instance; producers ``insert into W``, consumers ``from W`` receive its
emissions (CURRENT/EXPIRED per the definition's ``output`` clause), and
joins probe its buffer. Here the window is a device stage with shared
state; subscriber queries read the emission stream through the window's
output junction.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.event import CURRENT, EXPIRED, RESET, TIMER, Event, HostBatch
from siddhi_tpu.core.plan.resolvers import SingleStreamResolver
from siddhi_tpu.core.stream.junction import Receiver, StreamJunction
from siddhi_tpu.ops.expressions import TYPE_KEY, VALID_KEY
from siddhi_tpu.ops.windows import conform_cols
from siddhi_tpu.query_api.definitions import WindowDefinition


class NamedWindowRuntime(Receiver):
    def __init__(self, definition: WindowDefinition, app_context, dictionary):
        from siddhi_tpu.ops.windows import create_window_stage

        self.definition = definition
        self.app_context = app_context
        self.dictionary = dictionary
        resolver = SingleStreamResolver(definition, dictionary)
        self.stage = create_window_stage(definition.window, definition, resolver,
                                         app_context)
        self.host_mode = getattr(self.stage, "host_mode", False)
        self.state = None if self.host_mode else self.stage.init_state()
        self.out_junction = StreamJunction(definition, app_context)
        self.scheduler = None
        self._step = None
        self._lock = threading.RLock()

    def contents(self):
        """Probe surface for joins (reference WindowWindowProcessor.find)."""
        with self._lock:
            if self.host_mode:
                return self.stage.contents()
            return self.stage.contents(self.state)

    def _make_step(self):
        stage = self.stage

        def step(state, cols, now):
            ctx = {"xp": jnp, "current_time": now}
            return stage.apply(state, conform_cols(stage, cols), ctx)

        # NOT donated: probe readers (joins, on-demand queries) hold
        # references to the state buffers between steps
        return jax.jit(step)

    def receive(self, events: List[Event]):
        batch = HostBatch.from_events(events, self.definition, self.dictionary)
        self._process(batch)

    # queries `insert into W` treat the window as their output junction
    send_events = receive

    _now_override = None   # timer chunks sweep at their scheduled time

    def process_timer(self, ts: int):
        from siddhi_tpu.core.query.runtime import _zero_value

        batch = HostBatch.from_events(
            [Event(timestamp=int(ts),
                   data=[_zero_value(a.type) for a in self.definition.attributes])],
            self.definition, self.dictionary)
        batch.cols[TYPE_KEY][...] = TIMER
        # lock before setting the override (see QueryRuntime.process_timer)
        with self._lock:
            self._now_override = int(ts)
            try:
                self._process(batch)
            finally:
                self._now_override = None

    def _process(self, batch: HostBatch):
        with self._lock:
            batch.cols["__gk__"] = np.zeros(batch.capacity, np.int32)
            now = np.int64(
                self._now_override
                if self._now_override is not None
                else self.app_context.timestamp_generator.current_time())
            if self.host_mode:
                out_batch, notify = self.stage.process(batch, int(now))
                out_host = dict(out_batch.cols)
                overflow = None
            else:
                if self._step is None:
                    self._step = self._make_step()
                self.state, out = self._step(self.state, batch.cols, now)
                out_host = {k: np.asarray(v) for k, v in out.items()}
                overflow = out_host.pop("__overflow__", None)
                notify = out_host.pop("__notify__", None)
            if overflow is not None and int(overflow) > 0:
                from siddhi_tpu.core.stream.junction import FatalQueryError

                raise FatalQueryError(
                    f"window '{self.definition.id}': buffer capacity exceeded — "
                    f"raise app_context.window_capacity before creating the runtime"
                )
            out_host.pop("__flush__", None)
            types_wanted = {
                "current": (CURRENT,),
                "expired": (EXPIRED,),
                "all": (CURRENT, EXPIRED),
            }[self.definition.output_event_type]
            events = HostBatch(out_host).to_events(
                [(a.name, a.type) for a in self.definition.attributes],
                self.dictionary, types_wanted=types_wanted)
        if events:
            self.out_junction.send_events(events)
        if notify is not None and int(notify) >= 0 and self.scheduler is not None:
            self.scheduler.notify_at(int(notify), self.process_timer)
