from siddhi_tpu.core.window.named_window import NamedWindowRuntime

__all__ = ["NamedWindowRuntime"]
