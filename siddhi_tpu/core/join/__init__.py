"""Device-resident partitioned join engine (see ``engine.py``).

``attach_join_engine`` is the single planner hook: it classifies a
freshly-built ``JoinQueryRuntime`` (engine-eligible / pipeline-eligible /
legacy), instantiates the engine for eligible shapes, and registers the
join observability surface (``siddhi_join_partition_rows`` occupancy
gauges + ``siddhi_join_probe_ms`` / ``siddhi_join_insert_ms``
histograms, exported by ``observability/export.py``)."""

from __future__ import annotations

from siddhi_tpu.core.join.engine import (  # noqa: F401 — public surface
    ENGINE_STATE_KEYS,
    PIDX_KEYS,
    SEQ_KEY,
    DeviceJoinEngine,
    engine_ineligibility,
    extract_partition_keys,
    pipeline_ineligibility,
)


def attach_join_engine(rt, on_expr) -> None:
    """Classify ``rt`` and attach the device engine when eligible.
    Called by the planner right after the runtime is built; respects the
    ``siddhi_tpu.join_engine`` opt-out (``legacy`` keeps the synchronous
    reference path wholesale, including pipeline ineligibility — the
    bit-identity baseline ``tools/quick_join_check.py`` compares
    against)."""
    from siddhi_tpu.core.eligibility import ReasonCode as RC
    from siddhi_tpu.core.eligibility import reason

    rt.engine = None
    rt.engine_reason = engine_ineligibility(rt)
    rt.pipeline_reason = pipeline_ineligibility(rt)
    mode = str(getattr(rt.app_context, "join_engine", "device") or "device")
    if mode != "device":
        rt.engine_reason = rt.engine_reason or \
            reason(RC.DISABLED, "disabled (siddhi_tpu.join_engine=legacy)")
        rt.pipeline_reason = reason(RC.DISABLED,
                                    "siddhi_tpu.join_engine=legacy")
        return
    if rt.engine_reason is not None:
        return
    pspec = extract_partition_keys(
        on_expr, rt.sides["left"], rt.sides["right"], rt.dictionary) \
        if on_expr is not None else None
    rt.engine = DeviceJoinEngine(rt, pspec)
    rt._instr_spec = None   # engine suffix (seq + fills) joins the spec
    _register_metrics(rt)


def _register_metrics(rt) -> None:
    tel = getattr(rt.app_context, "telemetry", None)
    if tel is None:
        return
    # pre-declare the per-query probe/insert histograms so the
    # siddhi_join_probe_ms / siddhi_join_insert_ms families exist on
    # /metrics from app start (export.py renders them as summaries)
    tel.histogram(f"join.probe_ms.{rt.name}")
    tel.histogram(f"join.insert_ms.{rt.name}")
    eng = rt.engine
    for side_key, plan in eng.plans.items():
        if not plan.use_pidx:
            continue
        for p in range(eng.P):
            # zero-pull gauge backend: partition_occupancy reads the
            # last DRAINED fill.<side> instrument lanes (host ring
            # mirror when instruments are off) — a scrape never touches
            # device state (observability/instruments.py)
            tel.gauge(
                f"join.partition_rows.{rt.name}.{side_key}.{p}",
                lambda e=eng, s=side_key, i=p: float(
                    e.partition_occupancy(s)[i]))
