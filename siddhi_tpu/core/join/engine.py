"""Device-resident partitioned join engine (PanJoin on device).

The legacy probe path (``core/query/join_runtime.build_side_step_fn``)
evaluates the ``on`` condition as one ``[N, W]`` broadcast compare of the
N trigger rows against the other side's whole W-slot ring and then
materializes every ``[N, W+1]`` joined column. This module replaces that
probe surface for eligible stream-stream window joins with a
PanJoin-style partitioned sub-structure ("A Partition-based Adaptive
Stream Join", PAPERS.md): each side's build state is indexed by a
hash-partitioned ``[P, W/P]`` sub-window directory with per-partition
occupancy, and a trigger row gathers ONLY its own hash partition of the
other side — the condition evaluates on ``[N, Wp]`` and the join
materializes ``[N, Wp+1]`` instead of ``[N, W+1]``, a ~P-fold cut of the
probe surface. One jitted step per arriving chunk performs
insert-into-own-side + the masked partition-local probe of the other
side, and stamps an explicit cross-stream sequence number into the meta
so left/right batches have a total order the CompletionPump can respect
(``join_runtime._pipeline_ok``).

Bit-identity with the legacy path (``tools/quick_join_check.py``) is
preserved by construction:

- the sub-window directory stores each member's global arrival sequence
  number (``gseq``); the member's legacy ring slot is ``gseq % W`` and
  its liveness is ``gseq >= floor`` (length windows: ``total - W``; time
  windows: ``expired_upto``) — the directory enumerates exactly the rows
  ``WindowStage.contents`` would, just partition-major;
- matched pairs re-sort by an explicit emission-order key
  ``trigger_row * (W + 1) + legacy_slot`` (one-sided/outer rows take
  slot ``W``), reproducing the legacy row-major ``[N, W+1]`` order
  exactly — the PR-7 okey convention applied within one step.

Partitioning engages only when the ``on`` condition carries an equality
conjunct over hashable key types (int/long/bool/string — floats keep the
broadcast compare: ``-0.0 == 0.0`` and NaN would break the equal-values
=> equal-hash invariant); without one the engine runs the same fused
step with the legacy-layout probe (P = 1), which is what keeps the
pipeline/fusion eligibility wins independent of the probe pruning.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from siddhi_tpu.core.plan.selector_plan import FLUSH_KEY, GK_KEY, STR_RANK
from siddhi_tpu.ops.expressions import (
    OKEY_KEY, TS_KEY, TYPE_KEY, VALID_KEY)
from siddhi_tpu.ops.windows import (
    LengthWindowStage, PassthroughWindowStage, TimeWindowStage, conform_cols)
from siddhi_tpu.query_api.definitions import AttrType

_LOG = logging.getLogger("siddhi_tpu.join.engine")

CURRENT, EXPIRED, TIMER, RESET = 0, 1, 2, 3
_BIG = np.int64(2 ** 62)

# state keys of the per-side partition directories + the cross-stream
# sequence counter — stripped from snapshots (canonical capture is the
# legacy ring layout) and rebuilt at restore (rebuild_probe_state)
PIDX_KEYS = ("lpidx", "rpidx")
SEQ_KEY = "jseq"
ENGINE_STATE_KEYS = PIDX_KEYS + (SEQ_KEY,)

_HASHABLE = (AttrType.INT, AttrType.LONG, AttrType.BOOL, AttrType.STRING)


# ------------------------------------------------------------ eligibility

def engine_ineligibility(rt) -> Optional[str]:
    """Why this join runtime cannot run the device engine (None = it
    can). v1 scope: non-partitioned stream-stream joins whose sides are
    device length/time/externalTime windows or windowless passthroughs.
    Shared-store sides (tables, named windows, aggregations), host-mode
    windows and `partition with` joins keep the legacy probe path (the
    keyed ``[K, W]`` ring of a partitioned join is already
    partition-local by construction). Reasons are
    ``core.eligibility.Reason`` strings (stable ``.code`` + free-text
    detail)."""
    from siddhi_tpu.core.eligibility import ReasonCode as RC
    from siddhi_tpu.core.eligibility import reason

    if rt.partition_ctx is not None:
        return reason(RC.PARTITIONED,
                      "partitioned join (keyed rings are already "
                      "partition-local)")
    if rt.index_probe is not None:
        return reason(RC.INDEXED_PROBE, "indexed table probe")
    for side in rt.sides.values():
        if side.store is not None:
            return reason(RC.STORE_SIDE,
                          f"shared-store side '{side.stream_id}'")
        if side.host_window is not None:
            return reason(RC.HOST_WINDOW,
                          f"host-mode window side '{side.stream_id}'")
        stage = side.window_stage
        if not isinstance(stage, (LengthWindowStage, TimeWindowStage,
                                  PassthroughWindowStage)):
            return reason(RC.WINDOW_KIND,
                          f"window stage {type(stage).__name__} on side "
                          f"'{side.stream_id}' (no partition adapter yet)")
    return None


def pipeline_ineligibility(rt) -> Optional[str]:
    """Why this join runtime's batches may NOT ride the CompletionPump
    (None = they may). Wider than engine eligibility: any stream-stream
    join whose probe surfaces live inside the jitted state can pipeline —
    the per-side ``__notify__`` is attributed to the side's own timer
    callback at drain, and the pump's per-owner FIFO preserves the
    cross-stream dispatch order (which the engine additionally stamps
    into the meta as an explicit sequence number)."""
    from siddhi_tpu.core.eligibility import ReasonCode as RC
    from siddhi_tpu.core.eligibility import reason

    for side in rt.sides.values():
        if side.store is not None:
            return reason(RC.STORE_SIDE,
                          f"shared-store probe side '{side.stream_id}' "
                          f"(host-interleaved contents)")
        if side.host_window is not None:
            return reason(RC.HOST_WINDOW,
                          f"host-mode window side '{side.stream_id}'")
        if side.window_stage is None:
            return reason(RC.NO_WINDOW,
                          f"side '{side.stream_id}' has no window stage")
    if rt.keyer is not None:
        return reason(RC.GROUPED_SELECT,
                      "grouped selector (host keyed select between stages)")
    if rt.index_probe is not None:
        return reason(RC.INDEXED_PROBE, "indexed table probe")
    return None


# ---------------------------------------------------- equality extraction

def extract_partition_keys(on_expr, left, right, dictionary):
    """Find an equality conjunct ``<left-side expr> == <right-side expr>``
    in the ``on`` condition (top level, or one conjunct of a top-level
    And) whose two values are hashable types, and compile each side's
    value closure against that side's OWN (unprefixed) columns. Returns
    ``{"left": fn, "right": fn}`` or None. Both closures cast to the
    promoted dtype before hashing so equal values always co-partition."""
    from siddhi_tpu.core.plan.resolvers import SingleStreamResolver
    from siddhi_tpu.ops.expressions import compile_expr
    from siddhi_tpu.ops.types import promote
    from siddhi_tpu.query_api.expressions import (
        And, AttributeFunction, Compare, Variable)

    def vars_of(e, out):
        if isinstance(e, Variable):
            out.append(e)
        for name in ("left", "right", "expression"):
            c = getattr(e, name, None)
            if c is not None and not isinstance(c, (str, int, float, bool)):
                vars_of(c, out)
        if isinstance(e, AttributeFunction):
            for p in e.parameters:
                vars_of(p, out)
        return out

    def side_ids(s):
        return {s.stream_id, s.ref_id} - {None}

    def owner_of(expr):
        """Which side an expression reads (None = mixed/unqualified)."""
        vs = vars_of(expr, [])
        if not vs or any(v.stream_id is None for v in vs):
            return None
        owners = set()
        for v in vs:
            in_l = v.stream_id in side_ids(left)
            in_r = v.stream_id in side_ids(right)
            if in_l == in_r:      # ambiguous (self-join raw id) or neither
                return None
            owners.add("left" if in_l else "right")
        return owners.pop() if len(owners) == 1 else None

    def try_eq(e):
        if not isinstance(e, Compare) or e.operator != "==":
            return None
        oa, ob = owner_of(e.left), owner_of(e.right)
        if oa is None or ob is None or oa == ob:
            return None
        by_side = {oa: e.left, ob: e.right}
        fns = {}
        types = {}
        for key, side in (("left", left), ("right", right)):
            res = SingleStreamResolver(side.definition, dictionary,
                                       ref_id=side.ref_id)
            try:
                fn, t = compile_expr(by_side[key], res)
            except Exception:  # noqa: BLE001 — fall back to broadcast probe
                return None
            fns[key] = fn
            types[key] = t
        if any(t not in _HASHABLE for t in types.values()):
            return None
        if types["left"] != types["right"]:
            # mixed types: only numeric pairs with a lossless promotion
            # keep the equal-values => equal-hash invariant (promote
            # raises on strings/bools, which must match exactly)
            from siddhi_tpu.ops.types import is_numeric

            if not (is_numeric(types["left"])
                    and is_numeric(types["right"])):
                return None
            try:
                promote(types["left"], types["right"])
            except Exception:  # noqa: BLE001 — incomparable types
                return None
        return fns

    hit = try_eq(on_expr)
    if hit is not None:
        return hit
    if isinstance(on_expr, And):
        for part in (on_expr.left, on_expr.right):
            hit = try_eq(part)
            if hit is not None:
                return hit
    return None


# ------------------------------------------------------------ hashing

_MIX1 = 0xBF58476D1CE4E5B9
_MIX2 = 0x94D049BB133111EB


def hash_partition_dev(vals, P: int):
    """splitmix64 finalizer -> partition id [0, P) (device). P pow2."""
    h = jnp.asarray(vals).astype(jnp.int64).astype(jnp.uint64)
    h = (h ^ (h >> jnp.uint64(30))) * jnp.uint64(_MIX1)
    h = (h ^ (h >> jnp.uint64(27))) * jnp.uint64(_MIX2)
    h = h ^ (h >> jnp.uint64(31))
    return (h & jnp.uint64(P - 1)).astype(jnp.int32)


def hash_partition_np(vals, P: int):
    """Host mirror of ``hash_partition_dev`` — MUST stay bit-identical
    (snapshot rebuild re-partitions the restored rings with it)."""
    h = np.asarray(vals).astype(np.int64).astype(np.uint64)
    h = (h ^ (h >> np.uint64(30))) * np.uint64(_MIX1)
    h = (h ^ (h >> np.uint64(27))) * np.uint64(_MIX2)
    h = h ^ (h >> np.uint64(31))
    return (h & np.uint64(P - 1)).astype(np.int32)


def _pow2(n: int, start: int = 1) -> int:
    k = max(start, 1)
    while k < n:
        k *= 2
    return k


# ------------------------------------------------------------ side plans

class _SidePlan:
    """Per-side partition-directory parameters (``use_pidx`` False =
    this side keeps the legacy-layout probe surface)."""

    __slots__ = ("kind", "W", "use_pidx", "Wp", "key_fn", "pidx_key",
                 "win_key")

    def __init__(self, side_key: str, side, pspec, P: int, slack: int):
        stage = side.window_stage
        if isinstance(stage, LengthWindowStage):
            self.kind, self.W = "length", int(stage.length)
        elif isinstance(stage, TimeWindowStage):
            self.kind, self.W = "time", int(stage.capacity)
        else:
            self.kind, self.W = "none", 1
        self.win_key = "lwin" if side_key == "left" else "rwin"
        self.pidx_key = "lpidx" if side_key == "left" else "rpidx"
        self.key_fn = pspec[side_key] if pspec is not None else None
        # partitioning pays only when the ring meaningfully exceeds the
        # partition count (tiny rings keep the full-surface probe), and
        # engages only when the host can mirror the ring's partition
        # occupancy EXACTLY for the adaptive sub-window growth: every
        # valid CURRENT row inserts at slot seq % W (length AND time
        # rings share that mechanic), so in-step filters/transforms —
        # which drop or rewrite rows device-side — keep the full-surface
        # probe (still fused, pipelined and fusion-eligible)
        self.use_pidx = (self.kind != "none" and self.key_fn is not None
                         and P > 1 and self.W >= 4 * P
                         and not side.filters and not side.transforms)
        self.Wp = (_pow2((self.W * slack + P - 1) // P)
                   if self.use_pidx else 0)

    # liveness floor: members with gseq >= floor are exactly the rows the
    # legacy contents() view reports live
    def live_floor(self, win_state):
        if self.kind == "length":
            return jnp.maximum(win_state["total"] - self.W, jnp.int64(0))
        return jnp.maximum(win_state["expired_upto"], jnp.int64(0))

    def live_floor_np(self, win_state):
        if self.kind == "length":
            return max(int(win_state["total"]) - self.W, 0)
        return max(int(win_state["expired_upto"]), 0)


class DeviceJoinEngine:
    """Owns the per-side partition plans and builds the fused
    insert+probe step of each side (``JoinQueryRuntime`` delegates its
    ``build_side_step_fn`` here when attached)."""

    def __init__(self, runtime, pspec):
        self.rt = runtime
        ac = runtime.app_context
        cfg_p = int(getattr(ac, "join_partitions", 0) or 0)
        if cfg_p <= 0:
            # auto: partition pruning pays where gathers are wide and
            # cheap (accelerators); the CPU fallback keeps the fused
            # full-surface probe, which holds legacy throughput while
            # still buying pipeline/fusion/mesh eligibility (PERF.md)
            import jax

            cfg_p = 1 if jax.default_backend() == "cpu" else 8
        P = _pow2(cfg_p)
        self.P = max(1, min(P, 64))
        self.slack = max(1, int(getattr(ac, "join_partition_slack", 2)))
        # adaptive sub-window growth (PanJoin's re-partitioning): when a
        # batch would push one partition's ring occupancy past Wp, the
        # host grows Wp BEFORE dispatch (capped at pow2(W), where skew
        # cannot overflow) instead of dying mid-stream. Off = static
        # provisioning; overflow is then a FatalQueryError naming
        # siddhi_tpu.join_partition_slack.
        self.grow = bool(getattr(ac, "join_partition_grow", True))
        # host mirrors of each side's ring partition occupancy: slot =
        # seq % W is pure ring mechanics (length AND time rings), so the
        # mirror is EXACT with zero device pulls — a partition's live
        # members are a subset of its ring slots, which bounds the
        # directory pressure (see prepare_batch)
        self._mirror: Dict[str, dict] = {}
        # per-side (total, [P] occ) memo of the mirror bincount: the P
        # registered partition gauges each read one lane, and a scrape
        # must not pay P ring passes (content-keyed, not time-keyed —
        # exactness is preserved)
        self._occ_memo: Dict[str, tuple] = {}
        self.plans: Dict[str, _SidePlan] = {
            k: _SidePlan(k, runtime.sides[k], pspec, self.P, self.slack)
            for k in ("left", "right")
        }

    @property
    def partitioned_probe(self) -> bool:
        return any(p.use_pidx for p in self.plans.values())

    # ------------------------------------------------------------- state

    def init_pidx_state(self) -> dict:
        """Engine-private state keys to merge into the runtime's state
        pytree (empty directories + the cross-stream sequence)."""
        st = {SEQ_KEY: jnp.int64(0)}
        for plan in self.plans.values():
            if plan.use_pidx:
                st[plan.pidx_key] = {
                    "gseq": jnp.full((self.P, plan.Wp), -1, jnp.int64),
                    "cnt": jnp.zeros((self.P,), jnp.int64),
                }
        return st

    def partition_occupancy(self, side_key: str) -> np.ndarray:
        """Live members per partition of one side ([P] int64) — the
        ``siddhi_join_partition_rows`` gauge backend. ZERO device pulls
        by construction (a /metrics scrape must never touch the device,
        transfer-guard-verified): the primary source is the last drained
        ``fill.<side>`` instrument lanes, which the step computes from
        the directory it already holds and ships on the meta pull that
        happens anyway (``observability/instruments.py``); with
        instruments off (``profile_device_instruments: false``) the
        host ring-occupancy mirror answers instead — exact for length
        rings, an upper bound for time rings whose expired rows linger
        in their slots until overwritten."""
        plan = self.plans[side_key]
        if not plan.use_pidx:
            return np.zeros(self.P, np.int64)
        last = getattr(self.rt, "_instr_last", {}).get(f"fill.{side_key}")
        if last is not None and np.asarray(last).shape[0] == self.P:
            return np.asarray(last, np.int64)
        mir = self._mirror.get(side_key)
        if mir is None:
            return np.zeros(self.P, np.int64)
        memo = self._occ_memo.get(side_key)
        if memo is not None and memo[0] == mir["total"]:
            return memo[1]
        ring = mir["ring"]
        occ = np.bincount(ring[ring >= 0],
                          minlength=self.P).astype(np.int64)[: self.P]
        self._occ_memo[side_key] = (mir["total"], occ)
        return occ

    # ------------------------------------------------------ restore path

    def rebuild_probe_state(self) -> None:
        """Re-derive the partition directories (and host occupancy
        mirrors) from the (canonical) ring state after a snapshot
        restore — the snapshot stores only the legacy ``[W]`` ring layout
        (``strip_engine_state``), so a legacy revision restores into the
        engine and vice versa bit-identically. Live rows re-insert in
        global-sequence order; partition offsets may differ from the
        never-restored trajectory, but probe results cannot (membership
        and ``gseq`` are identical)."""
        if self.rt._state is None:
            return
        for side_key in self.plans:
            self._rebuild_side(side_key)
        state = dict(self.rt._state)
        if SEQ_KEY not in state:
            state[SEQ_KEY] = jnp.int64(0)
        self.rt._state = state

    def _ring_partitions(self, plan, win) -> np.ndarray:
        """Partition id of every OCCUPIED ring slot of one side ([W]
        int32, -1 = empty) — hashed from the ring's own buffered values,
        host-side."""
        total = int(np.asarray(win["total"]))
        filled = min(total, plan.W)
        ring_p = np.full(plan.W, -1, np.int32)
        if filled:
            buf = {k: np.asarray(v) for k, v in win["buf"].items()}
            vals, mask = plan.key_fn(buf, {"xp": np})
            vals = np.broadcast_to(np.asarray(vals), (plan.W,))
            pr = hash_partition_np(vals, self.P).astype(np.int32)
            if mask is not None:
                pr = np.where(
                    np.broadcast_to(np.asarray(mask, bool), (plan.W,)),
                    np.int32(0), pr)
            ring_p[:filled] = pr[:filled]
        return ring_p

    def _rebuild_side(self, side_key: str) -> None:
        """Rebuild ONE side's directory + host mirror from its ring
        (restore path and adaptive growth). Auto-sizes Wp up to pow2(W)
        when the restored ring is hotter than the current sub-windows
        (growth on); with growth off an unrepresentable ring is fatal,
        naming the static knob."""
        from siddhi_tpu.core.stream.junction import FatalQueryError

        plan = self.plans[side_key]
        if not plan.use_pidx or self.rt._state is None:
            return
        state = dict(self.rt._state)
        win = state[plan.win_key]
        win_h = {k: np.asarray(v) for k, v in win.items()
                 if k in ("total", "expired_upto")}
        total = int(np.asarray(win["total"]))
        ring_p = self._ring_partitions(plan, win)
        occ = np.bincount(ring_p[ring_p >= 0], minlength=self.P)
        need = int(occ.max(initial=0))
        if need > plan.Wp and self.grow:
            plan.Wp = min(_pow2(2 * need), _pow2(plan.W))
        floor = plan.live_floor_np(win_h)
        gseqs = np.arange(floor, total, dtype=np.int64)
        gseq_grid = np.full((self.P, plan.Wp), -1, np.int64)
        cnt = np.zeros(self.P, np.int64)
        if gseqs.size:
            slots = (gseqs % plan.W).astype(np.int64)
            p = ring_p[slots].astype(np.int64)
            for i in range(gseqs.size):     # gseq-ascending fill
                pi = int(p[i])
                if cnt[pi] >= plan.Wp:
                    raise FatalQueryError(
                        f"query '{self.rt.name}': "
                        f"{self.rt.overflow_knob_msg(code=4)}")
                gseq_grid[pi, cnt[pi]] = gseqs[i]
                cnt[pi] += 1
        state[plan.pidx_key] = {"gseq": jnp.asarray(gseq_grid),
                                "cnt": jnp.asarray(cnt)}
        self.rt._state = state
        self._mirror[side_key] = {"ring": ring_p, "total": total}

    # ------------------------------------------------- adaptive capacity

    def prepare_batch(self, side_key: str, cols) -> bool:
        """Pre-dispatch host bookkeeping of one side's batch: advance the
        side's ring-occupancy mirror with the batch's hashed keys and
        GROW the sub-window capacity BEFORE the step could overflow it —
        PanJoin's adaptive re-partitioning, keyed off exact ring
        mechanics (slot = seq % W) with zero device pulls. A partition's
        live members are always a subset of its ring slots, so
        ``Wp >= max ring occupancy`` makes directory overflow impossible.
        Returns True when capacities changed (the runtime's compiled
        side steps were dropped; fused groups must drop theirs too)."""
        plan = self.plans[side_key]
        if not plan.use_pidx:
            return False
        valid = (np.asarray(cols[VALID_KEY], bool)
                 & (np.asarray(cols[TYPE_KEY]) == CURRENT))
        n = int(valid.sum())
        if n == 0:
            return False
        B = valid.shape[0]
        hvals, hmask = plan.key_fn(cols, {"xp": np})
        hvals = np.broadcast_to(np.asarray(hvals), (B,))
        p = hash_partition_np(hvals, self.P).astype(np.int32)
        if hmask is not None:
            p = np.where(np.broadcast_to(np.asarray(hmask, bool), (B,)),
                         np.int32(0), p)
        p = p[valid]
        mir = self._mirror.get(side_key)
        if mir is None:
            mir = self._mirror[side_key] = {
                "ring": np.full(plan.W, -1, np.int32), "total": 0}
        W = plan.W
        ring = mir["ring"]
        if n >= W:
            slots = (mir["total"] + np.arange(n - W, n)) % W
            ring[:] = -1
            ring[slots] = p[n - W:]
        else:
            slots = (mir["total"] + np.arange(n)) % W
            ring[slots] = p
        mir["total"] += n
        occ = np.bincount(ring[ring >= 0], minlength=self.P)
        need = int(occ.max(initial=0))
        if need <= plan.Wp or not self.grow:
            # growth off: the in-step overflow check surfaces the skew as
            # FatalQueryError naming siddhi_tpu.join_partition_slack
            return False
        plan.Wp = min(_pow2(2 * need), _pow2(plan.W))
        _LOG.info(
            "query '%s': join partition sub-windows of side %s grown to "
            "%d (ring occupancy %d) — adaptive re-partition",
            self.rt.name, side_key, plan.Wp, need)
        # rebuild the directory from the PRE-batch device ring (the step
        # inserts this batch into the grown directory), then restore the
        # batch-advanced mirror — it is the post-dispatch truth
        self._rebuild_side(side_key)
        self._mirror[side_key] = mir
        self.rt._steps.clear()
        return True

    def _shrink_target(self, side_key: str) -> Optional[tuple]:
        """(current Wp, shrink target) for one side, or None when the
        side is already right-sized. The target keeps the same 2x
        headroom the growth path provisions (``_pow2(2 * need)``) and
        never drops below the configured-slack initial sizing — the
        autopilot may only release what adaptive growth added. Host
        mirror / drained instrument lanes only (zero device pulls)."""
        plan = self.plans[side_key]
        if not plan.use_pidx:
            return None
        occ = self.partition_occupancy(side_key)
        need = int(occ.max(initial=0))
        floor = _pow2((plan.W * self.slack + self.P - 1) // self.P)
        target = max(_pow2(2 * need), floor)
        if target >= plan.Wp:
            return None
        return plan.Wp, target

    def shrink_candidates(self) -> Dict[str, tuple]:
        """Read-only autopilot signal: sides whose Wp could shrink back
        after a skew burst passed — {side: (wp, target)}."""
        out = {}
        for side_key in self.plans:
            t = self._shrink_target(side_key)
            if t is not None:
                out[side_key] = t
        return out

    def shrink_partitions(self) -> Dict[str, tuple]:
        """Release over-provisioned sub-window capacity — the reverse of
        ``prepare_batch``'s adaptive growth, through the SAME directory
        rebuild path (so probe membership and gseq order are identical
        by construction, only the capacity changes). Caller holds the
        runtime's owner lock; pipelined state futures are safe — the
        rebuild materializes the logical current state exactly as the
        growth path does. Returns {side: (old_wp, new_wp)}."""
        shrunk: Dict[str, tuple] = {}
        if self.rt._state is None:
            return shrunk
        for side_key in self.plans:
            t = self._shrink_target(side_key)
            if t is None:
                continue
            old_wp, target = t
            plan = self.plans[side_key]
            plan.Wp = target
            # _rebuild_side auto-grows if the ring is hotter than the
            # occupancy signal suggested — shrink can never overflow
            self._rebuild_side(side_key)
            shrunk[side_key] = (old_wp, plan.Wp)
            _LOG.info(
                "query '%s': join partition sub-windows of side %s "
                "shrunk %d -> %d (ring occupancy fell) — autopilot "
                "re-partition", self.rt.name, side_key, old_wp, plan.Wp)
        if shrunk:
            self.rt._steps.clear()
        return shrunk

    # -------------------------------------------------------- step build

    def build_side_step(self, side_key: str):
        """The fused (state, probe_cols, probe_valid, cols, now) ->
        (state', out) step of one side: transforms/filters -> window
        insert -> post-filters -> directory insert (own side) + masked
        partition-local probe (other side) -> selector. The signature
        matches the legacy builder so ``process_side_batch`` stays the
        single host driver; the probe placeholders are unused (both
        surfaces live inside the state)."""
        rt = self.rt
        side = rt.sides[side_key]
        other_key = "right" if side_key == "left" else "left"
        other = rt.sides[other_key]
        splan = self.plans[side_key]
        oplan = self.plans[other_key]
        sel = rt.selector_plan
        on_cond = rt.on_cond
        split = rt.keyer is not None
        P, slack = self.P, self.slack
        # device instruments: with the knob on, the step also ships each
        # partitioned side's per-partition directory fill behind the
        # sequence lane — the layout JoinQueryRuntime._step_instrument_
        # slots declares and the drain decodes (captured at build; the
        # step cache is cleared whenever capacities change)
        ins_on = rt._instruments_on()

        def _meta_suffix(new_state, seq):
            suffix = [seq.reshape(1)]
            if ins_on:
                for plan in (self.plans["left"], self.plans["right"]):
                    if not plan.use_pidx:
                        continue
                    gseq = new_state[plan.pidx_key]["gseq"]
                    floor = plan.live_floor(new_state[plan.win_key])
                    suffix.append(jnp.sum(
                        (gseq >= floor) & (gseq >= 0),
                        axis=1, dtype=jnp.int64))
            return suffix

        def _pidx_insert(pidx, cols, win_before, win_after):
            """Scatter this batch's inserted rows into the side's own
            partition directory; returns (pidx', overflow_flag)."""
            valid_cur = cols[VALID_KEY] & (cols[TYPE_KEY] == CURRENT)
            B = valid_cur.shape[0]
            total0 = win_before["total"]
            rank = jnp.cumsum(valid_cur.astype(jnp.int64)) - 1
            gseq = total0 + rank
            floor_after = splan.live_floor(win_after)
            # rows evicted/expired within this very batch never enter the
            # directory (the legacy ring drops them the same way)
            ins = valid_cur & (gseq >= floor_after)
            vals, mask = splan.key_fn(cols, {"xp": jnp})
            vals = jnp.broadcast_to(jnp.asarray(vals), (B,))
            p = hash_partition_dev(vals, P).astype(jnp.int64)
            if mask is not None:
                p = jnp.where(jnp.broadcast_to(jnp.asarray(mask, bool), (B,)),
                              jnp.int64(0), p)
            p = jnp.where(ins, p, jnp.int64(P))          # P = dropped
            maskp = p[None, :] == jnp.arange(P, dtype=jnp.int64)[:, None]
            pos = jnp.cumsum(maskp.astype(jnp.int64), axis=1) - 1
            pc = jnp.clip(p, 0, P - 1).astype(jnp.int32)
            pos_row = jnp.take_along_axis(pos, pc[None, :], axis=0)[0]
            n_per = jnp.sum(maskp.astype(jnp.int64), axis=1)
            off = (pidx["cnt"][pc] + pos_row) % splan.Wp
            flat = jnp.where(p < P, pc.astype(jnp.int64) * splan.Wp + off,
                             jnp.int64(P * splan.Wp))
            gflat = pidx["gseq"].reshape(-1)
            occupant = gflat[jnp.clip(flat, 0, P * splan.Wp - 1)]
            # overwriting a LIVE occupant (or >Wp inserts into one
            # partition this batch) silently drops probe members — fatal,
            # named knob (join_partition_slack / join_partitions)
            ov = (jnp.any((flat < P * splan.Wp)
                          & (occupant >= floor_after) & (occupant >= 0))
                  | jnp.any(n_per > splan.Wp)).astype(jnp.int32)
            g2 = gflat.at[flat].set(gseq, mode="drop").reshape(P, splan.Wp)
            return {"gseq": g2, "cnt": pidx["cnt"] + n_per}, ov

        def _materialize(wout, ev, match, one_sided, N, S):
            """Joined-row materialization shared by BOTH probe branches
            (partition-gathered and legacy-layout): [N, S] probe
            candidates + the one-sided column S flatten to row-major
            [N*(S+1)] columns, the layout the legacy broadcast probe
            emits — keep this the single source of truth so the two
            branches cannot drift apart."""
            NW = N * (S + 1)
            joined: Dict[str, jnp.ndarray] = {}
            for a in side.definition.attributes:
                v = jnp.broadcast_to(wout[a.name][:, None], (N, S + 1))
                mk = jnp.broadcast_to(wout[a.name + "?"][:, None],
                                      (N, S + 1))
                joined[side.prefix + a.name] = v.reshape(NW)
                joined[side.prefix + a.name + "?"] = mk.reshape(NW)
            for a in other.definition.attributes:
                pc_ = jnp.broadcast_to(ev[other.prefix + a.name], (N, S))
                pm_ = jnp.broadcast_to(ev[other.prefix + a.name + "?"],
                                       (N, S))
                joined[other.prefix + a.name] = jnp.concatenate(
                    [pc_, jnp.zeros((N, 1), pc_.dtype)], axis=1).reshape(NW)
                joined[other.prefix + a.name + "?"] = jnp.concatenate(
                    [pm_, jnp.ones((N, 1), bool)], axis=1).reshape(NW)
            joined[VALID_KEY] = jnp.concatenate(
                [match, one_sided[:, None]], axis=1).reshape(NW)
            joined[TS_KEY] = jnp.repeat(wout[TS_KEY], S + 1)
            joined[TYPE_KEY] = jnp.repeat(wout[TYPE_KEY], S + 1)
            joined[GK_KEY] = jnp.zeros(NW, jnp.int32)
            joined[FLUSH_KEY] = jnp.repeat(
                jnp.arange(N, dtype=jnp.int32), S + 1)
            return joined

        def step(state, probe_cols, probe_valid, cols, current_time):
            ctx = {"xp": jnp, "current_time": current_time}
            cols = dict(cols)
            strrank = cols.pop(STR_RANK, None)
            cols.pop(OKEY_KEY, None)
            for t in side.transforms:
                cols = t.apply(cols, ctx)
            valid = cols[VALID_KEY]
            timer = cols[TYPE_KEY] == TIMER
            for f in side.filters:
                valid = valid & (f(cols, ctx) | timer)
            cols[VALID_KEY] = valid
            new_state = dict(state)
            win_before = state[splan.win_key]
            conformed = conform_cols(side.window_stage, cols)
            new_win, wout = side.window_stage.apply(win_before, conformed,
                                                    ctx)
            new_state[splan.win_key] = new_win
            wout = dict(wout)
            notify = wout.pop("__notify__", None)
            overflow = wout.pop("__overflow__", None)
            wout.pop("__flush__", None)
            wout.pop(OKEY_KEY, None)
            pvalid = wout[VALID_KEY]
            ptimer = wout[TYPE_KEY] == TIMER
            for f in side.post_filters:
                pvalid = pvalid & (f(wout, ctx) | ptimer)
            wout[VALID_KEY] = pvalid

            # overflow bitmask: 1 = window ring, 4 = partition sub-window,
            # 8 = selector (distinctCount) — decoded by
            # JoinQueryRuntime.overflow_knob_msg into the exact knob
            ovbits = jnp.int32(0)
            if overflow is not None:
                ovbits = ovbits | jnp.where(
                    jnp.asarray(overflow).astype(jnp.int32) > 0, 1, 0)

            # ---- insert this batch into OUR OWN partition directory
            if splan.use_pidx:
                new_state[splan.pidx_key], pov = _pidx_insert(
                    state[splan.pidx_key], conformed, win_before, new_win)
                ovbits = ovbits | (pov * 4)

            N = wout[VALID_KEY].shape[0]
            W = oplan.W if oplan.kind != "none" else None
            row_live = wout[VALID_KEY] & (
                (wout[TYPE_KEY] == CURRENT) | (wout[TYPE_KEY] == EXPIRED))
            gathered = oplan.use_pidx and side.triggers

            if gathered:
                # ---- masked partition-local probe: gather only the
                # trigger row's hash partition of the other side
                opidx = state[oplan.pidx_key]
                oring = state[oplan.win_key]["buf"]
                ofloor = oplan.live_floor(state[oplan.win_key])
                vals, mask = splan.key_fn(wout, ctx)
                vals = jnp.broadcast_to(jnp.asarray(vals), (N,))
                p_i = hash_partition_dev(vals, P)
                if mask is not None:
                    p_i = jnp.where(
                        jnp.broadcast_to(jnp.asarray(mask, bool), (N,)),
                        jnp.int32(0), p_i)
                cand_g = opidx["gseq"][p_i]                     # [N, Wp]
                cand_live = (cand_g >= ofloor) & (cand_g >= 0)
                cand_slot = (jnp.clip(cand_g, 0) % W).astype(jnp.int32)
                Wp = oplan.Wp
                ev: Dict[str, jnp.ndarray] = {TS_KEY: wout[TS_KEY][:, None]}
                for a in other.definition.attributes:
                    ev[other.prefix + a.name] = oring[a.name][cand_slot]
                    ev[other.prefix + a.name + "?"] = \
                        oring[a.name + "?"][cand_slot]
                for a in side.definition.attributes:
                    ev[side.prefix + a.name] = wout[a.name][:, None]
                    ev[side.prefix + a.name + "?"] = \
                        wout[a.name + "?"][:, None]
                cond = (on_cond(ev, ctx) if on_cond is not None
                        else jnp.ones((N, Wp), bool))
                cond = jnp.broadcast_to(cond, (N, Wp))
                match = row_live[:, None] & cand_live & cond
                no_match = (row_live & ~jnp.any(match, axis=1)
                            & side.outer & side.triggers)
                one_sided = no_match | (
                    wout[VALID_KEY] & (wout[TYPE_KEY] == RESET))
                NW = N * (Wp + 1)
                joined = _materialize(wout, ev, match, one_sided, N, Wp)
                # emission-order key: (trigger row, LEGACY ring slot) —
                # sorting by it reproduces the [N, W+1] row-major order
                # of the broadcast probe exactly (one-sided rows at W)
                stride = jnp.int64(W + 1)
                slot_cols = jnp.concatenate(
                    [cand_slot.astype(jnp.int64),
                     jnp.full((N, 1), W, jnp.int64)], axis=1)
                okey = (jnp.arange(N, dtype=jnp.int64)[:, None] * stride
                        + slot_cols).reshape(NW)
                okey = jnp.where(joined[VALID_KEY], okey, _BIG)
                order = jnp.argsort(okey, stable=True)
                joined = {k: v[order] for k, v in joined.items()}
            else:
                # ---- legacy-layout probe (P=1 / untriggering side /
                # passthrough other side): identical to the broadcast path
                pcols, pvalid_o = other.window_stage.contents(
                    state[oplan.win_key])
                Wo = pvalid_o.shape[0]
                ev = {TS_KEY: wout[TS_KEY][:, None]}
                for a in other.definition.attributes:
                    ev[other.prefix + a.name] = pcols[a.name][None, :]
                    ev[other.prefix + a.name + "?"] = \
                        pcols[a.name + "?"][None, :]
                for a in side.definition.attributes:
                    ev[side.prefix + a.name] = wout[a.name][:, None]
                    ev[side.prefix + a.name + "?"] = \
                        wout[a.name + "?"][:, None]
                pv = pvalid_o[None, :]
                if side.triggers:
                    cond = (on_cond(ev, ctx) if on_cond is not None
                            else jnp.ones((N, Wo), bool))
                    cond = jnp.broadcast_to(cond, (N, Wo))
                    match = row_live[:, None] & jnp.broadcast_to(
                        pv, (N, Wo)) & cond
                else:
                    match = jnp.zeros((N, Wo), bool)
                no_match = (row_live & ~jnp.any(match, axis=1)
                            & side.outer & side.triggers)
                one_sided = no_match | (
                    wout[VALID_KEY] & (wout[TYPE_KEY] == RESET))
                joined = _materialize(wout, ev, match, one_sided, N, Wo)

            if strrank is not None:
                joined[STR_RANK] = strrank

            # ---- cross-stream total order: every dispatched step (either
            # side) increments ONE sequence; the meta carries it so the
            # pump's drain can verify FIFO == dispatch order
            seq = state[SEQ_KEY] + 1
            new_state[SEQ_KEY] = seq

            from siddhi_tpu.core.query.runtime import pack_meta

            if split:
                if notify is not None:
                    joined["__notify__"] = notify
                joined["__overflow__"] = ovbits
                out = pack_meta(joined)
                out["__meta__"] = jnp.concatenate(
                    [out["__meta__"]] + _meta_suffix(new_state, seq))
                return new_state, out

            new_state["sel"], out = sel.apply(state["sel"], joined, ctx)
            sel_ov = out.pop("__overflow__", None)
            if sel_ov is not None:
                ovbits = ovbits | jnp.where(
                    jnp.asarray(sel_ov).astype(jnp.int32) > 0, 8, 0)
            out["__overflow__"] = ovbits
            if notify is not None:
                out["__notify__"] = notify
            out = pack_meta(out)
            out["__meta__"] = jnp.concatenate(
                [out["__meta__"]] + _meta_suffix(new_state, seq))
            return new_state, out

        return step
