from siddhi_tpu.core.trigger.trigger import TriggerRuntime

__all__ = ["TriggerRuntime"]
