"""Triggers: ``define trigger T at ('start' | every <time> | '<cron>')``.

Mirror of reference ``core/trigger/{StartTrigger,PeriodicTrigger.java:36,
CronTrigger.java:46}``: a trigger defines a stream ``T (triggered_time
long)`` and publishes one event per firing. Cron triggers evaluate their
next fire time with the same Quartz-subset schedule the cron window uses
(``ops/host_windows.CronSchedule``) and chain through the scheduler.
"""

from __future__ import annotations

from siddhi_tpu.core.event import Event
from siddhi_tpu.query_api.definitions import TriggerDefinition


class TriggerRuntime:
    def __init__(self, definition: TriggerDefinition, junction, app_context,
                 barrier=None):
        self.definition = definition
        self.junction = junction
        self.app_context = app_context
        self._barrier = barrier  # the app's quiesce gate (InputEntryValve role)
        self._job = None
        self._cron = None
        self._stopped = False
        if definition.cron is not None:
            from siddhi_tpu.ops.host_windows import CronSchedule

            self._cron = CronSchedule(definition.cron)

    def start(self):
        scheduler = self.app_context.scheduler
        if self.definition.at_start:
            ts = self.app_context.timestamp_generator.current_time()
            self._fire(ts)
        elif self.definition.at_every is not None and scheduler is not None:
            self._job = scheduler.schedule_periodic(self.definition.at_every, self._fire)
        elif self._cron is not None and scheduler is not None:
            now = int(self.app_context.timestamp_generator.current_time())
            scheduler.notify_at(self._cron.next_fire(now), self._cron_fire)

    def stop(self):
        self._stopped = True
        if self._job is not None and self.app_context.scheduler is not None:
            self.app_context.scheduler.cancel(self._job)
            self._job = None

    def _cron_fire(self, ts: int):
        if self._stopped:
            return
        self._fire(ts)
        scheduler = self.app_context.scheduler
        if scheduler is not None:
            scheduler.notify_at(self._cron.next_fire(int(ts)), self._cron_fire)

    def _fire(self, ts: int):
        events = [Event(timestamp=int(ts), data=[int(ts)])]
        if self._barrier is not None:
            with self._barrier:
                self.junction.send_events(events)
        else:
            self.junction.send_events(events)
