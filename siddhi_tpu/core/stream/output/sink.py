"""Sink SPI: publishing stream output to external transports.

Mirror of the reference transport-out boundary
(``stream/output/sink/Sink.java``, ``InMemorySink.java``,
``sink/distributed/*.java`` distribution strategies). A ``SinkRuntime``
subscribes the stream's junction like any other receiver; events are
mapped to payloads by a ``SinkMapper`` and published — through a single
transport, or through several destinations picked by a distribution
strategy (roundRobin / broadcast / partitioned, reference
``RoundRobinDistributionStrategy.java`` etc.).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from siddhi_tpu.core.stream.input.source import ConnectionUnavailableException
from siddhi_tpu.core.stream.junction import Receiver
from siddhi_tpu.core.util.transport import InMemoryBroker
from siddhi_tpu.query_api.definitions import StreamDefinition
from siddhi_tpu.resilience import stat_count
from siddhi_tpu.resilience.retry import RetryPolicy


class SinkMapper:
    """Maps events to transport payloads (reference SinkMapper.java)."""

    def init(self, stream_def: StreamDefinition, options: Dict[str, str]):
        self.stream_def = stream_def
        self.options = options

    def map(self, event) -> object:
        raise NotImplementedError


class PassThroughSinkMapper(SinkMapper):
    def map(self, event):
        return list(event.data)


class JsonSinkMapper(SinkMapper):
    def map(self, event):
        return json.dumps({"event": {
            a.name: event.data[i] for i, a in enumerate(self.stream_def.attributes)
        }})


SINK_MAPPERS = {
    "passthrough": PassThroughSinkMapper,
    "json": JsonSinkMapper,
}


class Sink:
    """Transport SPI (reference Sink.java). Subclasses publish payloads."""

    def init(self, stream_def: StreamDefinition, options: Dict[str, str],
             app_context) -> None:
        self.stream_def = stream_def
        self.options = options
        self.app_context = app_context

    def connect(self) -> None:
        pass

    def publish(self, payload) -> None:
        raise NotImplementedError

    def disconnect(self) -> None:
        pass

    def destroy(self) -> None:
        pass


class InMemorySink(Sink):
    """``@sink(type='inMemory', topic='...')`` (reference InMemorySink)."""

    def init(self, stream_def, options, app_context):
        super().init(stream_def, options, app_context)
        self.topic = options.get("topic")
        if self.topic is None:
            raise ValueError("@sink(type='inMemory') needs a 'topic'")

    def publish(self, payload):
        InMemoryBroker.publish(self.topic, payload)


class LogSink(Sink):
    """``@sink(type='log')`` — prints events (reference siddhi-io log sink
    / EventPrinter-style observability)."""

    def init(self, stream_def, options, app_context):
        super().init(stream_def, options, app_context)
        self.prefix = options.get("prefix", stream_def.id)

    def publish(self, payload):
        print(f"{self.prefix} : {payload}")


SINKS = {
    "inmemory": InMemorySink,
    "log": LogSink,
}


# ------------------------------------------------------- distribution


class DistributionStrategy:
    """Chooses destination indexes per event (reference
    ``sink/distributed/DistributionStrategy.java``)."""

    def init(self, n_destinations: int, stream_def: StreamDefinition,
             options: Dict[str, str]):
        self.n = n_destinations
        self.stream_def = stream_def
        self.options = options

    def destinations_for(self, event) -> List[int]:
        raise NotImplementedError


class RoundRobinStrategy(DistributionStrategy):
    def init(self, n, stream_def, options):
        super().init(n, stream_def, options)
        self._i = 0

    def destinations_for(self, event):
        d = self._i % self.n
        self._i += 1
        return [d]


class BroadcastStrategy(DistributionStrategy):
    def destinations_for(self, event):
        return list(range(self.n))


class PartitionedStrategy(DistributionStrategy):
    """Hash of ``partitionKey`` attribute picks the destination
    (reference PartitionedDistributionStrategy.java)."""

    def init(self, n, stream_def, options):
        super().init(n, stream_def, options)
        key = options.get("partitionKey")
        if key is None:
            raise ValueError("partitioned distribution needs 'partitionKey'")
        self._idx = [a.name for a in stream_def.attributes].index(key)

    def destinations_for(self, event):
        return [hash(event.data[self._idx]) % self.n]


STRATEGIES = {
    "roundrobin": RoundRobinStrategy,
    "broadcast": BroadcastStrategy,
    "partitioned": PartitionedStrategy,
}


class SinkRuntime(Receiver):
    """One @sink subscription on a stream junction."""

    def __init__(self, sinks: List[Sink], mapper: SinkMapper,
                 strategy: Optional[DistributionStrategy], definition,
                 app_context=None, retry_policy=None):
        self.sinks = sinks
        self.mapper = mapper
        self.strategy = strategy
        self.definition = definition
        self.app_context = app_context
        # shared backoff policy (resilience/retry.py): unlike a source
        # reconnect, a publish retry holds the junction's delivery path —
        # bounded attempts, then RetryExhausted rides the stream's
        # @OnError routing like any other processing failure
        self.retry_policy = retry_policy if retry_policy is not None \
            else RetryPolicy(initial_ms=10, max_ms=1_000, max_attempts=8)
        self._connected = False

    def connect(self):
        for s in self.sinks:
            s.connect()
        self._connected = True

    def _publish(self, sink: Sink, payload):
        if self.retry_policy is None:
            sink.publish(payload)
            return
        self.retry_policy.run(
            lambda: sink.publish(payload),
            (ConnectionUnavailableException,),
            # app shutdown (or a supervisor abandoning the runtime) must
            # not sit out the remaining backoff sleeps per pending event
            stop=lambda: getattr(self.app_context, "stopped", False),
            on_retry=lambda *_: stat_count(
                self.app_context, "resilience.sink_retries"))

    def receive(self, events):
        from siddhi_tpu.observability.tracing import span

        with span("sink.publish", stream=self.definition.id,
                  events=len(events)):
            for e in events:
                if e.is_expired:
                    continue
                payload = self.mapper.map(e)
                if self.strategy is None:
                    self._publish(self.sinks[0], payload)
                else:
                    for d in self.strategy.destinations_for(e):
                        self._publish(self.sinks[d], payload)

    def receive_batch(self, batch, junction=None):
        dictionary = (junction.app_context.string_dictionary
                      if junction is not None else None)
        self.receive(batch.to_events(
            [(a.name, a.type) for a in self.definition.attributes], dictionary))

    def shutdown(self):
        if self._connected:
            for s in self.sinks:
                s.disconnect()
        for s in self.sinks:
            s.destroy()


def create_sink_runtime(ann, stream_def: StreamDefinition, app_context,
                        extensions: Dict[str, type]) -> SinkRuntime:
    """Build a SinkRuntime from ``@sink(type='...', ..., @map(...),
    @distribution(strategy='...', @destination(...), ...))``."""
    from siddhi_tpu.ops.expressions import resolve_in

    opts = {k: v for k, v in ann.elements if k is not None}
    type_name = (opts.pop("type", None) or "").lower()
    if not type_name:
        raise ValueError("@sink needs a type")
    cls = resolve_in(extensions, "sink", type_name) or SINKS.get(type_name)
    if cls is None:
        raise ValueError(f"unknown sink type '{type_name}'")

    map_ann = ann.annotation("map")
    map_opts = {}
    map_type = "passthrough"
    if map_ann is not None:
        map_opts = {k: v for k, v in map_ann.elements if k is not None}
        map_type = (map_opts.pop("type", None) or "passthrough").lower()
    mcls = resolve_in(extensions, "sinkMapper", map_type) or SINK_MAPPERS.get(map_type)
    if mcls is None:
        raise ValueError(f"unknown sink map type '{map_type}'")
    mapper = mcls()
    mapper.init(stream_def, map_opts)

    dist_ann = ann.annotation("distribution")
    if dist_ann is None:
        sink = cls()
        sink.init(stream_def, opts, app_context)
        return SinkRuntime([sink], mapper, None, stream_def,
                           app_context=app_context)

    dist_opts = {k: v for k, v in dist_ann.elements if k is not None}
    strat_name = (dist_opts.pop("strategy", None) or "roundrobin").lower()
    scls = STRATEGIES.get(strat_name)
    if scls is None:
        raise ValueError(f"unknown distribution strategy '{strat_name}'")
    sinks = []
    for dest in dist_ann.annotations:
        if dest.name.lower() != "destination":
            continue
        d_opts = dict(opts)
        d_opts.update({k: v for k, v in dest.elements if k is not None})
        sink = cls()
        sink.init(stream_def, d_opts, app_context)
        sinks.append(sink)
    if not sinks:
        raise ValueError("@distribution needs at least one @destination")
    strategy = scls()
    strategy.init(len(sinks), stream_def, dist_opts)
    return SinkRuntime(sinks, mapper, strategy, stream_def,
                       app_context=app_context)


