"""StreamCallback: user hook receiving all events of a stream.

Mirror of reference ``core/stream/output/StreamCallback.java`` — subscribe
to a junction, override ``receive``.
"""

from __future__ import annotations

from typing import List

from siddhi_tpu.core.event import Event
from siddhi_tpu.core.stream.junction import Receiver


class StreamCallback(Receiver):
    stream_id: str = ""

    def receive(self, events: List[Event]):
        raise NotImplementedError

    # parity helper with reference's to Event[] signature
    def receive_events(self, events: List[Event]):
        self.receive(events)
