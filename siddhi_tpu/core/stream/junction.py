"""StreamJunction: per-stream pub/sub bus.

Mirror of reference ``core/stream/StreamJunction.java``: each defined stream
gets a junction; producers publish event chunks, receivers (query input
processors, stream callbacks, sinks) subscribe. Sync mode fans out directly
(``StreamJunction.java:175-178``); ``@Async`` buffering is a host-side queue
+ worker thread (the Disruptor's role, ``:276-313``) — see
``enable_async``. ``@OnError(action='STREAM')`` fault routing
(``:368-430``) publishes failed events + error into the shadow ``!stream``
junction.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
import traceback
from typing import List, Optional

from siddhi_tpu.analysis.guards import guarded
from siddhi_tpu.analysis.locks import make_lock
from siddhi_tpu.core.event import Event
from siddhi_tpu.observability import journey
from siddhi_tpu.observability.tracing import span
from siddhi_tpu.query_api.definitions import StreamDefinition

log = logging.getLogger(__name__)

# marker for "no batch in flight" (None is the queue's stop sentinel)
_NOTHING = object()

# the junction whose delivery loop is running on THIS thread: receivers
# reached through the Event path (Receiver.receive has no junction
# parameter) read it so their pipelined completions still know their
# delivering junction (error attribution + completion-latency feedback)
_DELIVERING = threading.local()


def current_delivering_junction() -> Optional["StreamJunction"]:
    return getattr(_DELIVERING, "junction", None)

# worker heartbeat floor: the drain loop polls its queue with this bound,
# so a healthy worker — even an idle one — bumps its beats counter at
# least ~10x/sec and the supervisor can tell wedged from idle (its
# wedge timeout is clamped to a multiple of this floor)
_IDLE_POLL_S = 0.1


class FatalQueryError(RuntimeError):
    """Framework-infrastructure failure (dense-capacity overflow knobs):
    unlike per-event processing errors — which the junction logs/routes
    per @OnError like the reference — these always propagate to the
    sender."""


class Receiver:
    """Subscriber interface (reference StreamJunction.Receiver)."""

    def receive(self, events: List[Event]):
        raise NotImplementedError

    def receive_batch(self, batch, junction: "StreamJunction"):
        """Columnar fast path: receivers that can consume a HostBatch
        directly override this; the default decodes to Events (so every
        receiver keeps working when a producer uses the bulk API)."""
        self.receive(junction.decode_events(batch))


@guarded
class StreamJunction:
    # only the adaptive-batch control loop's read-modify-write state is
    # lock-guarded; the resilience counters (`_beats`, `_inflight`) and
    # the delivery-thread-confined registries (`receivers`,
    # `_pending_mutations`, `_wal_seq_of`, `_jt_enq`) are deliberately
    # lock-free — gauges and the supervisor read them racily on purpose
    GUARDED_BY = {"_lat_ewma": "adapt", "_cur_batch": "adapt"}

    def __init__(self, definition: StreamDefinition, app_context, fault_junction: Optional["StreamJunction"] = None):
        self.definition = definition
        self.app_context = app_context
        self.receivers: List[Receiver] = []
        self.fault_junction = fault_junction
        self.on_error_action = "LOG"  # LOG | STREAM (from @OnError)
        self._async = False
        self._queue: Optional[queue.Queue] = None
        self._worker: Optional[threading.Thread] = None
        self._batch_size = 256
        self._cur_batch = 256
        self._max_delay_s: Optional[float] = None
        self._latency_target_ms: Optional[float] = None
        self._lat_ewma = 0.0
        # _adapt used to run only on the single worker thread; pipelined
        # completions now also feed it from whichever thread drains the
        # pump, so the EWMA/cap read-modify-write needs a lock
        self._adapt_lock = make_lock("adapt")
        self._running = False
        self._fatal: Optional[Exception] = None  # async worker's FatalQueryError
        # resilience hooks (resilience/supervisor.py, resilience/faults.py):
        # the in-flight batch survives a worker death for its replacement;
        # the generation token retires late-waking stale workers; beats is
        # the supervisor's liveness counter; fault_hook is the injection
        # point the drain loop polls
        self._inflight = _NOTHING
        self._inflight_owner = None    # thread that parked _inflight
        self._gen = 0
        self._beats = 0
        self.fault_hook = None
        # overload armor (resilience/overload.py): queued unit id -> its
        # ingest-WAL sequence number, so a shed unit's record can be
        # discarded (replay must cover exactly the non-shed suffix).
        # Empty unless the app registered quotas AND runs a WAL.
        self._wal_seq_of: dict = {}
        # batch-journey tracing (observability/journey.py): queued unit
        # id -> enqueue perf_counter, so the worker can attribute the
        # @Async queue residence. Empty unless journeys are enabled.
        self._jt_enq: dict = {}
        # deferred receiver-set mutations (autopilot fusion actuator):
        # drained by the DELIVERING thread before it fans a batch out,
        # so the receiver list is never rewired mid-iteration. Empty
        # unless a controller scheduled a dissolve/re-form.
        self._pending_mutations: List = []

    def defer_mutation(self, fn) -> None:
        """Schedule ``fn()`` to run on the next delivering thread BEFORE
        it iterates receivers — the only point where the receiver set
        may be rewired live (fused-group dissolve/re-form). A junction
        that never delivers again simply never applies it."""
        self._pending_mutations.append(fn)

    def _drain_mutations(self) -> None:
        while self._pending_mutations:
            fn = self._pending_mutations.pop(0)
            try:
                fn()
            except Exception:  # noqa: BLE001 — a failed rewire must not
                # poison the delivery that happened to drain it
                logging.getLogger(__name__).exception(
                    "deferred receiver mutation failed on stream '%s'",
                    self.definition.id)

    def subscribe(self, receiver: Receiver):
        if receiver not in self.receivers:
            self.receivers.append(receiver)

    def replace_receivers(self, members: List[Receiver], group: Receiver):
        """Swap a contiguous run of subscribed receivers for ONE fused
        receiver at the run's position (fan-out fusion,
        ``core/plan/fanout_plan.py``) — every other subscriber keeps its
        delivery slot, so callback/sink ordering is unchanged."""
        i = self.receivers.index(members[0])
        for m in members:
            self.receivers.remove(m)
        self.receivers.insert(i, group)

    def enable_async(self, buffer_size: int = 1024, batch_size: int = 256,
                     max_delay_ms: Optional[float] = None,
                     latency_target_ms: Optional[float] = None):
        """@Async: decouple producers via a bounded queue + one worker that
        re-batches up to batch_size (the role of StreamHandler.java:57-71).

        Adaptive batching (SURVEY §7 hard part 6 — batch size trades p99
        against events/sec; the reference's Disruptor has no such knob,
        its batch is whatever the ring hands the worker):
        - ``max.delay`` ('5 ms', '1 sec', …): a partial batch waits at
          most this long for more events before delivering — bounds the
          queueing half of tail latency under trickle load.
        - ``latency.target``: a closed loop on the PROCESSING half. Each
          delivery is timed; when the smoothed per-delivery latency
          overshoots the target the worker halves its current batch cap
          (floor 16), and when it runs under half the target the cap
          climbs 25% back toward ``batch.size``. Throughput degrades
          gracefully instead of p99 exploding when a query's step gets
          slower (capacity regrow, device contention)."""
        self._async = True
        self._batch_size = batch_size
        self._max_delay_s = (max_delay_ms / 1000.0
                             if max_delay_ms is not None else None)
        self._latency_target_ms = latency_target_ms
        with self._adapt_lock:
            # a live re-enable (autopilot re-tune) races the control
            # loop's read-modify-write in _adapt — same lock
            self._cur_batch = batch_size      # adaptive cap (<= batch_size)
            self._lat_ewma = 0.0
        self._queue = queue.Queue(maxsize=buffer_size)
        # observability: queue depth + in-flight unit gauges, scraped via
        # GET /metrics (telemetry is level-independent — a wedging @Async
        # queue must be visible whether or not @app:statistics is on)
        tel = getattr(self.app_context, "telemetry", None)
        if tel is not None:
            sid = self.definition.id
            tel.gauge(f"junction.{sid}.queue_depth", self._queue.qsize)
            tel.gauge(f"junction.{sid}.inflight_batches",
                      lambda j=self: 0 if j._inflight is _NOTHING else 1)

    def start_processing(self):
        self._running = True
        if self._async:
            self._start_worker()

    def _start_worker(self):
        self._gen += 1
        self._worker = threading.Thread(
            target=self._drain, args=(self._gen,), daemon=True,
            name=f"junction-{self.definition.id}-g{self._gen}")
        self._worker.start()

    def restart_worker(self):
        """Replace a dead or wedged worker (supervisor path): the queue and
        any in-flight batch stay intact; the generation bump makes a stale
        worker that later wakes exit without double-delivering."""
        if not (self._async and self._running):
            return
        self._start_worker()

    def stop_processing(self):
        self._running = False
        if self._worker is not None:
            if self._fatal is None:
                self._queue.put(None)
            else:
                # the worker died on a fatal error with producers possibly
                # having filled the queue — a blocking put would hang
                # shutdown on a queue nobody drains
                try:
                    self._queue.put_nowait(None)
                except queue.Full:
                    pass
            self._worker.join(timeout=5)
            self._worker = None

    def send_events(self, events: List[Event], wal_seq: Optional[int] = None):
        if not events:
            return
        sm = self.app_context.statistics_manager
        if sm is not None and sm.level >= 1:
            sm.throughput_tracker(self.definition.id).add(len(events))
        if self._fatal is not None:
            # the async worker died on a framework failure — surface it to
            # the producer instead of blocking on a queue nobody drains
            raise self._fatal
        if self._async and self._running:
            self._enqueue(events, wal_seq)
        else:
            self._deliver(events)
            # synchronous sends keep synchronous semantics: any batches
            # the receivers pipelined (CompletionPump) drain before the
            # send returns — the caller observes its outputs immediately,
            # exactly as before the pump existed. Overlap comes from
            # producers that deliver back-to-back (@Async workers).
            # own_only: this sender's dispatches and cascades, not an
            # unrelated busy stream's in-flight pulls.
            self._flush_pipeline(own_only=True)

    def decode_events(self, batch) -> List[Event]:
        return batch.to_events(
            [(a.name, a.type) for a in self.definition.attributes],
            self.app_context.string_dictionary,
            object_meta=getattr(self.definition, "object_elem_types", None),
            object_multi=getattr(self.definition, "object_multi_attrs", None),
        )

    def send_batch(self, batch, wal_seq: Optional[int] = None):
        """Columnar publish (no Event objects). @Async junctions enqueue the
        batch behind any pending event chunks (producer ordering is kept);
        it is delivered as one unit — already a batch."""
        sm = self.app_context.statistics_manager
        if sm is not None and sm.level >= 1:
            sm.throughput_tracker(self.definition.id).add(int(batch.size))
        if self._fatal is not None:
            raise self._fatal
        if self._async and self._running:
            self._enqueue(batch, wal_seq)
        else:
            self._deliver_batch(batch)
            self._flush_pipeline(own_only=True)   # see send_events

    def _flush_pipeline(self, own_only: bool = False):
        """Drain the app's CompletionPump (no-op when empty or when this
        is a nested flush inside an emit cascade). ``own_only`` (sync
        senders) drains only this thread's own dispatches and cascades;
        worker-loop flushes drain everything — including entries a dead
        predecessor worker left riding. The pump routes each drain error
        through the ENTRY's own delivering junction (fatals arm that
        junction's ``_fatal``, peer failures notify the supervisor, the
        rest log) — this junction only propagates the raise so a
        synchronous sender (or the worker loop) still sees the failure
        at the flush point."""
        pump = getattr(self.app_context, "completion_pump", None)
        if pump is None or not pump.has_pending:
            return
        pump.flush(own_only=own_only)

    def record_completion(self, elapsed_ms: float):
        """Completion-latency feedback from the CompletionPump: the TRUE
        deliver->emit time of a pipelined batch (the worker's own timing
        only saw the dispatch slice, which returns instantly once a batch
        rides the pipeline) — without this, ``latency.target`` would see
        near-zero latency and never shrink the batch cap on a slow
        device step."""
        self._adapt(elapsed_ms)

    def _enqueue(self, item, wal_seq: Optional[int] = None):
        """Producer-side @Async enqueue, counting backpressure stalls
        (sends that found the queue FULL and had to block) so sizing
        regressions are visible on /metrics before they become p99.

        Overload armor (resilience/overload.py): with quotas registered,
        admission runs FIRST — past the queue quota the stream's shed
        policy engages (shed_newest/shed_oldest drop a unit and discard
        its WAL record; block waits bounded, escalating to the
        supervisor). The blocking fallback itself is BOUNDED in all
        configurations: it re-checks ``_fatal`` each slice (a worker
        dying mid-wait used to leave the producer parked forever) and
        escalates to the supervisor every ``block_timeout_s`` so a
        wedged consumer is replaced instead of deadlocking the
        producer with only a stall counter to show for it."""
        from siddhi_tpu.resilience.overload import (
            BLOCK_PUT_SLICE_S,
            DEFAULT_BLOCK_TIMEOUT_S,
        )

        ctl = getattr(self.app_context, "overload", None)
        if ctl is not None and not ctl.admit(self, item, wal_seq):
            return                    # shed (counted; WAL record discarded)
        if wal_seq is not None:
            # mapped BEFORE the put: once queued, the worker (or a
            # shed_oldest eviction) may pop it at any moment
            self._wal_seq_of[id(item)] = wal_seq
        if journey.enabled():
            # queue-residence stamp (same before-the-put discipline).
            # Units evicted by shed_oldest leave stale stamps behind; at
            # most qsize stamps can be LIVE, so past that bound the
            # OLDEST surplus is stale (insertion-ordered dict) — evict
            # exactly it, never the live backlog's stamps (wiping those
            # would blind queue attribution during the very overload
            # episode being diagnosed)
            live_cap = (self._queue.maxsize or 8192) + 256
            while len(self._jt_enq) > live_cap:
                try:
                    # concurrent producers race this unlocked dict: pop
                    # tolerates losing the key, and a torn iterator just
                    # retries on the next enqueue
                    self._jt_enq.pop(next(iter(self._jt_enq)), None)
                except (StopIteration, RuntimeError):
                    break
            self._jt_enq[id(item)] = time.perf_counter()
        try:
            self._queue.put_nowait(item)
            return
        except queue.Full:
            pass
        tel = getattr(self.app_context, "telemetry", None)
        if tel is not None:
            tel.count(f"junction.{self.definition.id}.backpressure_stalls")
        timeout_s = (ctl.block_timeout_s if ctl is not None
                     else DEFAULT_BLOCK_TIMEOUT_S)
        waited = 0.0
        while True:
            try:
                self._queue.put(item, timeout=BLOCK_PUT_SLICE_S)
                return
            except queue.Full:
                pass
            if self._fatal is not None:
                self._wal_seq_of.pop(id(item), None)
                self._jt_enq.pop(id(item), None)
                raise self._fatal
            waited += BLOCK_PUT_SLICE_S
            if waited >= timeout_s:
                waited = 0.0
                if ctl is not None:
                    ctl.escalate(self)
                else:
                    self._escalate_default()

    def _escalate_default(self) -> None:
        """Bounded-wait escalation for apps WITHOUT overload quotas: the
        blocked producer is still visible (counter + log) and the
        supervisor still gets a chance to replace a wedged consumer."""
        from siddhi_tpu.resilience import stat_count

        tel = getattr(self.app_context, "telemetry", None)
        if tel is not None:
            tel.count(f"junction.{self.definition.id}.enqueue_timeouts")
        stat_count(self.app_context, "resilience.enqueue_timeouts")
        sup = getattr(self.app_context, "supervisor", None)
        if sup is not None and hasattr(sup, "notify_backpressure"):
            try:
                sup.notify_backpressure(self)
                return
            except Exception:  # noqa: BLE001 — escalation must not mask
                log.exception("backpressure escalation failed")
        log.warning(
            "producer blocked on full @Async queue of stream '%s' — the "
            "consumer is not draining (wedged worker? attach "
            "rt.supervise() to auto-replace it)", self.definition.id)

    def _deliver_batch(self, batch, enq_t=None):
        from siddhi_tpu.core.event import HostBatch, LazyColumns

        if self._pending_mutations:
            self._drain_mutations()
        with span("junction.dispatch", stream=self.definition.id,
                  rows=int(batch._size) if batch._size is not None else -1):
            prev = current_delivering_junction()
            _DELIVERING.junction = self
            jt = journey.enabled()
            # queue-residence scope: receivers of THIS delivery read it;
            # nested sync deliveries (emit cascades) mask it (journey.py)
            prev_q = journey.push_delivery_queue_wait(enq_t) if jt else None
            try:
                for r in self.receivers:
                    # receivers mutate batch.cols in place (filters, key
                    # columns) — hand each its own dict so mutations don't
                    # leak across; LazyColumns keeps device-held outputs
                    # unpulled until read
                    try:
                        sub = HostBatch(LazyColumns(batch.cols),
                                        size=batch._size)
                        # pack stamp rides the re-wrap; each receiver
                        # forks its own journey (journey.begin)
                        sub.journey = batch.journey
                        r.receive_batch(sub, self)
                    except Exception as e:  # noqa: BLE001 — fault routing
                        self.handle_error(self.decode_events(batch), e)
            finally:
                _DELIVERING.junction = prev
                if jt:
                    journey.pop_delivery_queue_wait(prev_q)

    def _adapt(self, elapsed_ms: float):
        """Latency-target control loop: EWMA the delivery latency, shrink
        the batch cap on overshoot, regrow on sustained headroom. Every
        @Async delivery's latency also lands in the junction's histogram
        tracker — the batcher's contribution to tail latency is exactly
        what max.delay / latency.target tune."""
        sm = self.app_context.statistics_manager
        if sm is not None and sm.level >= 1:
            sm.latency_tracker(
                f"junction.{self.definition.id}").record(elapsed_ms)
        target = self._latency_target_ms
        if target is None:
            return
        with self._adapt_lock:
            self._lat_ewma = (0.7 * self._lat_ewma + 0.3 * elapsed_ms
                              if self._lat_ewma else elapsed_ms)
            if self._lat_ewma > target:
                self._cur_batch = max(16, self._cur_batch // 2)
                self._lat_ewma = target  # re-converge from the new cap
            elif (self._lat_ewma < target / 2
                  and self._cur_batch < self._batch_size):
                self._cur_batch = min(self._batch_size,
                                      max(self._cur_batch + 1,
                                          int(self._cur_batch * 1.25)))

    def _pump_submits(self) -> int:
        pump = getattr(self.app_context, "completion_pump", None)
        return pump.submits_of(self) if pump is not None else 0

    def _timed_deliver(self, events: List[Event], enq_t=None):
        ctl = getattr(self.app_context, "overload", None)
        if ctl is not None:
            # weighted fair scheduling (resilience/overload.py): a worker
            # of an app running over its fair share yields briefly while
            # a sibling app is backlogged — one flooded tenant must not
            # monopolize the cores its siblings' workers need
            ctl.throttle(len(events))
        t0 = time.perf_counter()
        n0 = self._pump_submits()
        self._deliver(events, enq_t)
        if self._pump_submits() == n0:
            # pipelined deliveries return at dispatch; their near-zero
            # slice must not feed the latency loop — record_completion
            # supplies the TRUE sample at drain instead
            self._adapt((time.perf_counter() - t0) * 1000.0)

    def _timed_deliver_batch(self, batch, enq_t=None):
        # columnar unit variant of _timed_deliver — same pipelined-skip
        # and fair-throttle rules; the two must stay in lock-step
        ctl = getattr(self.app_context, "overload", None)
        if ctl is not None:
            n = batch._size   # known count only — never force a pull here
            ctl.throttle(int(n) if n is not None else 1)
        t0 = time.perf_counter()
        n0 = self._pump_submits()
        self._deliver_batch(batch, enq_t)
        if self._pump_submits() == n0:
            self._adapt((time.perf_counter() - t0) * 1000.0)

    def _drain(self, gen: Optional[int] = None):
        if gen is None:
            gen = self._gen
        while True:
            self._beats += 1
            hook = self.fault_hook
            if hook is not None:
                # fault-injection point (resilience/faults.py): the hook
                # may raise (simulated worker crash — the in-flight batch
                # stays parked for the replacement) or block (wedge)
                try:
                    hook(self)
                except Exception as e:  # noqa: BLE001 — injected death
                    log.warning("junction '%s' worker killed: %s",
                                self.definition.id, e)
                    return
            if gen != self._gen:
                return     # superseded by a restart while wedged/blocked
            if self._inflight is not _NOTHING:
                owner = self._inflight_owner
                if (owner is not None and owner.is_alive()
                        and owner is not threading.current_thread()):
                    # a superseded-but-ALIVE predecessor still owns the
                    # unit (slow delivery, e.g. a first-batch jit
                    # compile): adopting it would double-deliver when the
                    # predecessor eventually completes. Wait for it to
                    # finish or die, beating so the supervisor sees this
                    # worker as healthy (and keeping queue order intact).
                    time.sleep(_IDLE_POLL_S)
                    continue
                item = self._inflight    # predecessor died mid-delivery
                self._inflight_owner = threading.current_thread()
                enq_t = None             # stamp went with the predecessor
            else:
                try:
                    item = self._queue.get(timeout=_IDLE_POLL_S)
                    if self._wal_seq_of:
                        # dequeued for delivery: its WAL record is now
                        # "will be processed" — drop the shed handle
                        self._wal_seq_of.pop(id(item), None)
                    enq_t = (self._jt_enq.pop(id(item), None)
                             if self._jt_enq else None)
                except queue.Empty:
                    # idle: drain any batches still riding the pipeline —
                    # bounds emission lag under trickle load to one idle
                    # poll (this is what lets scheduler-driven windows
                    # and absent deadlines ride the pump)
                    self._flush_pipeline()
                    if not self._running and self._queue.empty():
                        return   # stop raced our sentinel away
                    continue
                self._inflight = item
                self._inflight_owner = threading.current_thread()
                if gen != self._gen:
                    return   # superseded mid-fetch: item handed over
            if item is None:
                self._inflight = _NOTHING
                self._flush_pipeline()   # the worker's last act: nothing
                #                          may stay riding after shutdown
                return
            if not isinstance(item, list):
                # columnar HostBatch: delivered as ONE pre-formed unit
                # (the cap never splits producer batches — max.delay /
                # latency.target shape only the event-path coalescing),
                # but its delivery latency still feeds the adaptive loop
                # (unless it pipelined — see _timed_deliver)
                self._timed_deliver_batch(item, enq_t)
                self._inflight = _NOTHING
                if self._queue.empty():
                    self._flush_pipeline()
                continue
            batch = list(item)
            self._inflight = batch   # coalesced extras ride the same unit
            deadline = (time.perf_counter() + self._max_delay_s
                        if self._max_delay_s is not None else None)
            stop_after = False
            follow = None            # HostBatch that broke the coalesce
            follow_enq = None
            # re-batch pending chunks up to the (adaptive) cap; a partial
            # batch waits at most max.delay for more. The cap is read
            # ONCE per drain, under the adapt lock — the control loop
            # may rewrite it concurrently from a pump-draining thread
            with self._adapt_lock:
                cap = self._cur_batch
            while len(batch) < cap:
                try:
                    if deadline is None:
                        more = self._queue.get_nowait()
                    else:
                        wait = deadline - time.perf_counter()
                        if wait <= 0:
                            break
                        # bounded slices of the max.delay wait, beating
                        # between them — a worker waiting out a LONG
                        # coalesce deadline is healthy, and must not look
                        # wedged to the supervisor
                        more = self._queue.get(
                            timeout=min(wait, _IDLE_POLL_S))
                except queue.Empty:
                    if deadline is None or time.perf_counter() >= deadline:
                        break
                    self._beats += 1
                    continue
                if self._wal_seq_of:
                    self._wal_seq_of.pop(id(more), None)
                more_enq = (self._jt_enq.pop(id(more), None)
                            if self._jt_enq else None)
                if more is None:
                    stop_after = True
                    break
                if not isinstance(more, list):
                    follow = more
                    follow_enq = more_enq
                    break
                batch.extend(more)
            if gen != self._gen and follow is None and not stop_after:
                return   # superseded while coalescing: the (possibly
                #          grown) batch stays parked for the replacement
            # coalesced extras keep the FIRST chunk's enqueue stamp — the
            # longest (and attribution-relevant) residence of the unit
            self._timed_deliver(batch, enq_t)
            if follow is not None:
                self._inflight = follow
                self._timed_deliver_batch(follow, follow_enq)
            self._inflight = _NOTHING
            if stop_after or self._queue.empty():
                self._flush_pipeline()
            if stop_after:
                return

    def _deliver(self, events: List[Event], enq_t=None):
        if self._pending_mutations:
            self._drain_mutations()
        with span("junction.dispatch", stream=self.definition.id,
                  rows=len(events)):
            prev = current_delivering_junction()
            _DELIVERING.junction = self
            jt = journey.enabled()
            prev_q = journey.push_delivery_queue_wait(enq_t) if jt else None
            try:
                for r in self.receivers:
                    try:
                        r.receive(events)
                    except Exception as e:  # noqa: BLE001 — fault routing
                        self.handle_error(events, e)
            finally:
                _DELIVERING.junction = prev
                if jt:
                    journey.pop_delivery_queue_wait(prev_q)

    def handle_error(self, events: List[Event], e: Exception):
        from siddhi_tpu.ops.expressions import CompileError

        supervisor = getattr(self.app_context, "supervisor", None)
        if supervisor is not None:
            # cluster-peer failures trigger the recovery protocol
            # (resilience/supervisor.py); other errors are ignored there
            try:
                supervisor.notify_error(self, e)
            except Exception:  # noqa: BLE001 — supervision must not mask
                log.exception("supervisor notification failed")

        if isinstance(e, (FatalQueryError, CompileError)):
            # framework-infrastructure failures (capacity overflow knobs)
            # and deferred compile errors (first-trace design diagnostics)
            # always surface to the sender — routing them to a fault
            # stream would hide a misconfigured deployment. On an @Async
            # junction the raise unwinds the worker; the stored error makes
            # every later send re-raise instead of hanging on a full queue.
            self._fatal = e
            raise e
        if self.on_error_action == "STREAM" and self.fault_junction is not None:
            self.route_fault_events(events, e)
        else:
            # default/LOG action: log and DROP — the reference's
            # StreamJunction never propagates processing errors back to
            # the sender (FaultStreamTestCase test1/test2)
            log.error(
                "error processing events in stream '%s': %s\n%s",
                self.definition.id, e, traceback.format_exc(),
            )

    def route_fault_events(self, events: List[Event], e: Exception):
        """Publish ``events`` + error to the '!stream' fault junction —
        fault stream schema = original attrs + _error (reference
        FaultStreamEventConverter). The tail of ``handle_error``'s STREAM
        action, also used directly by receivers that do their own
        per-member attribution (fused fan-out groups)."""
        self.fault_junction.send_events([
            Event(timestamp=ev.timestamp, data=list(ev.data) + [str(e)])
            for ev in events
        ])
