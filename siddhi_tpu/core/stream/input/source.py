"""Source SPI: external transports feeding streams.

Mirror of the reference transport-in boundary
(``stream/input/source/Source.java:155-185`` connectWithRetry,
``SourceMapper.java`` payload->event mapping, ``InMemorySource.java:63``).
TPU-first inversion: mappers produce *columnar* rows where possible so the
ingest path stays vectorized (``InputHandler.send_columns``); object
payloads fall back to per-event mapping.

Lifecycle: ``SourceRuntime.connect_with_retry`` drives connect() with
exponential backoff on ``ConnectionUnavailableException``;
``pause()/resume()`` gate delivery (the snapshot service pauses sources
around persist(), reference ``SiddhiAppRuntimeImpl.persist``).
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional

from siddhi_tpu.core.util.transport import InMemoryBroker
from siddhi_tpu.query_api.definitions import StreamDefinition


class ConnectionUnavailableException(Exception):
    """Raise from Source.connect / Sink.publish when the transport is
    down — the runtime retries with backoff (reference
    ``exception/ConnectionUnavailableException.java``)."""


class SourceMapper:
    """Maps transport payloads to event rows (reference
    ``stream/input/source/SourceMapper.java``)."""

    def init(self, stream_def: StreamDefinition, options: Dict[str, str]):
        self.stream_def = stream_def
        self.options = options

    def map(self, payload) -> List[list]:
        """Return a list of data rows (one list per event)."""
        raise NotImplementedError


class PassThroughSourceMapper(SourceMapper):
    """Payload is already a data row (or list of rows)."""

    def map(self, payload) -> List[list]:
        if isinstance(payload, (list, tuple)) and payload and isinstance(
            payload[0], (list, tuple)
        ):
            return [list(p) for p in payload]
        return [list(payload)]


class JsonSourceMapper(SourceMapper):
    """``{"event": {attr: value, ...}}`` or a bare attr->value object (the
    shape of the reference's siddhi-map-json default mapping)."""

    def map(self, payload) -> List[list]:
        obj = json.loads(payload) if isinstance(payload, (str, bytes)) else payload
        if isinstance(obj, list):
            out = []
            for o in obj:
                out.extend(self.map(o))
            return out
        if "event" in obj:
            obj = obj["event"]
        return [[obj.get(a.name) for a in self.stream_def.attributes]]


SOURCE_MAPPERS = {
    "passthrough": PassThroughSourceMapper,
    "json": JsonSourceMapper,
}


class Source:
    """Transport SPI (reference ``Source.java``). Subclasses implement
    connect/disconnect and push payloads via ``self.handler(payload)``."""

    def init(self, stream_def: StreamDefinition, options: Dict[str, str],
             app_context) -> None:
        self.stream_def = stream_def
        self.options = options
        self.app_context = app_context
        self.handler = None          # set by SourceRuntime

    def connect(self) -> None:
        raise NotImplementedError

    def disconnect(self) -> None:
        pass

    def destroy(self) -> None:
        pass


class InMemorySource(Source):
    """``@source(type='inMemory', topic='...')`` — subscribes the broker
    (reference ``InMemorySource.java:63``)."""

    def init(self, stream_def, options, app_context):
        super().init(stream_def, options, app_context)
        topic = options.get("topic")
        if topic is None:
            raise ValueError("@source(type='inMemory') needs a 'topic'")
        src = self

        class _Sub(InMemoryBroker.Subscriber):
            def __init__(self):
                self.topic = topic

            def on_message(self, payload):
                src.handler(payload)

        self._sub = _Sub()

    def connect(self):
        InMemoryBroker.subscribe(self._sub)

    def disconnect(self):
        InMemoryBroker.unsubscribe(self._sub)


SOURCES = {
    "inmemory": InMemorySource,
}


class SourceRuntime:
    """Owns one @source: source + mapper + delivery gate + retry loop."""

    def __init__(self, source: Source, mapper: SourceMapper, input_handler,
                 app_context, retry_interval_ms: int = 100,
                 max_retry_interval_ms: int = 5_000, retry_policy=None):
        from siddhi_tpu.resilience.retry import RetryPolicy

        self.source = source
        self.mapper = mapper
        self.input_handler = input_handler
        self.app_context = app_context
        self.retry_interval_ms = retry_interval_ms
        self.max_retry_interval_ms = max_retry_interval_ms
        # shared backoff policy (resilience/retry.py): unbounded, like the
        # reference's connectWithRetry — the transport may come back hours
        # later; shutdown() is the only way out
        self.retry_policy = retry_policy or RetryPolicy(
            initial_ms=retry_interval_ms, max_ms=max_retry_interval_ms)
        self._resume = threading.Event()
        self._resume.set()
        self._connected = False
        self._shutdown = False
        source.handler = self._on_payload

    # ------------------------------------------------------------ delivery

    def _on_payload(self, payload):
        self._resume.wait()          # paused during persist()
        rows = self.mapper.map(payload)
        if not rows:
            return
        for row in rows:
            self.input_handler.send(row)

    def pause(self):
        self._resume.clear()

    def resume(self):
        self._resume.set()

    @property
    def is_paused(self) -> bool:
        return not self._resume.is_set()

    # ----------------------------------------------------------- lifecycle

    def connect_with_retry(self):
        """Reference Source.connectWithRetry:155-185: exponential backoff
        until the transport accepts the connection, driven by the shared
        retry policy (``resilience/retry.py``)."""
        from siddhi_tpu.resilience import stat_count

        def _connect():
            self.source.connect()
            self._connected = True

        self.retry_policy.run(
            _connect, (ConnectionUnavailableException,),
            stop=lambda: self._shutdown,
            on_retry=lambda *_: stat_count(
                self.app_context, "resilience.source_retries"))

    def shutdown(self):
        self._shutdown = True
        self._resume.set()
        if self._connected:
            self.source.disconnect()
        self.source.destroy()


def create_source_runtime(ann, stream_def: StreamDefinition, input_handler,
                          app_context, extensions: Dict[str, type]):
    """Build a SourceRuntime from a ``@source(type='...', ..., @map(...))``
    annotation (reference ``SiddhiAppRuntimeBuilder`` + extension loader)."""
    from siddhi_tpu.ops.expressions import resolve_in

    opts = {k: v for k, v in ann.elements if k is not None}
    type_name = (opts.pop("type", None) or "").lower()
    if not type_name:
        raise ValueError("@source needs a type")
    cls = resolve_in(extensions, "source", type_name) or SOURCES.get(type_name)
    if cls is None:
        raise ValueError(f"unknown source type '{type_name}'")
    map_ann = ann.annotation("map")
    map_opts = {}
    map_type = "passthrough"
    if map_ann is not None:
        map_opts = {k: v for k, v in map_ann.elements if k is not None}
        map_type = (map_opts.pop("type", None) or "passthrough").lower()
    mcls = resolve_in(extensions, "sourceMapper", map_type) \
        or SOURCE_MAPPERS.get(map_type)
    if mcls is None:
        raise ValueError(f"unknown source map type '{map_type}'")
    mapper = mcls()
    mapper.init(stream_def, map_opts)
    source = cls()
    # namespaced deployment config (reference ConfigReader per extension)
    from siddhi_tpu.core.util.config import ConfigReader

    source.config_reader = ConfigReader(
        getattr(app_context.siddhi_context, "config_manager", None),
        f"source.{type_name}")
    source.init(stream_def, opts, app_context)
    return SourceRuntime(source, mapper, input_handler, app_context)


