"""IngestPackPool: the multicore host ingest runtime (ROADMAP item 4).

``HostBatch.from_events`` runs on ONE producer thread at ~1.87M eps
against a measured 25.7M eps host-pipeline ceiling (PERF.md) — the next
bottleneck the moment device steps get cheap. Per "Scaling Ordered
Stream Processing on Shared-Memory Multicores" (PAPERS.md), the pool
shards the encode work of one batch across worker cores as
sequence-numbered sub-batch tasks and merges in order:

- **Sequence-numbered sub-batches.** ``plan_events``/``plan_columns``
  split a batch into contiguous row ranges (``ingest_split`` rows each,
  at most one per worker); each task packs its range into a DISJOINT
  slice of the pre-allocated output columns (``core/event.py``
  ``_parallel_from_events``/``_parallel_from_columns``).
- **Ordered merge.** ``run_ordered`` waits the tasks out strictly in
  sequence order — the CompletionPump's dispatch-order discipline
  (``core/query/completion.py``) applied to pack: completion order may
  be arbitrary, observation order never is. New dictionary strings are
  resolved AFTER the ordered wait, serially, in attribute-major row
  order, so the id space is bit-identical to the inline path.
- **Supervision.** Workers beat like @Async junction workers; a dead or
  killed packer's sub-batch is RE-PACKED inline by the merging thread
  (never lost), dead threads respawn on the next submit (and on the
  AppSupervisor tick via :meth:`heal`), and ``fault_hook`` gives the
  FaultInjector the same kill/delay surface junction workers have.

The pool engages only when ``siddhi_tpu.ingest_pool`` > 0 (default 0 =
today's inline single-thread pack, bit-identical by construction) and a
batch is big enough to span >= 2 sub-batches.

Where the parallelism actually pays: the COLUMNS path — numpy slice
copies and dtype conversions release the GIL, so sub-batches genuinely
overlap on real cores. The EVENTS path's per-row work (``np.fromiter``
over Python generators, the native strdict probe via ``ctypes.PyDLL``)
holds the GIL, so its pool points bound coordination overhead on ANY
CPython host — the per-event object front door scales by moving to the
columns/wire format, not by adding packers; the pool keeps both paths
on one code shape so the ordered-merge/WAL/journey semantics are proven
once. Telemetry rides the
``ingest.*`` prefix (``observability/export.py``): queue-depth /
worker / utilization gauges, ``siddhi_ingest_pack_ms`` per-sub-batch
and ``siddhi_ingest_merge_ms`` per-batch histograms, and repack/death
counters.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, List, Optional, Tuple

from siddhi_tpu.analysis.guards import guarded
from siddhi_tpu.analysis.locks import make_lock
from siddhi_tpu.query_api.definitions import AttrType

log = logging.getLogger(__name__)


class _Task:
    __slots__ = ("seq", "lo", "hi", "fn", "done", "error", "elapsed_ms")

    def __init__(self, seq: int, lo: int, hi: int, fn: Callable):
        self.seq = seq
        self.lo = lo
        self.hi = hi
        self.fn = fn
        self.done = threading.Event()
        self.error: Optional[Exception] = None
        self.elapsed_ms = 0.0


@guarded
class IngestPackPool:
    """Per-app ordered pack pool (see module docstring).

    Thread contract: ``run_ordered`` may be called from any producer /
    junction-worker thread (several concurrently — tasks interleave on
    the shared queue, each caller waits only its own). Workers take NO
    ranked locks; the pool's own bookkeeping lock ranks ``ingest``
    (a leaf under barrier/owner, ``analysis/lockorder.py``)."""

    # `_stopped` stays undeclared: it is a double-checked shutdown gate
    # whose UNLOCKED fast-path reads are deliberate (re-verified under
    # the lock in _spawn_missing_locked); `_busy`/`_beats` are lock-free
    # utilization/liveness probes
    GUARDED_BY = {"_threads": "ingest", "_gen": "ingest"}

    def __init__(self, app_context, workers: int, split_rows: int = 8192):
        if workers <= 0:
            raise ValueError("IngestPackPool needs workers > 0")
        self.app_context = app_context
        self.workers = int(workers)
        self.split_rows = max(256, int(split_rows))
        self._tasks: "queue.Queue" = queue.Queue()
        self._lock = make_lock("ingest")
        self._threads: List[threading.Thread] = []
        self._gen = 0
        self._busy = 0
        self._beats = 0
        self._stopped = False
        self.worker_deaths = 0
        self.repacked_subbatches = 0
        # fault-injection point (resilience/faults.py kill_packer /
        # delay_packer): polled by each worker before running a task —
        # a raising hook kills THAT worker (its task is re-packed by the
        # merge thread); a sleeping hook delays one sub-batch, forcing
        # out-of-order completion the ordered merge must absorb
        self.fault_hook = None
        tel = getattr(app_context, "telemetry", None)
        self._tel = tel
        if tel is not None:
            tel.gauge("ingest.pool.queue_depth", self._tasks.qsize)
            tel.gauge("ingest.pool.workers", self.alive_workers)
            tel.gauge("ingest.pool.utilization",
                      lambda p=self: p._busy / max(1, p.workers))
            self._pack_hist = tel.histogram("ingest.pack_ms")
            self._merge_hist = tel.histogram("ingest.merge_ms")
        else:
            self._pack_hist = self._merge_hist = None
        with self._lock:
            self._spawn_missing_locked()

    # ----------------------------------------------------------- lifecycle

    def alive_workers(self) -> int:
        with self._lock:
            return sum(1 for t in self._threads if t.is_alive())

    def _spawn_missing_locked(self) -> int:
        """Replace dead worker threads (pool lock held). Returns how many
        were spawned."""
        if self._stopped:
            # re-checked under the lock: a heal()/run_ordered that passed
            # its unlocked gate while shutdown() ran must not respawn
            # workers nobody will ever send a stop sentinel to
            return 0
        self._threads = [t for t in self._threads if t.is_alive()]
        n = 0
        while len(self._threads) < self.workers:
            self._gen += 1
            t = threading.Thread(
                target=self._loop, daemon=True,
                name=f"ingest-pack-{self.app_context.name}-g{self._gen}")
            t.start()
            self._threads.append(t)
            n += 1
        return n

    def heal(self) -> int:
        """Supervisor tick entry (``resilience/supervisor.py``): respawn
        dead packers NOW instead of waiting for the next submit."""
        if self._stopped:
            return 0
        with self._lock:
            return self._spawn_missing_locked()

    def resize(self, workers: int) -> int:
        """Live worker-count change (the autopilot's ingest actuator).
        Growth spawns the missing threads; shrink retires the excess by
        queueing one stop sentinel per surplus worker — they finish
        their current sub-batch first, so an in-flight ``run_ordered``
        is never abandoned and the ordered merge is untouched (resize
        changes how many cores pack, never what a pack produces).
        Returns the new worker count."""
        workers = int(workers)
        if workers <= 0:
            raise ValueError("resize needs workers > 0 — use shutdown() "
                             "to dissolve the pool")
        with self._lock:
            if self._stopped:
                return self.workers
            surplus = len([t for t in self._threads if t.is_alive()]) \
                - workers
            self.workers = workers
            self._spawn_missing_locked()
        # sentinels queue BEHIND any pending tasks: surplus workers
        # drain real work first, then exit; _spawn_missing prunes the
        # dead threads on the next submit/heal
        for _ in range(max(0, surplus)):
            self._tasks.put(None)
        return workers

    def shutdown(self) -> None:
        with self._lock:
            # under the lock: serializes against a concurrent
            # heal()/_spawn_missing so no worker spawns after the
            # sentinels are counted out
            self._stopped = True
            threads = self._threads
            self._threads = []
        for _ in threads:
            self._tasks.put(None)
        for t in threads:
            t.join(timeout=5)
        if self._tel is not None:
            # literal names: graftlint R3 pairs each gauge registration
            # with a remove_gauge site by template
            self._tel.remove_gauge("ingest.pool.queue_depth")
            self._tel.remove_gauge("ingest.pool.workers")
            self._tel.remove_gauge("ingest.pool.utilization")

    # ------------------------------------------------------------ planning

    def plan_events(self, n: int, definition) -> Optional[List[Tuple[int, int]]]:
        """Sub-batch ranges for an Event-path pack, or None when the
        batch stays inline: too small to span two sub-batches, pool shut
        down, a pool worker itself is packing (no nested submits), or
        the schema carries OBJECT (set-valued) attributes — their
        variable-width '#set' companions need the whole batch."""
        if self._stopped or _IN_WORKER.active:
            return None
        if any(a.type == AttrType.OBJECT for a in definition.attributes):
            return None
        return self._ranges(n)

    def plan_columns(self, data, definition) -> Optional[List[Tuple[int, int]]]:
        """Sub-batch ranges for a columnar pack. Requires every supplied
        attribute column to be exactly batch-length (the inline path
        dictionary-encodes a LONGER string column in full — splitting
        would change the id-assignment order, so such batches stay
        inline)."""
        if self._stopped or _IN_WORKER.active:
            return None
        first = next(iter(data.values()))
        n = len(first)
        for attr in definition.attributes:
            col = data.get(attr.name)
            if col is None or len(col) != n:
                return None
        return self._ranges(n)

    def _ranges(self, n: int) -> Optional[List[Tuple[int, int]]]:
        split = self.split_rows
        n_chunks = min(self.workers, (n + split - 1) // split)
        if n_chunks < 2:
            return None
        per = (n + n_chunks - 1) // n_chunks
        return [(lo, min(lo + per, n)) for lo in range(0, n, per)]

    # ------------------------------------------------------------- running

    def run_ordered(self, chunks: List[Tuple[int, int]],
                    fn: Callable[[int, int], None]) -> List[float]:
        """Submit every sub-batch, then wait them out strictly in
        sequence order (dispatch-order discipline). A sub-batch whose
        worker died (injected kill, unexpected error escaping the pack
        fn is re-raised) is re-packed INLINE here — the batch is never
        lost, at worst slower. Returns per-sub-batch service times in
        sequence order (journey max-not-sum attribution)."""
        with self._lock:
            self._spawn_missing_locked()
        tasks = [_Task(seq, lo, hi, fn)
                 for seq, (lo, hi) in enumerate(chunks)]
        for t in tasks:
            self._tasks.put(t)
        out: List[float] = []
        for t in tasks:
            waited = 0.0
            while not t.done.wait(timeout=1.0):
                waited += 1.0
                if self._stopped and self.alive_workers() == 0:
                    # shutdown raced this pack: every worker drained its
                    # stop sentinel (queued BEFORE these tasks) and
                    # exited, so nobody will ever claim them — pack the
                    # abandoned sub-batch inline instead of wedging the
                    # producer thread forever. Safe: zero live workers
                    # means zero concurrent writers to these slices.
                    if not t.done.is_set():
                        t0 = time.perf_counter()
                        fn(t.lo, t.hi)
                        t.elapsed_ms = (time.perf_counter() - t0) * 1000.0
                        t.done.set()
                    break
                if waited >= 30.0:
                    waited = 0.0
                    log.warning(
                        "ingest pack pool of app '%s': sub-batch %d "
                        "[%d:%d) still pending after 30s (wedged "
                        "packer?)", self.app_context.name, t.seq, t.lo,
                        t.hi)
            if t.error is not None:
                # dead packer: re-pack this sub-batch on the merge
                # thread — ordered, exact, never lost
                t0 = time.perf_counter()
                fn(t.lo, t.hi)
                t.elapsed_ms = (time.perf_counter() - t0) * 1000.0
                self.repacked_subbatches += 1
                if self._tel is not None:
                    self._tel.count("ingest.pool.repacks")
                    self._pack_hist.record(t.elapsed_ms)
                with self._lock:
                    self._spawn_missing_locked()
            out.append(t.elapsed_ms)
        return out

    def record_merge(self, merge_ms: float) -> None:
        if self._merge_hist is not None:
            self._merge_hist.record(merge_ms)

    # -------------------------------------------------------------- worker

    def _loop(self) -> None:
        _IN_WORKER.active = True
        while True:
            task = self._tasks.get()
            if task is None:
                return
            self._beats += 1
            hook = self.fault_hook
            if hook is not None:
                try:
                    hook(self)
                except Exception as e:  # noqa: BLE001 — injected death
                    task.error = e
                    task.done.set()
                    self.worker_deaths += 1
                    if self._tel is not None:
                        self._tel.count("ingest.pool.worker_deaths")
                    log.warning("ingest pack worker killed: %s", e)
                    return
            self._busy += 1
            t0 = time.perf_counter()
            try:
                task.fn(task.lo, task.hi)
                task.elapsed_ms = (time.perf_counter() - t0) * 1000.0
                if self._pack_hist is not None:
                    self._pack_hist.record(task.elapsed_ms)
            except Exception as e:  # noqa: BLE001 — surfaced via re-pack
                task.error = e
            finally:
                self._busy -= 1
                task.done.set()


# a pool worker must never re-submit to the pool from inside a pack fn
# (nested ordered waits could exhaust the workers): plan_* checks this
# thread-local and keeps worker-side packs inline
class _InWorker(threading.local):
    active = False


_IN_WORKER = _InWorker()
