"""InputHandler / InputManager: API entry for pushing events.

Mirror of reference ``core/stream/input/InputHandler.java:59`` (``send``
variants set the playback clock then forward into the junction) and
``InputManager.java``. The snapshot quiesce gate (``InputEntryValve`` +
``ThreadBarrier``) is a host-side RLock here.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

from siddhi_tpu.core.event import Event
from siddhi_tpu.core.stream.junction import StreamJunction


class InputHandler:
    def __init__(self, stream_id: str, junction: StreamJunction, app_context, barrier: threading.RLock,
                 ensure_started=None):
        self.stream_id = stream_id
        self.junction = junction
        self.app_context = app_context
        self._barrier = barrier
        self._ensure_started = ensure_started
        self._last_ts = None   # @app:enforceOrder monotonicity watermark

    def _check_order(self, first_ts: int, last_ts: int):
        """@app:enforceOrder: reject out-of-order ingestion on this stream
        (the reference carries the flag on SiddhiAppContext with no
        enforcement anywhere — here it buys a real guarantee: a send whose
        timestamp precedes the stream's watermark raises instead of
        silently reordering window/pattern state)."""
        if self._last_ts is not None and first_ts < self._last_ts:
            raise ValueError(
                f"@app:enforceOrder: event timestamp {first_ts} precedes "
                f"stream '{self.stream_id}' watermark {self._last_ts}")
        self._last_ts = last_ts if self._last_ts is None \
            else max(self._last_ts, last_ts)

    def send(self, *args):
        """send(data_list) | send(ts, data_list) | send(Event) | send([Event,...])"""
        if getattr(self.app_context, "stopped", False):
            # reference: sends after shutdown fail (the disruptor is gone,
            # StartStopTestCase test1 expects an exception)
            raise RuntimeError(
                f"SiddhiApp '{self.app_context.name}' has been shut down — "
                f"cannot send to '{self.stream_id}'")
        if self._ensure_started is not None:
            self._ensure_started()
        tsg = self.app_context.timestamp_generator
        if len(args) == 1:
            a = args[0]
            if isinstance(a, Event):
                events = [a]
            elif isinstance(a, (list, tuple)) and a and isinstance(a[0], Event):
                events = list(a)
            else:
                events = [Event(timestamp=tsg.current_time(), data=list(a))]
        elif len(args) == 2 and isinstance(args[0], int):
            events = [Event(timestamp=args[0], data=list(args[1]))]
        else:
            raise TypeError(f"unsupported send arguments: {args!r}")
        for ev in events:
            if ev.timestamp < 0:
                ev.timestamp = tsg.current_time()
        wal = getattr(self.app_context, "ingest_wal", None)
        replaying = wal is not None and wal.in_replay()
        with self._barrier:  # snapshot quiesce gate (ThreadBarrier.java:30-36)
            # order check INSIDE the barrier (atomic with delivery order)
            # and BEFORE the clock advances — a rejected batch must not
            # fire timers or expire windows as a side effect. A WAL replay
            # bypasses the watermark: the suffix re-enters with its
            # ORIGINAL (already-validated, arrival-ordered) timestamps,
            # which an in-process restore's watermark has already passed.
            if self.app_context.enforce_order and events and not replaying:
                ts_seq = [e.timestamp for e in events]
                if any(b < a for a, b in zip(ts_seq, ts_seq[1:])):
                    raise ValueError(
                        f"@app:enforceOrder: non-monotone timestamps inside "
                        f"a batch on stream '{self.stream_id}'")
                self._check_order(ts_seq[0], ts_seq[-1])
            # WAL boundary (resilience/replay.py): the batch is ACCEPTED
            # once validation passed — record before delivery, inside the
            # snapshot barrier so a checkpoint always cuts between batches.
            # The record's seq rides to the junction: if quota admission
            # SHEDS the batch (resilience/overload.py) the record is
            # discarded, keeping replay exactly the non-shed suffix.
            wal_seq = None
            if wal is not None:
                wal_seq = wal.record_events(self.stream_id, events)
            for ev in events:
                tsg.set_current_timestamp(ev.timestamp)
            self.junction.send_events(events, wal_seq=wal_seq)

    def send_columns(self, data, timestamps=None):
        """Columnar bulk ingestion — the TPU-native fast path: one numpy
        array per attribute (strings as str arrays or pre-encoded int ids),
        optional per-row timestamps. Skips Event objects entirely; receivers
        that understand batches consume them directly."""
        import numpy as np

        from siddhi_tpu.core.event import HostBatch, pack_pool_of

        if self._ensure_started is not None:
            self._ensure_started()
        tsg = self.app_context.timestamp_generator
        now = tsg.current_time()
        batch = HostBatch.from_columns(
            data, self.junction.definition,
            self.app_context.string_dictionary,
            timestamps=timestamps, default_ts=now,
            pool=pack_pool_of(self.app_context))
        wal = getattr(self.app_context, "ingest_wal", None)
        replaying = wal is not None and wal.in_replay()
        with self._barrier:
            if timestamps is not None:
                ts_arr = np.asarray(timestamps, np.int64)
                if ts_arr.size:
                    # order check before the clock advances (see send();
                    # a WAL replay bypasses the watermark)
                    if self.app_context.enforce_order and not replaying:
                        if np.any(ts_arr[1:] < ts_arr[:-1]):
                            raise ValueError(
                                f"@app:enforceOrder: non-monotone timestamps "
                                f"inside a batch on stream '{self.stream_id}'")
                        self._check_order(int(ts_arr[0]), int(ts_arr[-1]))
                    # advance in two hops so clock listeners observe the
                    # batch's EARLIEST timestamp first (a head-absent wait
                    # must anchor at the first event, not the batch max)
                    lo = int(ts_arr.min())
                    hi = int(ts_arr.max())
                    if lo != hi:
                        tsg.set_current_timestamp(lo)
                    tsg.set_current_timestamp(hi)
            wal_seq = None
            if wal is not None:
                # raw columns, not the encoded HostBatch: replay re-encodes
                # against the restored dictionary. Timestamps are recorded
                # RESOLVED — a default-stamped batch must replay at its
                # original ingest time, not the replay wall clock
                wal_seq = wal.record_columns(
                    self.stream_id, data,
                    timestamps if timestamps is not None
                    else np.full(int(batch.size), now, np.int64))
            self.junction.send_batch(batch, wal_seq=wal_seq)


class InputManager:
    """Reference ``core/stream/input/InputManager.java``."""

    def __init__(self, app_context, junctions: Dict[str, StreamJunction], barrier: threading.RLock):
        self.app_context = app_context
        self._junctions = junctions
        self._barrier = barrier
        self._handlers: Dict[str, InputHandler] = {}
        self.ensure_started = None  # set by SiddhiAppRuntime (lazy app start)

    def get_input_handler(self, stream_id: str) -> InputHandler:
        h = self._handlers.get(stream_id)
        if h is None:
            if stream_id not in self._junctions:
                raise KeyError(f"stream '{stream_id}' is not defined")
            h = InputHandler(stream_id, self._junctions[stream_id], self.app_context, self._barrier,
                             ensure_started=lambda: self.ensure_started and self.ensure_started())
            self._handlers[stream_id] = h
        return h
