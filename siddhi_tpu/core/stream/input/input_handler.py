"""InputHandler / InputManager: API entry for pushing events.

Mirror of reference ``core/stream/input/InputHandler.java:59`` (``send``
variants set the playback clock then forward into the junction) and
``InputManager.java``. The snapshot quiesce gate (``InputEntryValve`` +
``ThreadBarrier``) is a host-side RLock here.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

from siddhi_tpu.core.event import Event
from siddhi_tpu.core.stream.junction import StreamJunction


class InputHandler:
    def __init__(self, stream_id: str, junction: StreamJunction, app_context, barrier: threading.RLock,
                 ensure_started=None):
        self.stream_id = stream_id
        self.junction = junction
        self.app_context = app_context
        self._barrier = barrier
        self._ensure_started = ensure_started

    def send(self, *args):
        """send(data_list) | send(ts, data_list) | send(Event) | send([Event,...])"""
        if self._ensure_started is not None:
            self._ensure_started()
        tsg = self.app_context.timestamp_generator
        if len(args) == 1:
            a = args[0]
            if isinstance(a, Event):
                events = [a]
            elif isinstance(a, (list, tuple)) and a and isinstance(a[0], Event):
                events = list(a)
            else:
                events = [Event(timestamp=tsg.current_time(), data=list(a))]
        elif len(args) == 2 and isinstance(args[0], int):
            events = [Event(timestamp=args[0], data=list(args[1]))]
        else:
            raise TypeError(f"unsupported send arguments: {args!r}")
        for ev in events:
            if ev.timestamp < 0:
                ev.timestamp = tsg.current_time()
            tsg.set_current_timestamp(ev.timestamp)
        with self._barrier:  # snapshot quiesce gate (ThreadBarrier.java:30-36)
            self.junction.send_events(events)

    def send_columns(self, data, timestamps=None):
        """Columnar bulk ingestion — the TPU-native fast path: one numpy
        array per attribute (strings as str arrays or pre-encoded int ids),
        optional per-row timestamps. Skips Event objects entirely; receivers
        that understand batches consume them directly."""
        import numpy as np

        from siddhi_tpu.core.event import HostBatch

        if self._ensure_started is not None:
            self._ensure_started()
        tsg = self.app_context.timestamp_generator
        now = tsg.current_time()
        batch = HostBatch.from_columns(
            data, self.junction.definition,
            self.app_context.string_dictionary,
            timestamps=timestamps, default_ts=now)
        if timestamps is not None:
            ts_arr = np.asarray(timestamps, np.int64)
            if ts_arr.size:
                tsg.set_current_timestamp(int(ts_arr.max()))
        with self._barrier:
            self.junction.send_batch(batch)


class InputManager:
    """Reference ``core/stream/input/InputManager.java``."""

    def __init__(self, app_context, junctions: Dict[str, StreamJunction], barrier: threading.RLock):
        self.app_context = app_context
        self._junctions = junctions
        self._barrier = barrier
        self._handlers: Dict[str, InputHandler] = {}
        self.ensure_started = None  # set by SiddhiAppRuntime (lazy app start)

    def get_input_handler(self, stream_id: str) -> InputHandler:
        h = self._handlers.get(stream_id)
        if h is None:
            if stream_id not in self._junctions:
                raise KeyError(f"stream '{stream_id}' is not defined")
            h = InputHandler(stream_id, self._junctions[stream_id], self.app_context, self._barrier,
                             ensure_started=lambda: self.ensure_started and self.ensure_started())
            self._handlers[stream_id] = h
        return h
