"""Zero-copy columnar wire format — the production ingest front door.

The "millions of devices pushing telemetry" path (ROADMAP item 4): a
client encodes a batch of events as ONE binary frame of contiguous
typed column buffers; the server ingests it with ``np.frombuffer``
views and ZERO per-event Python — no JSON rows, no ``Event`` objects,
no per-string dictionary probes on the hot path. Exposed as
``POST /ingest/{stream}`` on the REST service (``service/rest.py``) and
driven by ``tools/wire_bench.py``.

Frame layout (all little-endian; Arrow's spirit, one frame = one batch)::

    0   magic   b"SWF1"
    4   u16     version (1)
    6   u16     flags (bit0: frame carries a __ts__ timestamp column)
    8   u64     encoder id  (dictionary-delta continuity, see below)
    16  u32     dict_base   (client string ids the server already knows)
    20  u32     dict_delta_n (new strings in this frame)
    24  u32     n_rows
    28  u16     n_cols
    30  u16     reserved (0)
    32  u32     dir_nbytes  (column directory length)
    36  u32     dict_nbytes (dictionary delta length)
    40  u64     payload_nbytes
    48  column directory, then dictionary delta, then payload

Column directory entry (variable size): ``u16 name_len | name utf-8 |
u8 type_code | u8 reserved | u64 offset | u64 nbytes`` — offsets are
payload-relative and 8-byte aligned, so every buffer is one aligned
``np.frombuffer`` view. Null masks travel as ``<name>?`` bool columns;
per-row timestamps as a ``__ts__`` int64 column.

**Dictionary delta.** Strings never travel per event: the client keeps
its own append-only string⇄id dictionary (ids are frame-column int32
values, -1 = null) and each frame carries only the NEW strings since
the last frame (``dict_base`` → ``dict_base + dict_delta_n``). The
server keeps a per-encoder LUT translating client ids to its own
app-global ``StringDictionary`` ids, extended from each delta with ONE
vectorized gather per string column afterwards. A frame whose
``dict_base`` does not match the server's LUT (server restart, LRU
eviction) is rejected with a clean ``SiddhiAppValidationException`` —
the client calls :meth:`WireEncoder.reset` and resends from a full
dictionary (``dict_base == 0`` always re-bootstraps the LUT).

Every malformed input — truncated buffer, bad magic/version, offsets
out of range, unknown type codes, id out of dictionary range — raises
``SiddhiAppValidationException``; never a crash, never a silent
partial batch.
"""

from __future__ import annotations

import struct
import threading
import uuid
from collections import OrderedDict
from typing import Dict, NamedTuple, Optional, Tuple

import numpy as np

from siddhi_tpu.compiler.errors import SiddhiAppValidationException
from siddhi_tpu.query_api.definitions import AttrType

MAGIC = b"SWF1"
VERSION = 1
FLAG_TS = 1
# bit 15: the frame is a CONTROL frame (hello / heartbeat / seq-ack /
# checkpoint-cut — the cluster fabric's link-management vocabulary).
# Control frames reuse the same 48-byte header so every endpoint needs
# exactly one frame parser; decode_frame rejects them cleanly and
# decode_control rejects data frames symmetrically.
FLAG_CONTROL = 0x8000

# Capability bits, carried on the hello path (dict_base slot of the
# hello header). Version gates the FRAME LAYOUT; capabilities gate
# optional behaviors within a version, so a decoder can refuse a
# feature without refusing the whole link.
CAP_TS = 1 << 0             # per-row __ts__ timestamp columns
CAP_DICT_DELTA = 1 << 1     # dictionary-delta string protocol
CAP_CONTROL = 1 << 2        # control frames (cluster fabric links)
CAPABILITIES = CAP_TS | CAP_DICT_DELTA | CAP_CONTROL

# control-frame kinds (u16 reserved slot, FLAG_CONTROL set)
CTRL_HELLO = 1              # version + capability negotiation
CTRL_HEARTBEAT = 2          # liveness tick (b = sender's monotone tick)
CTRL_SEQ_ACK = 3            # b = highest contiguous ingest seq applied
CTRL_CHECKPOINT_CUT = 4     # b = barrier id; body = JSON revision info

_HEADER = struct.Struct("<4sHHQIIIHHIIQ")     # 48 bytes
_DIR_FIXED = struct.Struct("<BBQQ")           # after the name
TS_COL = "__ts__"

# type codes <-> numpy dtypes; STRING_IDS columns carry client
# dictionary ids (int32, -1 = null)
T_INT64, T_FLOAT64, T_FLOAT32, T_INT32, T_BOOL, T_INT8, T_STRING_IDS = \
    range(7)
_DTYPES = {
    T_INT64: np.dtype("<i8"),
    T_FLOAT64: np.dtype("<f8"),
    T_FLOAT32: np.dtype("<f4"),
    T_INT32: np.dtype("<i4"),
    T_BOOL: np.dtype("?"),
    T_INT8: np.dtype("<i1"),
    T_STRING_IDS: np.dtype("<i4"),
}
_CODE_OF_DTYPE = {
    np.dtype("<i8"): T_INT64, np.dtype("<f8"): T_FLOAT64,
    np.dtype("<f4"): T_FLOAT32, np.dtype("<i4"): T_INT32,
    np.dtype("?"): T_BOOL, np.dtype("<i1"): T_INT8,
}


def _bad(msg: str) -> SiddhiAppValidationException:
    return SiddhiAppValidationException(f"wire frame: {msg}")


def _align8(n: int) -> int:
    return (n + 7) & ~7


# ----------------------------------------------------------- control frames


class ControlFrame(NamedTuple):
    """A decoded control frame. ``a`` and ``b`` are the two u64 slots
    (sender id and a kind-specific scalar: heartbeat tick, acked seq,
    checkpoint barrier id); ``body`` is an optional opaque blob (JSON by
    convention) for structured payloads like checkpoint revisions."""

    kind: int
    version: int
    capabilities: int
    a: int
    b: int
    body: bytes


def encode_control(kind: int, *, a: int = 0, b: int = 0,
                   body: bytes = b"", version: int = VERSION,
                   capabilities: int = CAPABILITIES) -> bytes:
    """Encode one control frame on the shared 48-byte header: the
    ``encoder_id`` slot carries ``a``, ``dict_base`` the capability
    bits, ``reserved`` the control kind, ``payload_nbytes`` carries
    ``b``, and ``dir_nbytes`` the body length."""
    if not 0 <= kind <= 0xFFFF:
        raise _bad(f"control kind {kind} out of range")
    return _HEADER.pack(MAGIC, version, FLAG_CONTROL, a,
                        capabilities & 0xFFFFFFFF, 0, 0, 0, kind,
                        len(body), 0, b) + bytes(body)


def is_control(buf: bytes) -> bool:
    """True iff ``buf`` starts with a control-frame header (cheap peek
    so a socket reader can route without a full decode)."""
    if len(buf) < 8 or bytes(buf[:4]) != MAGIC:
        return False
    (flags,) = struct.unpack_from("<H", buf, 6)
    return bool(flags & FLAG_CONTROL)


def decode_control(buf: bytes) -> ControlFrame:
    """Decode one control frame. Deliberately does NOT reject a version
    mismatch: the HELLO frame must be readable across versions so the
    negotiation error can name both sides (see :func:`negotiate_hello`)
    instead of dying as a frame-parse error."""
    if len(buf) < _HEADER.size:
        raise _bad(f"truncated control frame: {len(buf)} bytes < "
                   f"{_HEADER.size}-byte header")
    (magic, version, flags, a, caps, _delta_n, _n_rows, _n_cols, kind,
     body_n, _dict_n, b) = _HEADER.unpack_from(buf, 0)
    if magic != MAGIC:
        raise _bad(f"bad magic {magic!r} (expected {MAGIC!r})")
    if not flags & FLAG_CONTROL:
        raise _bad("data frame on the control path — route data frames "
                   "through decode_frame")
    if len(buf) < _HEADER.size + body_n:
        raise _bad(f"truncated control frame body: header promises "
                   f"{body_n} bytes, got {len(buf) - _HEADER.size}")
    body = bytes(buf[_HEADER.size:_HEADER.size + body_n])
    return ControlFrame(kind, version, caps, a, b, body)


def encode_hello(sender_id: int = 0, *, version: int = VERSION,
                 capabilities: int = CAPABILITIES) -> bytes:
    """The link-open frame every wire conversation starts with:
    protocol version + capability bits, so incompatible endpoints fail
    at negotiation time with an error naming both versions instead of
    mid-stream with a frame-parse error."""
    return encode_control(CTRL_HELLO, a=sender_id, version=version,
                          capabilities=capabilities)


def negotiate_hello(buf: bytes, required: int = 0) -> ControlFrame:
    """Decode a peer's hello and negotiate: a version mismatch (or a
    required capability the peer lacks) raises a clean
    ``SiddhiAppValidationException`` naming BOTH sides. Returns the
    hello with capabilities narrowed to the mutually-supported set."""
    hello = decode_control(buf)
    if hello.kind != CTRL_HELLO:
        raise _bad(f"expected a hello control frame, got control kind "
                   f"{hello.kind}")
    if hello.version != VERSION:
        raise _bad(
            f"protocol version mismatch: peer speaks wire version "
            f"{hello.version}, this endpoint speaks version {VERSION} "
            f"— upgrade the older side; the frame layout is not "
            f"cross-version compatible")
    agreed = hello.capabilities & CAPABILITIES
    missing = required & ~agreed
    if missing:
        raise _bad(
            f"capability mismatch: this endpoint requires bits "
            f"{required:#x} but the peer offers "
            f"{hello.capabilities:#x} (missing {missing:#x})")
    return hello._replace(capabilities=agreed)


# ------------------------------------------------------------------ encoder


class WireEncoder:
    """Client-side frame encoder (one per producing device/connection).

    Keeps the client half of the dictionary-delta protocol: an
    append-only string->int32 id map whose NEW entries ride each frame.
    ``encode`` takes attribute-name -> numpy array columns (strings as
    object/str arrays or pre-encoded int ids), optional ``<name>?``
    bool null masks, and optional per-row timestamps."""

    def __init__(self, encoder_id: Optional[int] = None):
        self.encoder_id = (int(encoder_id) if encoder_id is not None
                           else uuid.uuid4().int & ((1 << 64) - 1))
        self._to_id: Dict[str, int] = {}
        self._strings = []
        self._sent = 0        # ids the server has seen (delta watermark)

    def reset(self) -> None:
        """Resend the full dictionary in the next frame (server restart
        / LUT eviction recovery): the next frame's ``dict_base`` is 0,
        which re-bootstraps the server-side LUT."""
        self._sent = 0

    def _encode_strings(self, col: np.ndarray) -> np.ndarray:
        out = np.empty(len(col), np.int32)
        to_id = self._to_id
        for i, v in enumerate(col):
            if v is None:
                out[i] = -1
                continue
            if type(v) is not str:
                v = str(v)
            j = to_id.get(v)
            if j is None:
                j = len(self._strings)
                to_id[v] = j
                self._strings.append(v)
            out[i] = j
        return out

    def encode(self, data: Dict[str, np.ndarray],
               timestamps=None, string_ids=frozenset()) -> bytes:
        """``string_ids`` names columns that are ALREADY this encoder's
        client ids (int32, -1 = null) — the cluster router's relay path,
        which translates router ids via a LUT instead of re-interning
        strings per row (cluster/protocol.RelayEncoder). The caller
        guarantees the ids reference this encoder's dictionary."""
        cols: Dict[str, Tuple[int, np.ndarray]] = {}
        n_rows = None
        for name, values in data.items():
            arr = np.asarray(values)
            if n_rows is None:
                n_rows = len(arr)
            elif len(arr) != n_rows:
                raise _bad(f"column '{name}' has {len(arr)} rows, "
                           f"expected {n_rows}")
            if name.endswith("?"):
                cols[name] = (T_BOOL, np.ascontiguousarray(arr, np.bool_))
            elif name in string_ids:
                cols[name] = (T_STRING_IDS,
                              np.ascontiguousarray(arr, "<i4"))
            elif arr.dtype == object or arr.dtype.kind in ("U", "S"):
                cols[name] = (T_STRING_IDS,
                              self._encode_strings(arr.astype(object)))
            else:
                dt = arr.dtype.newbyteorder("<")
                code = _CODE_OF_DTYPE.get(dt)
                if code is None:
                    if arr.dtype.kind in "iu":
                        code, dt = T_INT64, np.dtype("<i8")
                    elif arr.dtype.kind == "f":
                        code, dt = T_FLOAT64, np.dtype("<f8")
                    elif arr.dtype.kind == "b":
                        code, dt = T_BOOL, np.dtype("?")
                    else:
                        raise _bad(f"column '{name}': unsupported dtype "
                                   f"{arr.dtype}")
                cols[name] = (code, np.ascontiguousarray(arr, dt))
        if n_rows is None:
            n_rows = 0
        flags = 0
        if timestamps is not None:
            flags |= FLAG_TS
            cols[TS_COL] = (T_INT64, np.ascontiguousarray(
                np.asarray(timestamps, np.int64)[:n_rows], "<i8"))

        delta = self._strings[self._sent:]
        dict_base = self._sent
        dict_parts = []
        for s in delta:
            b = s.encode("utf-8")
            dict_parts.append(struct.pack("<I", len(b)))
            dict_parts.append(b)
        dict_blob = b"".join(dict_parts)

        dir_parts = []
        payload_parts = []
        offset = 0
        for name, (code, arr) in cols.items():
            nb = arr.nbytes
            name_b = name.encode("utf-8")
            dir_parts.append(struct.pack("<H", len(name_b)))
            dir_parts.append(name_b)
            dir_parts.append(_DIR_FIXED.pack(code, 0, offset, nb))
            payload_parts.append(arr.tobytes())
            pad = _align8(nb) - nb
            if pad:
                payload_parts.append(b"\0" * pad)
            offset += _align8(nb)
        dir_blob = b"".join(dir_parts)
        payload = b"".join(payload_parts)
        header = _HEADER.pack(
            MAGIC, VERSION, flags, self.encoder_id,
            dict_base, len(delta), n_rows, len(cols), 0,
            len(dir_blob), len(dict_blob), len(payload))
        self._sent = len(self._strings)
        return header + dir_blob + dict_blob + payload


# ------------------------------------------------------------------ decoder


def _count_eviction() -> None:
    # process registry, not an app registry: the shared REST/cluster
    # DecoderRegistry outlives any single app (rendered as
    # siddhi_wire_decoder_evictions_total, observability/export.py)
    from siddhi_tpu.observability.telemetry import global_registry

    global_registry().count("ingest.wire.decoder_evictions")


class _EncoderState:
    __slots__ = ("lut", "lock")

    def __init__(self):
        self.lut = np.empty(0, np.int64)   # client id -> server id
        # serializes the gap-check + delta extension: a client retrying
        # a frame on a second connection must not append its delta twice
        # (ThreadingHTTPServer + AdmissionPool process frames concurrently)
        self.lock = threading.Lock()


class DecoderRegistry:
    """Server-side dictionary-delta state, one LUT per (scope, encoder).

    ``scope`` partitions the id space: LUT entries are server ids from a
    SPECIFIC app's StringDictionary, so a shared registry (the REST
    service) must key by app — one encoder posting to streams of two
    different apps would otherwise gather app A's ids into app B's
    columns silently. Bounded LRU (an evicted encoder's next frame fails
    the continuity check with a clean error telling the client to
    ``reset()``)."""

    def __init__(self, max_encoders: int = 256):
        self.max_encoders = int(max_encoders)
        self._states: "OrderedDict[tuple, _EncoderState]" = OrderedDict()
        # keys the LRU evicted, so the evicted client's NEXT frame gets
        # the documented reset() error naming the real cause instead of
        # either a confusing generic gap error or — for an encoder whose
        # LUT happened to be empty — a silent dictionary corruption.
        # Bounded itself (a key leaves when its client resets).
        self._evicted: "OrderedDict[tuple, None]" = OrderedDict()
        self.evictions = 0
        self._lock = threading.Lock()

    def _state_for(self, encoder_id: int, dict_base: int,
                   scope=None) -> _EncoderState:
        key = (scope, encoder_id)
        with self._lock:
            st = self._states.get(key)
            if st is None and dict_base != 0 and key in self._evicted:
                raise _bad(
                    f"encoder {encoder_id:#x} dictionary state was "
                    f"evicted by the bounded decoder LRU (max_encoders="
                    f"{self.max_encoders}) — reset the encoder "
                    f"(WireEncoder.reset) and resend from a full "
                    f"dictionary")
            if st is None or dict_base == 0:
                # dict_base 0 re-bootstraps: a reset() client resends
                # the full dictionary and the stale LUT must not shadow it
                st = _EncoderState()
                self._states[key] = st
                self._evicted.pop(key, None)
            self._states.move_to_end(key)
            while len(self._states) > self.max_encoders:
                old, _ = self._states.popitem(last=False)
                self._evicted[old] = None
                while len(self._evicted) > 8 * self.max_encoders:
                    self._evicted.popitem(last=False)
                self.evictions += 1
                _count_eviction()
            return st


def _view(payload: memoryview, offset: int, nbytes: int, code: int,
          name: str) -> np.ndarray:
    dt = _DTYPES.get(code)
    if dt is None:
        raise _bad(f"column '{name}': unknown type code {code}")
    if offset % 8 != 0:
        raise _bad(f"column '{name}': misaligned offset {offset}")
    if offset + nbytes > len(payload):
        raise _bad(f"column '{name}': buffer [{offset}:{offset + nbytes}) "
                   f"escapes the {len(payload)}-byte payload")
    if nbytes % dt.itemsize != 0:
        raise _bad(f"column '{name}': {nbytes} bytes is not a whole "
                   f"number of {dt.itemsize}-byte elements")
    return np.frombuffer(payload, dt, count=nbytes // dt.itemsize,
                         offset=offset)


def decode_frame(buf: bytes, definition, dictionary,
                 registry: DecoderRegistry, scope=None):
    """Decode one frame against a stream definition: returns
    ``(data, timestamps)`` ready for ``InputHandler.send_columns`` —
    string columns already translated to SERVER dictionary ids (int64,
    negative = null) by one vectorized LUT gather, every other column a
    zero-copy ``np.frombuffer`` view of ``buf``. ``scope`` must identify
    the dictionary's owner (the app name) when ``registry`` is shared
    across apps."""
    if len(buf) < _HEADER.size:
        raise _bad(f"truncated: {len(buf)} bytes < {_HEADER.size}-byte "
                   f"header")
    (magic, version, flags, encoder_id, dict_base, delta_n, n_rows,
     n_cols, _resv, dir_nbytes, dict_nbytes, payload_nbytes) = \
        _HEADER.unpack_from(buf, 0)
    if magic != MAGIC:
        raise _bad(f"bad magic {magic!r} (expected {MAGIC!r})")
    if flags & FLAG_CONTROL:
        raise _bad("control frame on the data path — route control "
                   "frames through decode_control")
    if version != VERSION:
        raise _bad(
            f"protocol version mismatch: frame encoded for wire "
            f"version {version}, this decoder speaks version {VERSION} "
            f"— negotiate on the hello path (encode_hello/"
            f"negotiate_hello) before streaming")
    need = _HEADER.size + dir_nbytes + dict_nbytes + payload_nbytes
    if len(buf) < need:
        raise _bad(f"truncated: header promises {need} bytes, got "
                   f"{len(buf)}")
    mv = memoryview(buf)
    dir_mv = mv[_HEADER.size:_HEADER.size + dir_nbytes]
    dict_mv = mv[_HEADER.size + dir_nbytes:
                 _HEADER.size + dir_nbytes + dict_nbytes]
    payload = mv[_HEADER.size + dir_nbytes + dict_nbytes:need]

    # ---- column directory
    columns: Dict[str, Tuple[int, int, int]] = {}
    pos = 0
    for _ in range(n_cols):
        if pos + 2 > len(dir_mv):
            raise _bad("truncated column directory")
        (name_len,) = struct.unpack_from("<H", dir_mv, pos)
        pos += 2
        if pos + name_len + _DIR_FIXED.size > len(dir_mv):
            raise _bad("truncated column directory entry")
        try:
            name = bytes(dir_mv[pos:pos + name_len]).decode("utf-8")
        except UnicodeDecodeError:
            raise _bad("undecodable column name") from None
        pos += name_len
        code, _r, offset, nbytes = _DIR_FIXED.unpack_from(dir_mv, pos)
        pos += _DIR_FIXED.size
        columns[name] = (code, offset, nbytes)

    # ---- dictionary delta -> per-encoder LUT extension. Deliberately
    # BEFORE column validation: the client advanced its delta watermark
    # at encode time, so applying the delta even when the frame is then
    # rejected keeps both sides in sync — the corrected retry (empty
    # delta, advanced dict_base) passes the continuity check. Validating
    # first would leave the server BEHIND the client's watermark and
    # force a full reset after every rejected frame.
    st = registry._state_for(encoder_id, dict_base, scope=scope)
    with st.lock:
        if len(st.lut) != dict_base:
            raise _bad(
                f"dictionary delta gap: frame assumes {dict_base} known "
                f"client ids but this server knows {len(st.lut)} for "
                f"encoder {encoder_id:#x} — reset the encoder "
                f"(WireEncoder.reset) and resend from a full dictionary")
        if delta_n:
            new_ids = np.empty(delta_n, np.int64)
            pos = 0
            for i in range(delta_n):
                if pos + 4 > len(dict_mv):
                    raise _bad("truncated dictionary delta")
                (slen,) = struct.unpack_from("<I", dict_mv, pos)
                pos += 4
                if pos + slen > len(dict_mv):
                    raise _bad("truncated dictionary delta string")
                try:
                    s = bytes(dict_mv[pos:pos + slen]).decode("utf-8")
                except UnicodeDecodeError:
                    raise _bad(
                        "undecodable dictionary delta string") from None
                pos += slen
                new_ids[i] = dictionary.encode(s)
            st.lut = np.concatenate([st.lut, new_ids])
        lut = st.lut        # immutable snapshot for the gathers below

    # ---- columns -> send_columns dict
    data: Dict[str, np.ndarray] = {}
    timestamps = None
    for attr in definition.attributes:
        rec = columns.get(attr.name)
        if rec is None:
            raise _bad(f"column '{attr.name}' missing from frame")
        code, offset, nbytes = rec
        arr = _view(payload, offset, nbytes, code, attr.name)
        if len(arr) != n_rows:
            raise _bad(f"column '{attr.name}': {len(arr)} rows, frame "
                       f"says {n_rows}")
        if attr.type == AttrType.STRING:
            if code != T_STRING_IDS:
                raise _bad(f"column '{attr.name}' is a string attribute "
                           f"but carries type code {code}")
            ids = arr.astype(np.int64)      # copy: view is read-only
            valid = ids >= 0
            if valid.any():
                hi = int(ids[valid].max())
                if hi >= len(lut):
                    raise _bad(
                        f"column '{attr.name}': client id {hi} outside "
                        f"the {len(lut)}-entry dictionary")
                # ONE vectorized gather translates the whole column from
                # client ids to server ids — zero per-event Python
                ids = np.where(valid, lut[np.where(valid, ids, 0)], -1)
            data[attr.name] = ids
        else:
            if code == T_STRING_IDS:
                raise _bad(f"column '{attr.name}' carries string ids but "
                           f"is not a string attribute")
            data[attr.name] = arr
        mrec = columns.get(attr.name + "?")
        if mrec is not None:
            mcode, moff, mnb = mrec
            if mcode != T_BOOL:
                raise _bad(f"null mask '{attr.name}?' must be bool")
            mask = _view(payload, moff, mnb, mcode, attr.name + "?")
            if len(mask) != n_rows:
                raise _bad(f"null mask '{attr.name}?': {len(mask)} rows, "
                           f"frame says {n_rows}")
            data[attr.name + "?"] = mask
    if flags & FLAG_TS:
        rec = columns.get(TS_COL)
        if rec is None:
            raise _bad("flags promise a __ts__ column but none is present")
        code, offset, nbytes = rec
        if code != T_INT64:
            raise _bad("__ts__ must be int64")
        timestamps = _view(payload, offset, nbytes, code, TS_COL)
        if len(timestamps) != n_rows:
            raise _bad(f"__ts__: {len(timestamps)} rows, frame says "
                       f"{n_rows}")
    return data, timestamps
