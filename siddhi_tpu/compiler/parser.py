"""Recursive-descent SiddhiQL parser: tokens -> query-api IR.

Covers the surface of the reference grammar
(``siddhi-query-compiler/.../SiddhiQL.g4``: ``siddhi_app``:34,
``definition_aggregation``:118, ``partition``:155, ``query``:180,
``pattern_stream``:200, ``sequence_stream``:291, ``store_query``:71) and the
folding logic of ``internal/SiddhiQLBaseVisitorImpl.java``, as a hand-written
parser.
"""

from __future__ import annotations

from typing import List, Optional

from siddhi_tpu.compiler.errors import SiddhiParserException
from siddhi_tpu.compiler.tokenizer import Token, is_time_unit, time_unit_ms, tokenize
from siddhi_tpu.query_api.annotations import Annotation
from siddhi_tpu.query_api.definitions import (
    AggregationDefinition,
    Attribute,
    AttrType,
    Duration,
    FunctionDefinition,
    StreamDefinition,
    TableDefinition,
    TimePeriod,
    TriggerDefinition,
    WindowDefinition,
)
from siddhi_tpu.query_api.execution import (
    AbsentStreamStateElement,
    CountStateElement,
    DeleteStream,
    EventOutputRate,
    EventTrigger,
    EveryStateElement,
    Filter,
    InputStore,
    InsertIntoStream,
    JoinInputStream,
    JoinType,
    LogicalStateElement,
    NextStateElement,
    OnDemandQuery,
    OrderByAttribute,
    OutputAttribute,
    Partition,
    Query,
    RangeCondition,
    RangePartitionType,
    ReturnStream,
    Selector,
    SetAttribute,
    SingleInputStream,
    SnapshotOutputRate,
    StateElement,
    StateInputStream,
    StateInputStreamType,
    StreamFunction,
    StreamStateElement,
    TimeOutputRate,
    UpdateOrInsertStream,
    UpdateSet,
    UpdateStream,
    ValuePartitionType,
    Window,
)
from siddhi_tpu.query_api.expressions import (
    Add,
    And,
    AttributeFunction,
    Compare,
    Constant,
    Divide,
    Expression,
    InOp,
    IsNull,
    Mod,
    Multiply,
    Not,
    Or,
    Subtract,
    TimeConstant,
    Variable,
)
from siddhi_tpu.query_api.siddhi_app import SiddhiApp

_TYPE_MAP = {
    "string": AttrType.STRING,
    "int": AttrType.INT,
    "long": AttrType.LONG,
    "float": AttrType.FLOAT,
    "double": AttrType.DOUBLE,
    "bool": AttrType.BOOL,
    "object": AttrType.OBJECT,
}

# Keywords that terminate a from-clause at bracket depth 0.
_FROM_END = {"select", "insert", "delete", "update", "return", "output", "group", "having", "order", "limit", "offset"}


class Parser:
    def __init__(self, tokens: List[Token]):
        self.tokens = tokens
        self.pos = 0

    # ------------------------------------------------------------- helpers

    def peek(self, offset: int = 0) -> Token:
        i = min(self.pos + offset, len(self.tokens) - 1)
        return self.tokens[i]

    def next(self) -> Token:
        t = self.tokens[self.pos]
        if t.kind != "eof":
            self.pos += 1
        return t

    def error(self, message: str, tok: Optional[Token] = None):
        t = tok or self.peek()
        raise SiddhiParserException(message, t.line, t.col, t.text)

    def expect_op(self, op: str) -> Token:
        t = self.peek()
        if not t.is_op(op):
            self.error(f"expected '{op}'")
        return self.next()

    def expect_kw(self, *kws: str) -> Token:
        t = self.peek()
        if not t.is_kw(*kws):
            self.error(f"expected {'/'.join(kws)}")
        return self.next()

    def accept_op(self, op: str) -> bool:
        if self.peek().is_op(op):
            self.next()
            return True
        return False

    def accept_kw(self, *kws: str) -> bool:
        if self.peek().is_kw(*kws):
            self.next()
            return True
        return False

    def name(self) -> str:
        """An identifier; keywords are allowed as names (e.g. `min(price)`)."""
        t = self.peek()
        if t.kind not in ("id", "keyword"):
            self.error("expected a name")
        return self.next().text

    def at_time_constant(self) -> bool:
        return self.peek().kind in ("int", "long") and (
            self.peek(1).kind == "keyword" and is_time_unit(self.peek(1).text)
        )

    def parse_time_constant(self) -> TimeConstant:
        total = 0
        while self.at_time_constant():
            value = self.next().value
            unit = self.next().text
            total += value * time_unit_ms(unit)
        return TimeConstant(total)

    # --------------------------------------------------------- annotations

    def parse_annotations(self) -> List[Annotation]:
        out = []
        while self.peek().is_op("@"):
            out.append(self.parse_annotation())
        return out

    def parse_annotation(self) -> Annotation:
        self.expect_op("@")
        name = self.name()
        if self.accept_op(":"):
            name = f"{name}:{self.name()}"
        ann = Annotation(name=name)
        if self.accept_op("("):
            if not self.peek().is_op(")"):
                while True:
                    if self.peek().is_op("@"):
                        ann.annotations.append(self.parse_annotation())
                    else:
                        key = None
                        # key may be dotted: buffer.size='64'
                        if self.peek().kind in ("id", "keyword") and (
                            self.peek(1).is_op("=") or self.peek(1).is_op(".")
                        ):
                            parts = [self.name()]
                            while self.accept_op("."):
                                parts.append(self.name())
                            key = ".".join(parts)
                            self.expect_op("=")
                        t = self.peek()
                        if t.kind in ("string", "int", "long", "float", "double"):
                            self.next()
                            ann.elements.append((key, str(t.value)))
                        elif t.is_kw("true", "false"):
                            self.next()
                            ann.elements.append((key, t.text.lower()))
                        else:
                            self.error("expected annotation element value")
                    if not self.accept_op(","):
                        break
            self.expect_op(")")
        return ann

    # ----------------------------------------------------------- top level

    def parse_siddhi_app(self) -> SiddhiApp:
        app = SiddhiApp()
        while True:
            t = self.peek()
            if t.kind == "eof":
                break
            if t.is_op(";"):
                self.next()
                continue
            annotations = self.parse_annotations()
            # `@app:*` annotations are app-level regardless of position
            # (reference SiddhiAppParser.java:91-212); the rest bind to the
            # immediately following definition/query/partition.
            element_annotations = []
            for a in annotations:
                if a.name.lower().startswith("app:"):
                    app.annotations.append(a)
                else:
                    element_annotations.append(a)
            t = self.peek()
            if t.is_kw("define"):
                self.parse_definition(app, element_annotations)
            elif t.is_kw("partition"):
                app.execution_elements.append(self.parse_partition(element_annotations))
            elif t.is_kw("from"):
                app.execution_elements.append(self.parse_query(element_annotations))
            elif t.kind == "eof" or t.is_op(";"):
                app.annotations.extend(element_annotations)
            else:
                self.error("expected 'define', 'from', 'partition' or annotation")
        return app

    def parse_definition(self, app: SiddhiApp, element_annotations: List[Annotation]):
        self.expect_kw("define")
        t = self.peek()
        if t.is_kw("stream"):
            self.next()
            d = StreamDefinition(id=self.name(), annotations=element_annotations)
            d.attributes = self.parse_attribute_list()
            app.define_stream(d)
        elif t.is_kw("table"):
            self.next()
            d = TableDefinition(id=self.name(), annotations=element_annotations)
            d.attributes = self.parse_attribute_list()
            app.define_table(d)
        elif t.is_kw("window"):
            self.next()
            d = WindowDefinition(id=self.name(), annotations=element_annotations)
            d.attributes = self.parse_attribute_list()
            d.window = self.parse_window_handler_bare()
            if self.accept_kw("output"):
                ev = self.expect_kw("current", "expired", "all").text.lower()
                self.expect_kw("events")
                d.output_event_type = ev
            app.define_window(d)
        elif t.is_kw("trigger"):
            self.next()
            d = TriggerDefinition(id=self.name(), annotations=element_annotations)
            self.expect_kw("at")
            if self.accept_kw("every"):
                d.at_every = self.parse_time_constant().value
            elif self.peek().kind == "string":
                s = self.next().value
                if s.lower() == "start":
                    d.at_start = True
                else:
                    d.cron = s
            else:
                self.error("expected 'every <time>' or a quoted cron/'start'")
            app.define_trigger(d)
        elif t.is_kw("function"):
            self.next()
            d = FunctionDefinition(id=self.name())
            self.expect_op("[")
            d.language = self.name()
            self.expect_op("]")
            self.expect_kw("return")
            type_tok = self.next()
            d.return_type = _TYPE_MAP[type_tok.text.lower()]
            body = self.peek()
            if body.kind != "script":
                self.error("expected function body { ... }")
            d.body = self.next().value
            app.function_definitions[d.id] = d
        elif t.is_kw("aggregation"):
            self.next()
            d = AggregationDefinition(id=self.name(), annotations=element_annotations)
            self.expect_kw("from")
            d.input_stream = self.parse_single_input_stream()
            d.selector = self.parse_selector_clauses()
            self.expect_kw("aggregate")
            if self.accept_kw("by"):
                d.aggregate_attribute = self.parse_variable()
            self.expect_kw("every")
            d.time_period = self.parse_time_period()
            app.define_aggregation(d)
        else:
            self.error("expected stream/table/window/trigger/function/aggregation")
        self.accept_op(";")

    def parse_attribute_list(self) -> List[Attribute]:
        self.expect_op("(")
        attrs = []
        while True:
            attr_name = self.name()
            type_tok = self.next()
            if type_tok.text.lower() not in _TYPE_MAP:
                self.error(f"unknown type '{type_tok.text}'", type_tok)
            attrs.append(Attribute(attr_name, _TYPE_MAP[type_tok.text.lower()]))
            if not self.accept_op(","):
                break
        self.expect_op(")")
        return attrs

    def parse_window_handler_bare(self) -> Window:
        """`time(5 sec)` / `ns:name(args)` in a window definition (no `#window.`)."""
        ns = ""
        nm = self.name()
        if self.accept_op(":"):
            ns, nm = nm, self.name()
        params = self.parse_call_params()
        return Window(namespace=ns, name=nm, parameters=params)

    def parse_time_period(self) -> TimePeriod:
        durations = [self.parse_duration()]
        if self.peek().is_op("."):
            # range: sec ... year
            self.expect_op(".")
            self.expect_op(".")
            self.expect_op(".")
            durations.append(self.parse_duration())
            return TimePeriod(operator="range", durations=durations)
        while self.accept_op(","):
            durations.append(self.parse_duration())
        op = "interval" if len(durations) > 1 else "range"
        return TimePeriod(operator=op, durations=durations)

    def parse_duration(self) -> Duration:
        t = self.next()
        key = t.text.lower()
        mapping = {
            "sec": Duration.SECONDS, "second": Duration.SECONDS, "seconds": Duration.SECONDS,
            "min": Duration.MINUTES, "minute": Duration.MINUTES, "minutes": Duration.MINUTES,
            "hour": Duration.HOURS, "hours": Duration.HOURS,
            "day": Duration.DAYS, "days": Duration.DAYS,
            "month": Duration.MONTHS, "months": Duration.MONTHS,
            "year": Duration.YEARS, "years": Duration.YEARS,
        }
        if key not in mapping:
            self.error(f"unknown duration '{t.text}'", t)
        return mapping[key]

    # ------------------------------------------------------------ partition

    def parse_partition(self, annotations: List[Annotation]) -> Partition:
        self.expect_kw("partition")
        self.expect_kw("with")
        self.expect_op("(")
        p = Partition(annotations=annotations)
        while True:
            p.partition_types.append(self.parse_partition_type())
            if not self.accept_op(","):
                break
        self.expect_op(")")
        self.expect_kw("begin")
        while not self.peek().is_kw("end"):
            q_annotations = self.parse_annotations()
            p.queries.append(self.parse_query(q_annotations))
            self.accept_op(";")
        self.expect_kw("end")
        self.accept_op(";")
        return p

    def parse_partition_type(self):
        # range form:  cond as 'label' or cond as 'label' ... of Stream
        # value form:  expr of Stream
        start = self.pos
        expr = self.parse_expression()
        if self.peek().is_kw("as"):
            self.pos = start
            conditions = []
            while True:
                cond = self.parse_expression()
                self.expect_kw("as")
                label_tok = self.peek()
                if label_tok.kind != "string":
                    self.error("expected partition range label string")
                self.next()
                conditions.append(RangeCondition(partition_key=label_tok.value, condition=cond))
                if not self.accept_kw("or"):
                    break
            self.expect_kw("of")
            stream_id = self.name()
            return RangePartitionType(stream_id=stream_id, conditions=conditions)
        self.expect_kw("of")
        stream_id = self.name()
        return ValuePartitionType(stream_id=stream_id, expression=expr)

    # -------------------------------------------------------------- queries

    def parse_query(self, annotations: List[Annotation]) -> Query:
        q = Query(annotations=annotations)
        self.expect_kw("from")
        q.input_stream = self.parse_input_stream()
        q.selector = self.parse_selector_clauses()
        q.output_rate = self.parse_output_rate()
        q.output_stream = self.parse_output_action()
        self.accept_op(";")
        return q

    # .............................................. from-clause classifier

    def _scan_from_clause_kind(self) -> str:
        """Look ahead (no consumption) to classify single/join/pattern."""
        depth = 0
        i = self.pos
        saw_arrow = saw_comma = saw_join = saw_assign = saw_every = saw_not = False
        first = True
        while i < len(self.tokens):
            t = self.tokens[i]
            if t.kind == "eof":
                break
            if t.is_op("(", "["):
                depth += 1
            elif t.is_op(")", "]"):
                depth -= 1
            elif depth == 0:
                if t.kind == "keyword" and t.text.lower() in _FROM_END:
                    break
                if t.is_op(";"):
                    break
                if t.is_op("->"):
                    saw_arrow = True
                if t.is_op(","):
                    saw_comma = True
                if t.is_kw("join"):
                    saw_join = True
                if t.is_op("=") and not (i + 1 < len(self.tokens) and self.tokens[i + 1].is_op("=")):
                    saw_assign = True
                if first and t.is_kw("every"):
                    saw_every = True
                if first and t.is_kw("not"):
                    saw_not = True
            first = False
            i += 1
        if saw_arrow:
            return "pattern"
        if saw_comma and not saw_join:
            return "sequence"
        if saw_every or saw_not or (saw_assign and not saw_join):
            return "pattern"
        if saw_join:
            return "join"
        return "single"

    def parse_input_stream(self):
        kind = self._scan_from_clause_kind()
        if kind == "single":
            return self.parse_single_input_stream()
        if kind == "join":
            return self.parse_join_input_stream()
        return self.parse_state_input_stream(
            StateInputStreamType.PATTERN if kind == "pattern" else StateInputStreamType.SEQUENCE
        )

    # ....................................................... single stream

    def parse_single_input_stream(self) -> SingleInputStream:
        is_inner = self.accept_op("#")
        is_fault = False if is_inner else self.accept_op("!")
        stream_id = self.name()
        s = SingleInputStream(stream_id=stream_id, is_inner_stream=is_inner, is_fault_stream=is_fault)
        s.handlers = self.parse_stream_handlers()
        return s

    def parse_stream_handlers(self) -> List:
        handlers = []
        while True:
            t = self.peek()
            if t.is_op("["):
                self.next()
                handlers.append(Filter(self.parse_expression()))
                self.expect_op("]")
            elif t.is_op("#"):
                self.next()
                if self.peek().is_op("["):
                    # '#[expr]' filter-handler shorthand (SiddhiQL grammar
                    # StreamHandler: '#'? '[' expression ']')
                    self.next()
                    handlers.append(Filter(self.parse_expression()))
                    self.expect_op("]")
                    continue
                nm = self.name()
                if nm.lower() == "window" and self.accept_op("."):
                    wname = self.name()
                    params = self.parse_call_params()
                    handlers.append(Window(namespace="", name=wname, parameters=params))
                else:
                    ns = ""
                    if self.accept_op(":"):
                        ns, nm = nm, self.name()
                    params = self.parse_call_params()
                    handlers.append(StreamFunction(namespace=ns, name=nm, parameters=params))
            else:
                break
        return handlers

    def parse_call_params(self) -> List[Expression]:
        params: List[Expression] = []
        self.expect_op("(")
        if not self.peek().is_op(")"):
            while True:
                params.append(self.parse_expression())
                if not self.accept_op(","):
                    break
        self.expect_op(")")
        return params

    # ............................................................... join

    def parse_join_input_stream(self) -> JoinInputStream:
        left, left_uni = self.parse_join_side()
        join_type = self.parse_join_type()
        right, right_uni = self.parse_join_side()
        on = None
        within = None
        per = None
        if self.accept_kw("on"):
            on = self.parse_expression()
        if self.accept_kw("within"):
            within = self.parse_time_constant() if self.at_time_constant() else self.parse_expression()
            if self.accept_op(","):
                end = (self.parse_time_constant() if self.at_time_constant()
                       else self.parse_expression())
                within = (within, end)   # `within start, end` (agg joins)
        if self.accept_kw("per"):
            per = self.parse_expression()
        trigger = EventTrigger.ALL
        if left_uni and right_uni:
            self.error("both join sides cannot be unidirectional")
        elif left_uni:
            trigger = EventTrigger.LEFT
        elif right_uni:
            trigger = EventTrigger.RIGHT
        return JoinInputStream(left=left, right=right, type=join_type, on_compare=on,
                               trigger=trigger, within=within, per=per)

    def parse_join_side(self):
        s = self.parse_single_input_stream()
        if self.accept_kw("as"):
            s.stream_reference_id = self.name()
        unidirectional = self.accept_kw("unidirectional")
        if s.stream_reference_id is None and self.accept_kw("as"):
            s.stream_reference_id = self.name()
        return s, unidirectional

    def parse_join_type(self) -> JoinType:
        if self.accept_kw("left"):
            self.expect_kw("outer")
            self.expect_kw("join")
            return JoinType.LEFT_OUTER_JOIN
        if self.accept_kw("right"):
            self.expect_kw("outer")
            self.expect_kw("join")
            return JoinType.RIGHT_OUTER_JOIN
        if self.accept_kw("full"):
            self.expect_kw("outer")
            self.expect_kw("join")
            return JoinType.FULL_OUTER_JOIN
        if self.accept_kw("inner"):
            self.expect_kw("join")
            return JoinType.INNER_JOIN
        self.expect_kw("join")
        return JoinType.JOIN

    # .................................................. pattern / sequence

    def parse_state_input_stream(self, state_type: StateInputStreamType) -> StateInputStream:
        sep = "->" if state_type == StateInputStreamType.PATTERN else ","
        element = self.parse_state_chain(sep, state_type)
        within = None
        if self.accept_kw("within"):
            within = self.parse_time_constant().value
        return StateInputStream(state_type=state_type, state_element=element, within=within)

    def parse_state_chain(self, sep: str, state_type, depth: int = 0) -> StateElement:
        left = self.parse_state_unit(sep, state_type, depth)
        while (sep == "->" and self.accept_op("->")) or (sep == "," and self.accept_op(",")):
            right = self.parse_state_unit(sep, state_type, depth)
            left = NextStateElement(state=left, next=right)
        return left

    def _accept_scoped_within(self, depth: int):
        """A trailing top-level `within` belongs to the whole pattern
        (SiddhiQL.g4 pattern_stream: ... within_time?) — bind it to the
        preceding element only when more chain follows or we are inside
        parentheses (the scoped-within extension)."""
        mark = self.pos
        if not self.accept_kw("within"):
            return None
        w = self.parse_time_constant().value
        if depth > 0 or self.peek().is_op("->") or self.peek().is_op(","):
            return w
        self.pos = mark
        return None

    def parse_state_unit(self, sep: str, state_type, depth: int = 0) -> StateElement:
        if self.accept_kw("every"):
            if self.accept_op("("):
                inner = self.parse_state_chain(sep, state_type, depth + 1)
                self.expect_op(")")
                el: StateElement = EveryStateElement(state=inner)
            else:
                el = EveryStateElement(state=self.parse_state_source(sep, state_type))
            w = self._accept_scoped_within(depth)
            if w is not None:
                el.within = w
            return el
        if self.accept_op("("):
            inner = self.parse_state_chain(sep, state_type, depth + 1)
            self.expect_op(")")
            # `(...) within t` is always the scoped-within extension: the
            # parentheses make the scope explicit
            if self.accept_kw("within"):
                inner.within = self.parse_time_constant().value
            return inner
        return self.parse_state_source(sep, state_type)

    def parse_state_source(self, sep: str, state_type) -> StateElement:
        """One pattern source: logical / count / absent / plain stream.
        Absent sides (``not X [for t]``) may pair with present or absent
        sides through and/or (reference SiddhiQL.g4 absent_pattern_source /
        logical_absent_stateful_source)."""
        first = self.parse_maybe_absent_stream()
        t = self.peek()
        if t.is_kw("and", "or"):
            op = self.next().text.lower()
            second = self.parse_maybe_absent_stream()
            return LogicalStateElement(stream1=first, type=op, stream2=second)
        if isinstance(first, AbsentStreamStateElement):
            if first.waiting_time is None:
                self.error(
                    "absent pattern requires 'for <time>' or an and/or pairing")
            return first
        # count / regex quantifiers ('<:' is the tokenizer-fused max-only
        # form, e.g. `<:5>`)
        if t.is_op("<") or t.is_op("<:"):
            return self.parse_count_suffix(first)
        if t.is_op("+"):
            self.next()
            return CountStateElement(state=first, min_count=1, max_count=CountStateElement.ANY)
        if t.is_op("*"):
            self.next()
            return CountStateElement(state=first, min_count=0, max_count=CountStateElement.ANY)
        if t.is_op("?"):
            self.next()
            return CountStateElement(state=first, min_count=0, max_count=1)
        return first

    def parse_count_suffix(self, inner: StreamStateElement) -> CountStateElement:
        # forms: <2> | <2:5> | <2:> | <:5>   (tokenizer may fuse '<:' and ':>')
        el = CountStateElement(state=inner)
        if self.accept_op("<:"):
            el.min_count = CountStateElement.ANY
            el.max_count = self.next().value
            self.expect_op(">")
            return el
        self.expect_op("<")
        if self.accept_op(":"):
            # whitespace-separated max-only form `< :5>` (the ANTLR
            # grammar is whitespace-insensitive between '<' and ':')
            el.min_count = CountStateElement.ANY
            el.max_count = self.next().value
            self.expect_op(">")
            return el
        el.min_count = self.next().value
        if self.accept_op(":>"):
            # ':>' fused by the tokenizer — the closing '>' is already consumed
            el.max_count = CountStateElement.ANY
            return el
        if self.accept_op(":"):
            if self.peek().kind in ("int", "long"):
                el.max_count = self.next().value
            else:
                el.max_count = CountStateElement.ANY
        else:
            el.max_count = el.min_count
        self.expect_op(">")
        return el

    def parse_standard_state_stream(self) -> StreamStateElement:
        ref = None
        if (
            self.peek().kind in ("id", "keyword")
            and self.peek(1).is_op("=")
            and not self.peek(2).is_op("=")
        ):
            ref = self.name()
            self.expect_op("=")
        stream = self.parse_single_input_stream()
        stream.stream_reference_id = ref
        el = StreamStateElement(stream=stream)
        return el

    def parse_maybe_absent_stream(self) -> StreamStateElement:
        """Either ``not X [for t]`` or a plain (possibly captured) stream."""
        if self.accept_kw("not"):
            absent = self.parse_absent_stream()
            if self.accept_kw("for"):
                absent.waiting_time = self.parse_time_constant().value
            return absent
        return self.parse_standard_state_stream()

    def parse_absent_stream(self) -> AbsentStreamStateElement:
        stream = self.parse_single_input_stream()
        return AbsentStreamStateElement(stream=stream)

    # ....................................................... select clause

    def parse_selector_clauses(self) -> Selector:
        sel = Selector()
        if self.accept_kw("select"):
            if self.accept_op("*"):
                sel.select_all = True
            else:
                while True:
                    expr = self.parse_expression()
                    rename = None
                    if self.accept_kw("as"):
                        rename = self.name()
                    sel.selection_list.append(OutputAttribute(rename=rename, expression=expr))
                    if not self.accept_op(","):
                        break
        else:
            sel.select_all = True
        if self.accept_kw("group"):
            self.expect_kw("by")
            while True:
                sel.group_by_list.append(self.parse_variable())
                if not self.accept_op(","):
                    break
        if self.accept_kw("having"):
            sel.having = self.parse_expression()
        if self.accept_kw("order"):
            self.expect_kw("by")
            while True:
                var = self.parse_variable()
                order = "asc"
                if self.accept_kw("asc"):
                    order = "asc"
                elif self.accept_kw("desc"):
                    order = "desc"
                sel.order_by_list.append(OrderByAttribute(variable=var, order=order))
                if not self.accept_op(","):
                    break
        if self.accept_kw("limit"):
            sel.limit = self.next().value
        if self.accept_kw("offset"):
            sel.offset = self.next().value
        return sel

    def parse_output_rate(self):
        if not self.peek().is_kw("output"):
            return None
        # careful: `output` also starts output actions in store queries — but
        # in queries the action keywords are insert/delete/update/return.
        self.next()
        if self.accept_kw("snapshot"):
            self.expect_kw("every")
            return SnapshotOutputRate(value=self.parse_time_constant().value)
        rate_type = "all"
        if self.accept_kw("all"):
            rate_type = "all"
        elif self.accept_kw("first"):
            rate_type = "first"
        elif self.accept_kw("last"):
            rate_type = "last"
        self.expect_kw("every")
        if self.at_time_constant():
            return TimeOutputRate(value=self.parse_time_constant().value, type=rate_type)
        value = self.next().value
        self.expect_kw("events")
        return EventOutputRate(value=value, type=rate_type)

    def parse_output_event_type(self) -> Optional[str]:
        for kw in ("current", "expired", "all"):
            if self.peek().is_kw(kw):
                self.next()
                self.expect_kw("events")
                return kw
        if self.peek().is_kw("events"):
            # bare `insert events into` == current events (SiddhiQL.g4
            # output_event_type: the type qualifier is optional)
            self.next()
            return "current"
        return None

    def parse_output_action(self):
        if self.accept_kw("insert"):
            # `insert overwrite` is legacy; not supported
            ev = self.parse_output_event_type() or "current"
            if self.accept_kw("into"):
                is_inner = self.accept_op("#")
                is_fault = False if is_inner else self.accept_op("!")
                target = self.name()
                return InsertIntoStream(target_id=target, output_event_type=ev,
                                        is_inner_stream=is_inner, is_fault_stream=is_fault)
            self.error("expected 'into'")
        if self.accept_kw("delete"):
            target = self.name()
            ev = self.parse_output_event_type_for() or "current"
            self.expect_kw("on")
            cond = self.parse_expression()
            return DeleteStream(target_id=target, output_event_type=ev, on_delete=cond)
        if self.accept_kw("update"):
            if self.accept_kw("or"):
                self.expect_kw("insert")
                self.expect_kw("into")
                target = self.name()
                update_set = self.parse_update_set()
                self.expect_kw("on")
                cond = self.parse_expression()
                return UpdateOrInsertStream(target_id=target, on_update=cond, update_set=update_set)
            target = self.name()
            ev = self.parse_output_event_type_for() or "current"
            update_set = self.parse_update_set()
            self.expect_kw("on")
            cond = self.parse_expression()
            return UpdateStream(target_id=target, output_event_type=ev, on_update=cond,
                                update_set=update_set)
        if self.accept_kw("return"):
            return ReturnStream()
        self.error("expected insert/delete/update/return output action")

    def parse_output_event_type_for(self) -> Optional[str]:
        if self.accept_kw("for"):
            for kw in ("current", "expired", "all"):
                if self.peek().is_kw(kw):
                    self.next()
                    self.expect_kw("events")
                    return kw
            self.error("expected current/expired/all events")
        return None

    def parse_update_set(self) -> Optional[UpdateSet]:
        if not self.accept_kw("set"):
            return None
        us = UpdateSet()
        while True:
            table_var = self.parse_variable()
            self.expect_op("=")
            value = self.parse_expression()
            us.set_attributes.append(SetAttribute(table_variable=table_var, assignment=value))
            if not self.accept_op(","):
                break
        return us

    # --------------------------------------------------- on-demand queries

    @staticmethod
    def _mutation_type(out, default: str) -> str:
        if isinstance(out, DeleteStream):
            return "delete"
        if isinstance(out, UpdateOrInsertStream):
            return "update_or_insert"
        if isinstance(out, UpdateStream):
            return "update"
        return default

    def parse_on_demand_query(self) -> OnDemandQuery:
        q = OnDemandQuery()
        t = self.peek()
        if t.is_kw("delete") or (t.is_kw("update") and not self.peek(1).is_kw("or")):
            # `delete Table on <cond>` / `update Table set ... on <cond>`
            # (reference StoreQuery mutation forms)
            q.output_stream = self.parse_output_action()
            q.type = ("delete" if isinstance(q.output_stream, DeleteStream)
                      else "update")
            return q
        if t.is_kw("update"):  # `update or insert into Table set ... on ...`
            q.output_stream = self.parse_output_action()
            q.type = "update_or_insert"
            return q
        if self.accept_kw("from"):
            store = InputStore(store_id=self.name())
            if self.accept_kw("as"):
                store.store_reference_id = self.name()
            if self.accept_kw("on"):
                store.on_condition = self.parse_expression()
            if self.accept_kw("within"):
                first = (self.parse_time_constant()
                         if self.at_time_constant() else self.parse_expression())
                if self.accept_op(","):
                    second = (self.parse_time_constant()
                              if self.at_time_constant() else self.parse_expression())
                    store.within = (first, second)  # start, end
                else:
                    store.within = first
                if self.accept_kw("per"):
                    store.per = self.parse_expression()
            q.input_store = store
            q.selector = self.parse_selector_clauses()
            t = self.peek()
            if t.is_kw("insert", "update", "delete", "return") :
                q.output_stream = self.parse_output_action()
                q.type = self._mutation_type(q.output_stream, "find")
            else:
                q.output_stream = ReturnStream()
                q.type = "find"
            return q
        if self.accept_kw("select"):
            # `select ... {insert|update|update or insert|delete} ...` —
            # the projection becomes the mutation's pseudo trigger event
            self.pos -= 1
            q.selector = self.parse_selector_clauses()
            q.output_stream = self.parse_output_action()
            q.type = self._mutation_type(q.output_stream, "insert")
            return q
        self.error("expected on-demand query")

    # ---------------------------------------------------------- expressions

    def parse_expression(self) -> Expression:
        return self.parse_or()

    def parse_or(self) -> Expression:
        # `or(...)`/`and(...)` as *aggregator calls* only occur at primary
        # position, where parse_primary -> parse_name_expression handles them;
        # here 'or' is always the infix boolean.
        left = self.parse_and()
        while self.peek().is_kw("or"):
            self.next()
            left = Or(left, self.parse_and())
        return left

    def parse_and(self) -> Expression:
        left = self.parse_not()
        while self.peek().is_kw("and"):
            self.next()
            left = And(left, self.parse_not())
        return left

    def parse_not(self) -> Expression:
        if self.accept_kw("not"):
            return Not(self.parse_not())
        return self.parse_compare()

    def parse_compare(self) -> Expression:
        left = self.parse_additive()
        while True:
            t = self.peek()
            if t.is_op("<", "<=", ">", ">=", "==", "!="):
                op = self.next().text
                right = self.parse_additive()
                left = Compare(left, op, right)
            elif t.is_kw("in"):
                self.next()
                left = InOp(expression=left, source_id=self.name())
            elif t.is_kw("is") and self.peek(1).is_kw("null"):
                self.next()
                self.next()
                if isinstance(left, Variable) and left.stream_id is None and left.stream_index is None:
                    # could be a stream-state null check (`e1 is null`); the
                    # runtime parser resolves attr-vs-stream by name.
                    left = IsNull(expression=left)
                else:
                    left = IsNull(expression=left)
            else:
                return left

    def parse_additive(self) -> Expression:
        left = self.parse_multiplicative()
        while True:
            t = self.peek()
            if t.is_op("+"):
                self.next()
                left = Add(left, self.parse_multiplicative())
            elif t.is_op("-"):
                self.next()
                left = Subtract(left, self.parse_multiplicative())
            else:
                return left

    def parse_multiplicative(self) -> Expression:
        left = self.parse_unary()
        while True:
            t = self.peek()
            if t.is_op("*"):
                self.next()
                left = Multiply(left, self.parse_unary())
            elif t.is_op("/"):
                self.next()
                left = Divide(left, self.parse_unary())
            elif t.is_op("%"):
                self.next()
                left = Mod(left, self.parse_unary())
            else:
                return left

    def parse_unary(self) -> Expression:
        if self.peek().is_op("-"):
            self.next()
            inner = self.parse_unary()
            if isinstance(inner, Constant):
                return Constant(-inner.value, inner.type)
            return Subtract(Constant(0, AttrType.INT), inner)
        return self.parse_primary()

    def parse_primary(self) -> Expression:
        t = self.peek()
        if t.is_op("("):
            self.next()
            e = self.parse_expression()
            self.expect_op(")")
            return e
        if t.kind == "int":
            self.next()
            if self.peek().kind == "keyword" and is_time_unit(self.peek().text):
                self.pos -= 1
                return self.parse_time_constant()
            return Constant(t.value, AttrType.INT)
        if t.kind == "long":
            self.next()
            if self.peek().kind == "keyword" and is_time_unit(self.peek().text):
                self.pos -= 1
                return self.parse_time_constant()
            return Constant(t.value, AttrType.LONG)
        if t.kind == "float":
            self.next()
            return Constant(t.value, AttrType.FLOAT)
        if t.kind == "double":
            self.next()
            return Constant(t.value, AttrType.DOUBLE)
        if t.kind == "string":
            self.next()
            return Constant(t.value, AttrType.STRING)
        if t.is_kw("true"):
            self.next()
            return Constant(True, AttrType.BOOL)
        if t.is_kw("false"):
            self.next()
            return Constant(False, AttrType.BOOL)
        if t.kind in ("id", "keyword"):
            return self.parse_name_expression()
        if t.is_op("#"):
            # inner-stream qualified variable: '#Stream.attr' inside a
            # partition (SiddhiQL.g4 stream_id: '#'? name)
            self.next()
            e = self.parse_name_expression()
            if not isinstance(e, Variable) or e.stream_id is None:
                self.error("expected '#stream.attribute' reference", t)
            return Variable(attribute_name=e.attribute_name,
                            stream_id="#" + e.stream_id,
                            stream_index=e.stream_index)
        self.error("expected expression")

    def parse_name_expression(self) -> Expression:
        """function call | namespaced function | variable (possibly dotted)."""
        nm = self.name()
        # namespaced function ns:fn(...)
        if self.peek().is_op(":") and self.peek(2).is_op("("):
            self.next()
            fn = self.name()
            params = self.parse_call_params()
            return AttributeFunction(namespace=nm, name=fn, parameters=params)
        if self.peek().is_op("("):
            params = self.parse_call_params()
            return AttributeFunction(namespace="", name=nm, parameters=params)
        # variable forms: attr | stream.attr | ref[idx].attr
        stream_id = None
        stream_index = None
        attr = nm
        if self.peek().is_op("["):
            self.next()
            idx_tok = self.next()
            if idx_tok.is_kw("last"):
                stream_index = "last"
                if self.peek().is_op("-"):
                    self.next()
                    offset = self.next().value
                    stream_index = ("last", -offset)
            elif idx_tok.kind == "int":
                stream_index = idx_tok.value
            else:
                self.error("expected event index", idx_tok)
            self.expect_op("]")
            stream_id = nm
            if self.peek().is_op("."):
                self.next()
                attr = self.name()
            else:
                # bare indexed event ref (`e2[last-1] is null` — reference
                # SiddhiQL nullCheck over a StateEvent position)
                attr = None
        elif self.peek().is_op("."):
            self.next()
            stream_id = nm
            attr = self.name()
        return Variable(attribute_name=attr, stream_id=stream_id, stream_index=stream_index)

    def parse_variable(self) -> Variable:
        e = self.parse_name_expression()
        if not isinstance(e, Variable):
            self.error("expected attribute reference")
        return e
