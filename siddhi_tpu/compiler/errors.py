"""Compiler error types with line/column context.

Mirrors the role of reference ``internal/SiddhiErrorListener.java`` — parse
errors carry the offending line/column and a context snippet.
"""

from __future__ import annotations


class SiddhiParserException(Exception):
    def __init__(self, message: str, line: int = -1, col: int = -1, context: str = ""):
        self.line = line
        self.col = col
        self.context = context
        loc = f" at line {line}:{col}" if line >= 0 else ""
        ctx = f" near '{context}'" if context else ""
        super().__init__(f"{message}{loc}{ctx}")


class SiddhiAppValidationException(Exception):
    pass


class DuplicateDefinitionException(SiddhiAppValidationException):
    """Conflicting (re)definition of a stream/table/window id — same-id
    redefinitions are legal only when attribute lists are identical
    (reference ``AbstractDefinition.checkEquivalency``)."""
