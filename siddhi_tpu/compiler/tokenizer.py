"""SiddhiQL tokenizer.

Token classes mirror the lexer rules of the reference grammar
(``siddhi-query-compiler/src/main/antlr4/.../SiddhiQL.g4``): case-insensitive
keywords, case-sensitive identifiers (optionally backtick-quoted),
single/double/triple-quoted strings, int/long/float/double literals,
``--`` line comments and ``/* */`` block comments.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

from siddhi_tpu.compiler.errors import SiddhiParserException

# Multi-char operators first (maximal munch).
_OPERATORS = [
    "->", "<=", ">=", "==", "!=", "<:", ":>",
    "(", ")", "[", "]", "<", ">", ",", ";", ":", ".", "@",
    "+", "-", "*", "/", "%", "=", "#", "!", "?",
]

KEYWORDS = {
    "define", "stream", "table", "window", "trigger", "aggregation", "function",
    "from", "select", "as", "insert", "into", "delete", "update", "set", "return",
    "group", "by", "having", "order", "asc", "desc", "limit", "offset",
    "output", "snapshot", "all", "first", "last", "current", "expired", "events", "every",
    "at", "and", "or", "not", "in", "is", "null", "true", "false",
    "join", "inner", "outer", "left", "right", "full", "unidirectional", "on",
    "within", "per", "for", "of", "partition", "with", "begin", "end", "range",
    "aggregate", "string", "int", "long", "float", "double", "bool", "object",
    "seconds", "second", "sec", "minutes", "minute", "min", "hours", "hour",
    "days", "day", "weeks", "week", "months", "month", "years", "year",
    "millisecond", "milliseconds", "millisec", "ms",
}

_TIME_UNIT_MS = {
    "ms": 1, "millisec": 1, "millisecond": 1, "milliseconds": 1,
    "sec": 1000, "second": 1000, "seconds": 1000,
    "min": 60_000, "minute": 60_000, "minutes": 60_000,
    "hour": 3_600_000, "hours": 3_600_000,
    "day": 86_400_000, "days": 86_400_000,
    "week": 604_800_000, "weeks": 604_800_000,
    "month": 2_592_000_000, "months": 2_592_000_000,  # 30 days
    "year": 31_536_000_000, "years": 31_536_000_000,  # 365 days
}


def time_unit_ms(word: str) -> int:
    return _TIME_UNIT_MS[word.lower()]


def is_time_unit(word: str) -> bool:
    return word.lower() in _TIME_UNIT_MS


@dataclass
class Token:
    kind: str  # 'id', 'keyword', 'int', 'long', 'float', 'double', 'string', 'op', 'eof'
    text: str
    value: object
    line: int
    col: int

    def is_kw(self, *kws: str) -> bool:
        return self.kind == "keyword" and self.text.lower() in kws

    def is_op(self, *ops: str) -> bool:
        return self.kind == "op" and self.text in ops


def tokenize(source: str) -> List[Token]:
    tokens: List[Token] = []
    i, n = 0, len(source)
    line, col = 1, 1

    def advance(k: int = 1):
        nonlocal i, line, col
        for _ in range(k):
            if i < n and source[i] == "\n":
                line += 1
                col = 1
            else:
                col += 1
            i += 1

    while i < n:
        c = source[i]
        # whitespace
        if c in " \t\r\n":
            advance()
            continue
        # comments
        if source.startswith("--", i):
            while i < n and source[i] != "\n":
                advance()
            continue
        if source.startswith("/*", i):
            start_line, start_col = line, col
            advance(2)
            while i < n and not source.startswith("*/", i):
                advance()
            if i >= n:
                raise SiddhiParserException("unterminated block comment", start_line, start_col)
            advance(2)
            continue
        # strings
        if c in "'\"":
            start_line, start_col = line, col
            if source.startswith('"""', i):
                advance(3)
                j = source.find('"""', i)
                if j < 0:
                    raise SiddhiParserException("unterminated string", start_line, start_col)
                text = source[i:j]
                advance(j - i + 3)
                tokens.append(Token("string", text, text, start_line, start_col))
                continue
            quote = c
            advance()
            buf = []
            while i < n and source[i] != quote:
                if source[i] == "\n":
                    raise SiddhiParserException("unterminated string", start_line, start_col)
                buf.append(source[i])
                advance()
            if i >= n:
                raise SiddhiParserException("unterminated string", start_line, start_col)
            advance()  # closing quote
            text = "".join(buf)
            tokens.append(Token("string", text, text, start_line, start_col))
            continue
        # numbers
        if c.isdigit() or (c == "." and i + 1 < n and source[i + 1].isdigit()):
            start_line, start_col = line, col
            j = i
            while j < n and source[j].isdigit():
                j += 1
            is_decimal = False
            if j < n and source[j] == "." and j + 1 < n and source[j + 1].isdigit():
                is_decimal = True
                j += 1
                while j < n and source[j].isdigit():
                    j += 1
            if j < n and source[j] in "eE" and (
                (j + 1 < n and source[j + 1].isdigit())
                or (j + 2 < n and source[j + 1] in "+-" and source[j + 2].isdigit())
            ):
                is_decimal = True
                j += 1
                if source[j] in "+-":
                    j += 1
                while j < n and source[j].isdigit():
                    j += 1
            text = source[i:j]
            suffix = source[j].lower() if j < n else ""
            if suffix == "l" and not is_decimal:
                advance(j - i + 1)
                tokens.append(Token("long", text, int(text), start_line, start_col))
            elif suffix == "f":
                advance(j - i + 1)
                tokens.append(Token("float", text, float(text), start_line, start_col))
            elif suffix == "d":
                advance(j - i + 1)
                tokens.append(Token("double", text, float(text), start_line, start_col))
            elif is_decimal:
                advance(j - i)
                tokens.append(Token("double", text, float(text), start_line, start_col))
            else:
                advance(j - i)
                tokens.append(Token("int", text, int(text), start_line, start_col))
            continue
        # script body `{ ... }` — one token, as in the reference grammar's
        # SCRIPT lexer rule (used only for `define function` bodies)
        if c == "{":
            start_line, start_col = line, col
            depth = 0
            j = i
            in_quote = ""
            while j < n:
                ch = source[j]
                if in_quote:
                    if ch == in_quote:
                        in_quote = ""
                elif ch in "'\"":
                    in_quote = ch
                elif ch == "{":
                    depth += 1
                elif ch == "}":
                    depth -= 1
                    if depth == 0:
                        break
                j += 1
            if j >= n:
                raise SiddhiParserException("unterminated script body", start_line, start_col)
            body = source[i + 1 : j]
            advance(j - i + 1)
            tokens.append(Token("script", body, body, start_line, start_col))
            continue
        # backtick-quoted identifier
        if c == "`":
            start_line, start_col = line, col
            advance()
            j = source.find("`", i)
            if j < 0:
                raise SiddhiParserException("unterminated quoted identifier", start_line, start_col)
            text = source[i:j]
            advance(j - i + 1)
            tokens.append(Token("id", text, text, start_line, start_col))
            continue
        # identifiers / keywords
        if c.isalpha() or c == "_":
            start_line, start_col = line, col
            j = i
            while j < n and (source[j].isalnum() or source[j] == "_"):
                j += 1
            text = source[i:j]
            advance(j - i)
            kind = "keyword" if text.lower() in KEYWORDS else "id"
            tokens.append(Token(kind, text, text, start_line, start_col))
            continue
        # operators
        for op in _OPERATORS:
            if source.startswith(op, i):
                tokens.append(Token("op", op, op, line, col))
                advance(len(op))
                break
        else:
            raise SiddhiParserException(f"unexpected character '{c}'", line, col)

    tokens.append(Token("eof", "", None, line, col))
    return tokens
