"""Compiler facade.

Mirrors reference ``SiddhiCompiler.java`` static methods: ``parse``:63,
``parseQuery``:145, ``parseOnDemandQuery``:193, ``updateVariables``:233
(``${var}`` substitution from environment / system properties).
"""

from __future__ import annotations

import os
import re

from siddhi_tpu.compiler.errors import SiddhiParserException
from siddhi_tpu.compiler.parser import Parser
from siddhi_tpu.compiler.tokenizer import tokenize
from siddhi_tpu.query_api.execution import OnDemandQuery, Query
from siddhi_tpu.query_api.siddhi_app import SiddhiApp

_VAR_RE = re.compile(r"\$\{(\w+)\}")


class SiddhiCompiler:
    @staticmethod
    def update_variables(siddhi_app: str) -> str:
        """Substitute ``${var}`` from os.environ (reference
        ``SiddhiCompiler.updateVariables:233`` reads env then system props)."""

        def repl(m: re.Match) -> str:
            name = m.group(1)
            value = os.environ.get(name)
            if value is None:
                raise SiddhiParserException(
                    f"no system or environment variable found for '${{{name}}}'"
                )
            return value

        return _VAR_RE.sub(repl, siddhi_app)

    @staticmethod
    def parse(source: str) -> SiddhiApp:
        return Parser(tokenize(source)).parse_siddhi_app()

    @staticmethod
    def parse_query(source: str) -> Query:
        p = Parser(tokenize(source))
        annotations = p.parse_annotations()
        return p.parse_query(annotations)

    @staticmethod
    def parse_on_demand_query(source: str) -> OnDemandQuery:
        return Parser(tokenize(source)).parse_on_demand_query()

    # Java-style aliases
    updateVariables = update_variables
    parseQuery = parse_query
    parseOnDemandQuery = parse_on_demand_query
