"""SiddhiQL compiler: text -> query-api IR.

Fills the role of the reference's ``siddhi-query-compiler`` module
(ANTLR4 ``SiddhiQL.g4`` + ``SiddhiQLBaseVisitorImpl.java``), re-implemented
as a hand-written tokenizer + recursive-descent parser so no parser-generator
runtime is needed. Public entry points mirror ``SiddhiCompiler.java:63,145,193,233``.
"""

from siddhi_tpu.compiler.compiler import SiddhiCompiler
from siddhi_tpu.compiler.errors import SiddhiParserException
