"""The declared actuator registry — every live knob the autopilot may
touch, with its typed-knob name and hard bounds.

graftlint R7 (``analysis/rules_actuators.py``) holds this registry to
the same bidirectional parity discipline as metric families (R3) and
device instruments (R6): every ``Actuator(...)`` must name a typed knob
declared in ``core/util/knobs.py``, every ``PolicyRule(...)`` must name
a declared actuator, and an actuator no policy rule can ever reach is a
dead declaration — all three are lint findings.

Every ``apply`` preserves WHAT the engine emits by construction — it
may only change when/where work runs:

- ``pipeline_depth``  plain attr write; the CompletionPump reads
                      ``app_context.pipeline_depth`` at every submit.
- ``ingest_pool``     ``IngestPackPool.resize`` (ordered merge keeps
                      sub-batch sequence numbers authoritative).
- ``join_partitions`` Wp shrink through the same rebuild path the
                      PanJoin growth side uses (``_rebuild_side``).
- ``route_shards``    blue/green re-install via the canonical-snapshot
                      cross-restore path (``device_route_query_step``
                      on an already-routed runtime).
- ``admission_cap``   mutates the live ``OverloadConfig`` quotas.
- ``fuse_fanout``     dissolve/re-form fused fan-out groups, deferred
                      to a batch boundary on the delivering thread.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

# direction spellings used across policy, decision log and telemetry
UP, DOWN = "up", "down"


@dataclass(frozen=True)
class Actuator:
    """One declared actuation path.

    ``knob`` is the governing typed-knob key in ``core/util/knobs.py``
    (graftlint R7 checks the reference). ``lo``/``hi`` are hard value
    bounds the policy may never push past. ``apply(rt, direction)``
    returns ``(old, new)`` when it changed something, None when the
    actuation does not apply to this runtime (nothing to log)."""

    name: str
    knob: str
    lo: int
    hi: int
    doc: str
    apply: Optional[Callable] = None


def _clamp(v: int, lo: int, hi: int) -> int:
    return max(lo, min(hi, v))


def _apply_pipeline_depth(rt, direction) -> Optional[Tuple[int, int]]:
    ctx = rt.app_context
    old = int(getattr(ctx, "pipeline_depth", 1) or 1)
    new = _clamp(old + (1 if direction == UP else -1), 1, 8)
    if new == old:
        return None
    # the pump reads app_context.pipeline_depth live at every submit —
    # in-flight batches drain at the old depth, the next submit sees new
    ctx.pipeline_depth = new
    return old, new


def _apply_ingest_pool(rt, direction) -> Optional[Tuple[int, int]]:
    ctx = rt.app_context
    pool = getattr(ctx, "ingest_pack_pool", None)
    old = int(pool.workers) if pool is not None else 0
    new = _clamp(old + (1 if direction == UP else -1), 0, 8)
    if new == old:
        return None
    if pool is None:
        # pool-from-zero: same construction start() performs lazily
        from siddhi_tpu.core.stream.input.pack_pool import IngestPackPool

        ctx.ingest_pack_pool = IngestPackPool(
            ctx, workers=new, split_rows=ctx.ingest_split)
    elif new == 0:
        # pool-to-zero: graceful drain; in-flight run_ordered calls
        # detect the shutdown race and re-pack inline (bit-identical)
        pool.shutdown()
        ctx.ingest_pack_pool = None
    else:
        pool.resize(new)
    ctx.ingest_pool = new
    return old, new


def _apply_join_partitions(rt, direction) -> Optional[Tuple[int, int]]:
    """Shrink-only: Wp GROWTH stays where it always was (the engine
    grows pre-dispatch inside ``prepare_batch`` the moment occupancy
    demands it); the autopilot's contribution is the reverse path —
    releasing over-provisioned sub-windows after a skew burst passes."""
    if direction != DOWN:
        return None
    changed = None
    for qr in rt.query_runtimes.values():
        eng = getattr(qr, "engine", None)
        if eng is None or not hasattr(eng, "shrink_partitions"):
            continue
        with qr._lock:   # no batch mid-step while the directory rebuilds
            shrunk = eng.shrink_partitions()
        for _side, (old_wp, new_wp) in (shrunk or {}).items():
            changed = (old_wp, new_wp) if changed is None else \
                (max(changed[0], old_wp), max(changed[1], new_wp))
    return changed


def _apply_route_shards(rt, direction) -> Optional[Tuple[int, int]]:
    from siddhi_tpu.parallel.mesh import (
        device_route_query_step,
        make_mesh,
        route_ineligibility,
    )
    import jax

    n_dev = len(jax.devices())
    cap = int(getattr(rt.app_context, "route_shards", 0) or 0) or n_dev
    changed = None
    for qr in rt.query_runtimes.values():
        layout = getattr(qr, "_route_layout", None)
        if layout is None or route_ineligibility(qr) is not None:
            continue   # never routes an UNrouted query — install is a
            # deployment decision; the autopilot only re-sizes
        old = int(layout.n)
        new = old * 2 if direction == UP else old // 2
        if new < 2 or new > min(cap, n_dev) or new == old:
            continue
        with qr._lock:
            # drain this owner's pipelined batches so the canonical
            # snapshot captures a settled state (owner -> pump order)
            rt.app_context.completion_pump.flush_owner(qr)
            device_route_query_step(
                qr, make_mesh(new), rows_per_shard=layout.rows_per_shard,
                exchange=layout.exchange)
        changed = (old, new)
    return changed


def _apply_admission_cap(rt, direction) -> Optional[Tuple[int, int]]:
    ctl = getattr(rt.app_context, "overload", None)
    if ctl is None or ctl.config.queue_quota is None:
        return None   # no quotas armed: nothing to cap
    old = int(ctl.config.queue_quota)
    new = _clamp(old * 2 if direction == UP else old // 2, 16, 1 << 20)
    if new == old:
        return None
    # live config mutation — admit() reads the config per call, and the
    # quota gauges divide by it, so /metrics tracks the new cap at once
    ctl.config.queue_quota = new
    return old, new


def _apply_fuse_fanout(rt, direction) -> Optional[Tuple[int, int]]:
    from siddhi_tpu.core.plan.fanout_plan import plan_junction_groups

    ctx = rt.app_context
    target = direction == UP
    old_n = len(rt.fused_fanout_groups)
    if target and old_n > 0:
        return None          # already fused
    if not target and old_n == 0 and not ctx.fuse_fanout:
        return None          # already dissolved
    ctx.fuse_fanout = target

    def _refit(junction):
        # runs ON the delivering thread at a batch boundary (the
        # junction drains deferred mutations before fanning a batch
        # out), so the receiver list is never rewired mid-delivery
        for g in [g for g in list(rt.fused_fanout_groups)
                  if g.junction is junction]:
            g.dissolve()
            try:
                rt.fused_fanout_groups.remove(g)
            except ValueError:
                pass
        if target:
            rt.fused_fanout_groups.extend(plan_junction_groups(junction))

    junctions = {g.junction for g in rt.fused_fanout_groups} if not target \
        else set(rt.junctions.values())
    for j in junctions:
        j.defer_mutation(lambda jn=j: _refit(jn))
    return (old_n, 0) if not target else (0, 1)


def _declare(*actuators: Actuator) -> Dict[str, Actuator]:
    return {a.name: a for a in actuators}


ACTUATORS: Dict[str, Actuator] = _declare(
    Actuator(name="pipeline_depth", knob="pipeline_depth", lo=1, hi=8,
             doc="CompletionPump overlap depth (live attr read)",
             apply=_apply_pipeline_depth),
    Actuator(name="ingest_pool", knob="ingest_pool", lo=0, hi=8,
             doc="IngestPackPool worker count (ordered-merge resize)",
             apply=_apply_ingest_pool),
    Actuator(name="join_partitions", knob="join_partition_slack", lo=1,
             hi=64,
             doc="device-join Wp shrink (growth stays in prepare_batch)",
             apply=_apply_join_partitions),
    Actuator(name="route_shards", knob="route_shards", lo=2, hi=64,
             doc="routed shard count (canonical blue/green re-install)",
             apply=_apply_route_shards),
    Actuator(name="admission_cap", knob="quota_queue_depth", lo=16,
             hi=1 << 20,
             doc="live OverloadConfig queue quota",
             apply=_apply_admission_cap),
    Actuator(name="fuse_fanout", knob="fuse_fanout", lo=0, hi=1,
             doc="fan-out fusion dissolve/re-form at a batch boundary",
             apply=_apply_fuse_fanout),
)
