"""Rule/hysteresis policy layer: named bottleneck -> bounded knob delta.

Plain rules, deliberately so: each :class:`PolicyRule` names the ONE
declared actuator it may drive (graftlint R7 checks the reference) and
inspects only the read-only :class:`~siddhi_tpu.autopilot.signals.
SignalSnapshot`. The hysteresis machinery wrapping the rules is what
keeps a closed loop from chewing on itself:

- **cooldown**: after a knob moves, it holds still for
  ``autopilot_cooldown_s`` seconds;
- **oscillation damping**: a rule wanting to REVERSE a knob's last
  direction within two cooldown windows is suppressed (logged with
  ``applied=False`` so the flapping is auditable, not silent);
- **compile-storm backoff**: while the app's summed jit-compile count
  is climbing between ticks, ALL actuation freezes — re-steering an
  engine that is busy recompiling only feeds the storm.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from siddhi_tpu.autopilot.actuators import ACTUATORS, DOWN, UP
from siddhi_tpu.autopilot.signals import SignalSnapshot


@dataclass(frozen=True)
class PolicyRule:
    """One observation->direction mapping. ``when(sig)`` returns "up",
    "down" or None; ``name`` is the reason tag on the decision log and
    the ``siddhi_autopilot_decisions_total{reason=...}`` label."""

    name: str
    actuator: str
    when: Optional[Callable[[SignalSnapshot], Optional[str]]] = None


@dataclass
class Decision:
    """One policy verdict (logged even when damping/dry_run stops it)."""

    seq: int
    t: float
    app: str
    actuator: str
    knob: str            # the actuator's typed-knob key
    direction: str
    reason: str
    applied: bool = False
    old: Optional[int] = None
    new: Optional[int] = None

    def as_dict(self) -> dict:
        d = {"seq": self.seq, "t": round(self.t, 3), "app": self.app,
             "actuator": self.actuator, "knob": self.knob,
             "direction": self.direction, "reason": self.reason,
             "applied": self.applied}
        if self.old is not None:
            d["old"] = self.old
            d["new"] = self.new
        return d


def _device_bound(sig: SignalSnapshot) -> Optional[str]:
    b = sig.worst_bottleneck()
    if b is None:
        return None
    if b.get("stage") == "device" and (b.get("utilization") or 0) >= 0.5 \
            and sig.pipeline_depth < 8:
        return UP            # more overlap hides device latency
    if (b.get("utilization") or 0) < 0.15 and sig.pipeline_depth > 2:
        return DOWN          # pipeline deeper than the load needs
    return None


def _pack_bound(sig: SignalSnapshot) -> Optional[str]:
    b = sig.worst_bottleneck()
    if b is not None and b.get("stage") == "pack" \
            and (b.get("utilization") or 0) >= 0.3:
        return UP            # shard pack/encode across more workers
    if sig.pool_workers is not None and sig.pool_workers > 1 \
            and sig.pool_utilization < 0.2 \
            and (b is None or b.get("stage") != "pack"):
        return DOWN          # pool idling: hand the cores back
    return None


def _join_overprovisioned(sig: SignalSnapshot) -> Optional[str]:
    return DOWN if sig.join_shrinkable else None


def _shard_pressure(sig: SignalSnapshot) -> Optional[str]:
    if not sig.routed:
        return None
    b = sig.worst_bottleneck()
    if b is None:
        return None
    if b.get("stage") == "device" and (b.get("utilization") or 0) >= 0.9:
        return UP            # spread keys across more shards
    if (b.get("utilization") or 0) < 0.05 and max(sig.routed.values()) > 2:
        return DOWN          # exchange overhead for idle shards
    return None


def _queue_pressure(sig: SignalSnapshot) -> Optional[str]:
    qs = [v for k, v in sig.quota.items()
          if k.startswith("queue_utilization")]
    if not qs:
        return None
    if max(qs) >= 0.9:
        return DOWN          # shed earlier: protect latency over admission
    if max(qs) < 0.3:
        return UP            # pressure cleared: relax back toward config
    return None


def _fusion_churn(sig: SignalSnapshot) -> Optional[str]:
    b = sig.worst_bottleneck()
    if sig.fused_groups == 0 and b is not None \
            and b.get("stage") == "dispatch" \
            and (b.get("utilization") or 0) >= 0.5:
        return UP            # per-query dispatch overhead: re-form groups
    return None


# ONE rule per actuation path; each names its actuator literally so the
# R7 parity check can hold declarations and reachers to each other.
RULES = (
    PolicyRule(name="device_bound", actuator="pipeline_depth",
               when=_device_bound),
    PolicyRule(name="pack_bound", actuator="ingest_pool",
               when=_pack_bound),
    PolicyRule(name="join_overprovisioned", actuator="join_partitions",
               when=_join_overprovisioned),
    PolicyRule(name="shard_pressure", actuator="route_shards",
               when=_shard_pressure),
    PolicyRule(name="queue_pressure", actuator="admission_cap",
               when=_queue_pressure),
    PolicyRule(name="dispatch_bound", actuator="fuse_fanout",
               when=_fusion_churn),
)


@dataclass
class _KnobState:
    last_t: float = -1e18        # monotonic time of last APPLIED move
    last_direction: Optional[str] = None


@dataclass
class Policy:
    """Per-app hysteresis state around the shared RULES table."""

    cooldown_s: float = 5.0
    rules: tuple = RULES
    knobs: Dict[str, _KnobState] = field(default_factory=dict)
    last_jit_compiles: Optional[int] = None
    frozen: bool = False         # compile-storm backoff engaged last tick

    def observe_compiles(self, jit_compiles: int) -> bool:
        """Update the compile-storm detector; True = actuation frozen
        this tick (``siddhi_jit_compiles_total`` climbed since last)."""
        prev, self.last_jit_compiles = self.last_jit_compiles, jit_compiles
        self.frozen = prev is not None and jit_compiles > prev
        return self.frozen

    def decide(self, sig: SignalSnapshot, now: float) -> List[dict]:
        """Run every rule; returns verdicts as
        ``{"rule", "direction", "blocked"}`` — ``blocked`` is None when
        the move may actuate, else "cooldown" / "damped" (the caller
        logs blocked verdicts too; an invisible suppression is how
        oscillation hides)."""
        out = []
        for rule in self.rules:
            direction = rule.when(sig) if rule.when is not None else None
            if direction is None:
                continue
            st = self.knobs.setdefault(rule.actuator, _KnobState())
            blocked = None
            if now - st.last_t < self.cooldown_s:
                blocked = "cooldown"
            elif st.last_direction is not None \
                    and direction != st.last_direction \
                    and now - st.last_t < 2 * self.cooldown_s:
                blocked = "damped"
            out.append({"rule": rule, "direction": direction,
                        "blocked": blocked})
        return out

    def applied(self, actuator: str, direction: str, now: float) -> None:
        st = self.knobs.setdefault(actuator, _KnobState())
        st.last_t = now
        st.last_direction = direction

    def bounds_ok(self, actuator: str, value: int) -> bool:
        a = ACTUATORS[actuator]
        return a.lo <= value <= a.hi
