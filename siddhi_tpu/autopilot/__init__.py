"""Autopilot: a closed-loop controller over the engine's live knobs.

The engine measures everything — critical-path verdicts naming the
bottleneck stage (``observability/journey.py``), device-instrument
fills and shard skew, quota-utilization gauges, per-program jit-compile
counts — but every performance knob was still set by hand; the only
adaptive behaviors were PanJoin Wp growth and the AUTO join-partition
default. This package closes the observe→decide→actuate loop
(ROADMAP item 4), generalizing PanJoin's adaptive repartitioning
across the whole engine while every re-merge it touches keeps the
ordered-emission discipline:

- ``signals.py``   read-only snapshot of what the engine already
                   exports (no new device pulls — scrape discipline);
- ``policy.py``    rule/hysteresis layer: cooldowns, per-knob bounds,
                   oscillation damping, compile-storm backoff;
- ``actuators.py`` the declared ``ACTUATORS`` registry (graftlint R7:
                   every actuator names a typed knob from
                   ``core/util/knobs.py``, bidirectionally);
- ``controller.py`` the per-process controller thread, bounded
                   decision log, ``GET /autopilot`` report and the
                   ``siddhi_autopilot_*`` telemetry.

Gated by the typed knob ``siddhi_tpu.autopilot`` — ``off`` (default)
is bit-identical to an engine without this package; ``dry_run``
decides and logs but never actuates. Actuation may change *when*
things run, never *what* is emitted.
"""

from siddhi_tpu.autopilot.actuators import ACTUATORS  # noqa: F401
from siddhi_tpu.autopilot.controller import (  # noqa: F401
    AutopilotController,
)
from siddhi_tpu.autopilot.policy import Policy, PolicyRule  # noqa: F401
from siddhi_tpu.autopilot.signals import SignalSnapshot, collect  # noqa: F401
