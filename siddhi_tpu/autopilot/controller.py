"""The per-process autopilot controller: one thread, many apps.

Same process-singleton discipline as ``resilience/overload.py``'s
OverloadManager: ``SiddhiAppRuntime.start()`` registers the app when
``siddhi_tpu.autopilot`` != off, ``shutdown()`` unregisters it
identity-pinned (an old runtime shutting down never strips a newer
same-named app's controller). Each tick per app:

    observe (signals.collect, host reads only)
      -> decide (policy rules under cooldown/damping/compile-backoff)
        -> actuate (mode 'on') or log-only (mode 'dry_run')

Every verdict — applied, damped, cooling down or dry-run — lands in a
bounded per-app decision log (the ``GET /autopilot`` report) and on the
decision counter (``siddhi_autopilot_decisions_total{knob,direction,
reason}`` after export). Ticks also run manually via
``AutopilotController.instance().tick(name)`` — tests and the soak
drive the loop deterministically that way.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from siddhi_tpu.analysis.guards import guarded
from siddhi_tpu.analysis.locks import make_lock
from siddhi_tpu.autopilot import signals
from siddhi_tpu.autopilot.actuators import ACTUATORS
from siddhi_tpu.autopilot.policy import Decision, Policy

_LOG = logging.getLogger("siddhi_tpu.autopilot")

DECISION_LOG_CAPACITY = 256
MODE_VALUES = {"off": 0.0, "dry_run": 1.0, "on": 2.0}


class _AppState:
    def __init__(self, rt):
        self.rt = rt
        ctx = rt.app_context
        self.policy = Policy(
            cooldown_s=float(getattr(ctx, "autopilot_cooldown_s", 5.0)))
        self.decisions: deque = deque(maxlen=DECISION_LOG_CAPACITY)
        self.seq = 0
        self.ticks = 0
        self.freezes = 0
        # ticks (thread + manual) on one app serialize on this
        self.lock = make_lock("autopilot")

    @property
    def mode(self) -> str:
        return str(getattr(self.rt.app_context, "autopilot", "off"))

    @property
    def interval_s(self) -> float:
        return float(getattr(self.rt.app_context,
                             "autopilot_interval_s", 0.25) or 0.25)


@guarded
class AutopilotController:
    """Process-wide controller registry + tick thread."""

    _instance: Optional["AutopilotController"] = None
    _instance_lock = threading.Lock()

    GUARDED_BY = {"_apps": "autopilot"}

    def __init__(self):
        self._lock = make_lock("autopilot")
        self._apps: Dict[str, _AppState] = {}
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._stopping = False

    @classmethod
    def instance(cls) -> "AutopilotController":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = AutopilotController()
            return cls._instance

    # ------------------------------------------------------ registration

    def register(self, app_runtime) -> _AppState:
        """Idempotent attach; enables journey tracing (refcounted) for
        the app's lifetime — the critical-path report is the
        controller's primary signal."""
        ctx = app_runtime.app_context
        name = ctx.name
        with self._lock:
            st = self._apps.get(name)
            if st is not None and st.rt is app_runtime:
                return st
            if st is not None:
                # a same-named app replaces the registration (blue/green
                # redeploy); the OLD runtime's unregister is pinned to
                # its own state object so it cannot strip this one
                self._release(st)
            from siddhi_tpu.observability import journey

            journey.enable()
            st = _AppState(app_runtime)
            self._apps[name] = st
            tel = getattr(ctx, "telemetry", None)
            if tel is not None:
                tel.gauge("autopilot.mode",
                          lambda s=st: MODE_VALUES.get(s.mode, 0.0))
            self._ensure_thread()
            return st

    def unregister(self, name: str, app_runtime=None) -> None:
        """Identity-pinned: passing ``app_runtime`` only detaches when
        the registration still belongs to that runtime."""
        with self._lock:
            st = self._apps.get(name)
            if st is None:
                return
            if app_runtime is not None and st.rt is not app_runtime:
                return
            del self._apps[name]
            self._release(st)
            if not self._apps:
                self._stop_thread_locked()

    def _release(self, st: _AppState) -> None:
        tel = getattr(st.rt.app_context, "telemetry", None)
        if tel is not None:
            tel.remove_gauge("autopilot.mode")
        from siddhi_tpu.observability import journey

        journey.disable()

    # ------------------------------------------------------- tick thread

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stopping = False
        self._wake.clear()
        self._thread = threading.Thread(
            target=self._run, name="siddhi-autopilot", daemon=True)
        self._thread.start()

    def _stop_thread_locked(self) -> None:
        self._stopping = True
        self._wake.set()
        self._thread = None

    def _run(self) -> None:
        while True:
            with self._lock:
                if self._stopping or not self._apps:
                    return
                names = list(self._apps)
                interval = min(self._apps[n].interval_s for n in names)
            # wait BEFORE the first tick: a freshly-registered app gets
            # one full interval of undisturbed warmup, and tests driving
            # manual tick(name, now=...) clocks see no thread tick race
            if self._wake.wait(timeout=interval):
                self._wake.clear()
                continue
            for name in names:
                try:
                    self.tick(name)
                except Exception:  # noqa: BLE001 — one bad tick must not
                    # kill the controller for every app in the process
                    _LOG.exception("autopilot tick failed for %s", name)

    # -------------------------------------------------------------- tick

    def tick(self, name: str, now: Optional[float] = None) -> List[dict]:
        """One observe->decide->actuate cycle for one app. Returns the
        decision-log entries appended by this tick."""
        with self._lock:
            st = self._apps.get(name)
        if st is None:
            return []
        mode = st.mode
        if mode == "off":
            return []
        now = time.monotonic() if now is None else now
        with st.lock:
            return self._tick_locked(st, mode, now)

    def _tick_locked(self, st: _AppState, mode: str, now: float) -> List[dict]:
        rt = st.rt
        ctx = rt.app_context
        tel = getattr(ctx, "telemetry", None)
        sig = signals.collect(rt)
        st.ticks += 1
        if tel is not None:
            tel.count("autopilot.ticks")
        if st.policy.observe_compiles(sig.jit_compiles):
            # compile-storm backoff: programs are still compiling —
            # freeze every knob until the count stops climbing
            st.freezes += 1
            if tel is not None:
                tel.count("autopilot.freezes")
            return []
        entries: List[dict] = []
        for verdict in st.policy.decide(sig, now):
            rule, direction = verdict["rule"], verdict["direction"]
            blocked = verdict["blocked"]
            actuator = ACTUATORS[rule.actuator]
            st.seq += 1
            dec = Decision(seq=st.seq, t=now, app=ctx.name,
                           actuator=actuator.name, knob=actuator.knob,
                           direction=direction, reason=rule.name)
            applied_change = None
            if blocked is None and mode == "on" \
                    and actuator.apply is not None:
                try:
                    applied_change = actuator.apply(rt, direction)
                except Exception:  # noqa: BLE001 — a failed actuation is
                    # a logged non-event, never an engine fault
                    _LOG.exception("actuator %s failed on %s",
                                   actuator.name, ctx.name)
            if applied_change is not None:
                dec.applied = True
                dec.old, dec.new = applied_change
                st.policy.applied(actuator.name, direction, now)
            entry = dec.as_dict()
            entry["mode"] = mode
            if blocked is not None:
                entry["blocked"] = blocked
            st.decisions.append(entry)
            entries.append(entry)
            if tel is not None:
                tel.count(f"autopilot.decisions.{actuator.knob}"
                          f".{direction}.{rule.name}")
        return entries

    # ------------------------------------------------------------ report

    def report(self, app: Optional[str] = None) -> dict:
        """The ``GET /autopilot`` body. Raises KeyError for an unknown
        app (the REST layer maps it to 404)."""
        with self._lock:
            states = dict(self._apps)
        if app is not None:
            if app not in states:
                raise KeyError(f"app '{app}' has no autopilot registration")
            states = {app: states[app]}
        apps = {}
        for name in sorted(states):
            st = states[name]
            apps[name] = {
                "mode": st.mode,
                "interval_s": st.interval_s,
                "cooldown_s": st.policy.cooldown_s,
                "frozen": st.policy.frozen,
                "ticks": st.ticks,
                "freezes": st.freezes,
                "decisions": list(st.decisions),
            }
        return {
            "actuators": {
                a.name: {"knob": a.knob, "lo": a.lo, "hi": a.hi,
                         "doc": a.doc}
                for a in ACTUATORS.values()},
            "decision_log_capacity": DECISION_LOG_CAPACITY,
            "apps": apps,
        }
