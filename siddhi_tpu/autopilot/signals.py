"""Read-only signal snapshot for the autopilot controller.

Everything here is assembled from surfaces the engine ALREADY exports:
``journey.critical_path_report`` (stage quantiles + named bottleneck),
the app's ``TelemetryRegistry`` snapshot (``pipeline.*.inflight``,
``ingest.pool.*``, ``quota.*`` utilization gauges, per-program jit
compile counts) and the device-join engines' host occupancy mirrors.
A collect() NEVER issues a device pull — the same scrape-path
discipline as ``GET /metrics`` (gauges read drained instrument lanes
or host mirrors; see ``observability/instruments.py``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from types import SimpleNamespace
from typing import Dict, Optional


@dataclass
class SignalSnapshot:
    """One observation of an app runtime, host-side only."""

    app: str
    # per-query bottleneck verdicts from the critical-path report:
    # {query: {"stage", "kind", "mean_ms", "utilization", ...}}
    bottlenecks: Dict[str, dict] = field(default_factory=dict)
    gauges: Dict[str, float] = field(default_factory=dict)
    counters: Dict[str, int] = field(default_factory=dict)
    # sum of per-program jit compiles — the compile-storm signal
    # (export.py renders the per-key detail as siddhi_jit_compiles_total)
    jit_compiles: int = 0
    # quota-utilization gauges with the "quota." prefix stripped
    quota: Dict[str, float] = field(default_factory=dict)
    # max pipeline.<owner>.inflight across owners (0 = nothing pending)
    pipeline_inflight: float = 0.0
    pipeline_depth: int = 1
    # ingest pool: configured workers / live utilization (absent = no pool)
    pool_workers: Optional[int] = None
    pool_utilization: float = 0.0
    pool_queue_depth: float = 0.0
    # device-join sides whose Wp could shrink back after a skew burst:
    # {query: {side: (current_wp, shrink_target)}}
    join_shrinkable: Dict[str, dict] = field(default_factory=dict)
    # routed queries: {query: shard_count}
    routed: Dict[str, int] = field(default_factory=dict)
    fused_groups: int = 0

    def worst_bottleneck(self) -> Optional[dict]:
        """The highest-utilization bottleneck verdict, with its query
        name added under ``"query"`` (None when journeys are off or no
        batch has completed yet)."""
        worst = None
        for q, b in self.bottlenecks.items():
            if not b or b.get("stage") is None:
                continue
            if worst is None or (b.get("utilization") or 0.0) > \
                    (worst.get("utilization") or 0.0):
                worst = dict(b)
                worst["query"] = q
        return worst


def collect(app_runtime) -> SignalSnapshot:
    """Assemble one :class:`SignalSnapshot` from ``app_runtime``'s
    existing observability surfaces. Host reads only."""
    ctx = app_runtime.app_context
    sig = SignalSnapshot(app=ctx.name)
    tel = getattr(ctx, "telemetry", None)
    if tel is not None:
        snap = tel.snapshot()
        sig.gauges = dict(snap.get("gauges", {}))
        sig.counters = dict(snap.get("counters", {}))
        sig.jit_compiles = sum(
            int(v.get("compiles", 0)) for v in snap.get("jit", {}).values())
    for name, val in sig.gauges.items():
        if name.startswith("quota."):
            sig.quota[name[len("quota."):]] = val
        elif name.startswith("pipeline.") and name.endswith(".inflight"):
            sig.pipeline_inflight = max(sig.pipeline_inflight, val or 0.0)
    sig.pipeline_depth = int(getattr(ctx, "pipeline_depth", 1) or 1)
    pool = getattr(ctx, "ingest_pack_pool", None)
    if pool is not None:
        sig.pool_workers = int(pool.workers)
        sig.pool_utilization = float(
            sig.gauges.get("ingest.pool.utilization", 0.0) or 0.0)
        sig.pool_queue_depth = float(
            sig.gauges.get("ingest.pool.queue_depth", 0.0) or 0.0)
    from siddhi_tpu.observability import journey

    if journey.enabled():
        # critical_path_report takes a manager; scope it to this one
        # runtime without touching the (possibly shared) real manager
        shim = SimpleNamespace(app_runtimes={ctx.name: app_runtime})
        try:
            rep = journey.critical_path_report(shim, ctx.name)
            queries = rep["apps"].get(ctx.name, {}).get("queries", {})
            sig.bottlenecks = {
                q: r.get("bottleneck") or {} for q, r in queries.items()}
        except Exception:  # noqa: BLE001 — observation must never throw
            sig.bottlenecks = {}
    for qname, qr in app_runtime.query_runtimes.items():
        eng = getattr(qr, "engine", None)
        if eng is not None and hasattr(eng, "shrink_candidates"):
            try:
                cands = eng.shrink_candidates()
            except Exception:  # noqa: BLE001 — host mirror read only
                cands = {}
            if cands:
                sig.join_shrinkable[qname] = cands
        layout = getattr(qr, "_route_layout", None)
        if layout is not None:
            sig.routed[qname] = int(layout.n)
    sig.fused_groups = len(getattr(app_runtime, "fused_fanout_groups", ()))
    return sig
