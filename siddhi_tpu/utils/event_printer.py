"""EventPrinter: debugging print helpers (reference
``util/EventPrinter.java``) — attachable as stream/query callbacks."""

from __future__ import annotations

from typing import List, Optional

from siddhi_tpu.core.query.callback import QueryCallback
from siddhi_tpu.core.stream.output.stream_callback import StreamCallback


def print_events(timestamp, in_events: Optional[List], remove_events: Optional[List]):
    """Reference EventPrinter.print(long, Event[], Event[])."""
    print(f"Events{{ @timestamp = {timestamp}, inEvents = {in_events}, "
          f"RemoveEvents = {remove_events} }}")


class PrintingStreamCallback(StreamCallback):
    """`rt.add_callback(stream_id, PrintingStreamCallback())`."""

    def receive(self, events: List):
        print(events)


class PrintingQueryCallback(QueryCallback):
    """`rt.add_callback(query_name, PrintingQueryCallback())`."""

    def receive(self, timestamp, in_events, remove_events):
        print_events(timestamp, in_events, remove_events)
