"""Documentation generator: markdown reference of the queryable surface.

Mirror of the reference's annotation-driven doc generator
(``siddhi-doc-gen``: walks @Extension metadata into site docs) — here the
source of truth is the engine's own dispatch tables (window factories,
expression built-ins, aggregators, transport registries) plus any
extensions registered on a ``SiddhiManager``.
"""

from __future__ import annotations

import inspect
from typing import Optional

_WINDOWS_DEVICE = [
    ("length(n)", "sliding count window"),
    ("lengthBatch(n)", "tumbling count window"),
    ("time(t)", "sliding time window"),
    ("timeBatch(t[, startTime])", "tumbling time window"),
    ("externalTime(tsAttr, t)", "sliding window on an event-time attribute"),
    ("externalTimeBatch(tsAttr, t[, startTime[, timeout]])", "tumbling external-time window"),
    ("batch()", "per-chunk batch window"),
    ("timeLength(t, n)", "time+count bounded sliding window"),
    ("delay(t)", "emits events delayed by t"),
    ("hopping(windowT, hopT)", "trailing window emitted every hop"),
]
_WINDOWS_HOST = [
    ("sort(n, attr[, 'asc'|'desc', ...])", "keeps the n smallest/largest"),
    ("frequent(n[, attrs])", "Misra-Gries frequent keys"),
    ("lossyFrequent(support[, error][, attrs])", "lossy counting"),
    ("session(gap[, key[, allowedLatency]])", "per-key session chunks"),
    ("cron('<expr>')", "flushes on a cron schedule"),
    ("expression('<expr>')", "retention while the expression holds"),
    ("expressionBatch('<expr>')", "flushes when the expression breaks"),
]
_WINDOWS_KEYED = ["length", "lengthBatch", "batch", "time", "timeBatch", "hopping",
                  "externalTime", "timeLength", "delay", "session (incl. allowedLatency)",
                  "sort", "frequent", "lossyFrequent", "cron",
                  "expression", "expressionBatch (per-key host instances)"]
_AGGREGATORS = ["sum", "count", "avg", "min", "max", "stdDev", "and", "or",
                "minForever", "maxForever", "distinctCount", "unionSet"]
_INCREMENTAL_AGGS = ["sum", "count", "avg", "min", "max", "distinctCount"]
_FUNCTIONS = [
    "cast(x, 'type')", "convert(x, 'type')", "ifThenElse(c, a, b)",
    "coalesce(a, b, ...)", "default(x, d)", "maximum(...)", "minimum(...)",
    "instanceOfBoolean/String/Integer/Long/Float/Double(x)",
    "eventTimestamp()", "currentTimeMillis()", "uuid()", "log(...)",
    "createSet(x)", "sizeOfSet(s)",
]
_STREAM_FUNCTIONS = [
    "log([priority,] [message,] [is.event.logged])",
    "pol2Cart(theta, rho[, z])",
]
_SOURCES = ["inMemory(topic)"]
_SINKS = ["inMemory(topic)", "log([prefix])",
          "@distribution(strategy='roundRobin|broadcast|partitioned', @destination...)"]
_MAPPERS = ["passThrough", "json"]
_STORES = ["inMemory (@store)"]


def generate_docs(manager=None, title: str = "siddhi_tpu reference") -> str:
    """Markdown reference of windows, aggregators, functions, transports,
    and (when a manager is given) its registered extensions."""
    out = [f"# {title}", ""]

    def section(name, rows):
        out.append(f"## {name}")
        out.append("")
        for item in rows:
            if isinstance(item, tuple):
                out.append(f"- `{item[0]}` — {item[1]}")
            else:
                out.append(f"- `{item}`")
        out.append("")

    section("Windows (device)", _WINDOWS_DEVICE)
    section("Windows (host)", _WINDOWS_HOST)
    section("Windows (keyed, inside partitions)", _WINDOWS_KEYED)
    section("Attribute aggregators", _AGGREGATORS)
    section("Incremental aggregators (define aggregation)", _INCREMENTAL_AGGS)
    section("Built-in functions", _FUNCTIONS)
    section("Stream functions (#handler)", _STREAM_FUNCTIONS)
    section("Sources", _SOURCES)
    section("Sinks", _SINKS)
    section("Mappers", _MAPPERS)
    section("Table stores", _STORES)

    if manager is not None and getattr(manager.siddhi_context, "extensions", None):
        out.append("## Registered extensions")
        out.append("")
        for name, cls in sorted(manager.siddhi_context.extensions.items()):
            doc = inspect.getdoc(cls) or ""
            first = doc.splitlines()[0] if doc else ""
            out.append(f"- `{name}` ({cls.__name__})" + (f" — {first}" if first else ""))
        out.append("")
    return "\n".join(out)


def write_docs(path: str, manager=None) -> str:
    md = generate_docs(manager)
    with open(path, "w") as f:
        f.write(md)
    return path
