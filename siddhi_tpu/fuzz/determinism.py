"""The deterministic-time discipline for differential checks.

**The lesson (learned the hard way in ``tools/quick_join_check.py``,
PR 9):** any window whose expiry is driven by the WALL CLOCK — plain
``window.time``, ``window.timeBatch``, ``window.session`` and their
keyed (partitioned) variants — makes two runs of the same feed only
*approximately* comparable: expiry rides scheduler timers whose firing
order interleaves with batch processing differently run to run, so a
bit-identity diff between two strategies reports phantom divergences.

The fix is never "compare loosely"; it is "generate only windows whose
semantics are a pure function of the DATA": count-driven windows
(``length`` / ``lengthBatch``) and data-driven time windows
(``externalTime`` / ``externalTimeBatch``, which expire off an event
timestamp attribute the feed controls). Every differential harness —
the fuzzer's generator, the quick checks, future bench bit-identity
asserts — must draw windows from this module instead of rediscovering
the rule.

``window.time``/``timeBatch``/``session``/``hopping`` shapes still
deserve coverage for *eligibility classification* (the census: build
the app, read the reason codes, never diff outputs) — that is what
:data:`CENSUS_ONLY_WINDOWS` is for.
"""

from __future__ import annotations

from typing import Optional, Tuple

# Window kinds whose emissions are a pure function of the input feed —
# the ONLY kinds a cross-run differential check may generate. Entries
# are (kind, needs_ts_attr): externalTime variants take the name of a
# long timestamp attribute as their first parameter.
DETERMINISTIC_WINDOWS: Tuple[Tuple[str, bool], ...] = (
    ("length", False),
    ("lengthBatch", False),
    ("externalTime", True),
    ("externalTimeBatch", True),
)

# Wall-clock-driven kinds: valid for census/eligibility classification
# (build + classify, no output diff), NEVER for a bit-identity run.
CENSUS_ONLY_WINDOWS: Tuple[str, ...] = (
    "time", "timeBatch", "session", "hopping", "delay",
)


def is_deterministic(kind: Optional[str]) -> bool:
    """May a differential (bit-identity) check use this window kind?
    ``None`` (no window) is deterministic."""
    if kind is None:
        return True
    return any(kind == k for k, _ in DETERMINISTIC_WINDOWS)


def window_clause(kind: Optional[str], param: int,
                  ts_attr: Optional[str] = None,
                  unit_ms: int = 1000) -> str:
    """Render ``#window.<kind>(...)`` (empty string for ``None``).

    ``param`` is rows for count windows and the span in ``unit_ms``
    multiples for externalTime windows; ``ts_attr`` names the long
    timestamp attribute externalTime variants expire against."""
    if kind is None:
        return ""
    if kind in ("length", "lengthBatch"):
        return f"#window.{kind}({param})"
    if kind in ("externalTime", "externalTimeBatch"):
        if not ts_attr:
            raise ValueError(f"window.{kind} needs a timestamp attribute")
        return f"#window.{kind}({ts_attr}, {param * unit_ms} millisec)"
    if kind == "hopping":
        # census-only shapes render too (the classifier must BUILD the
        # app) — callers assert is_deterministic() before diffing.
        # hopping(windowTime, hopTime) takes two time constants
        return f"#window.hopping({param} sec, {param} sec)"
    if kind in CENSUS_ONLY_WINDOWS:
        return f"#window.{kind}({param} sec)"
    raise ValueError(f"unknown window kind '{kind}'")
