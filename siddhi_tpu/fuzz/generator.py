"""Seeded typed SiddhiQL generator: random-but-valid by construction.

The "Stream Types" discipline (PAPERS.md): every fragment is composed
against a typed stream context — a filter only compares attributes of
compatible types, a projection's expression types are computed as it is
built (so chained queries know their derived stream's schema), an
aggregation only folds numeric attributes, a join key is an attribute
both sides share at the same type. A generated app therefore compiles
by construction; "100 seeded cases all compile" is a regression test,
not a hope.

Determinism: windows are drawn exclusively from
``fuzz.determinism.DETERMINISTIC_WINDOWS`` (count-driven or
externalTime data-driven expiry) so two runs of one feed are
bit-comparable — the wall-clock window lesson is enforced here, at the
grammar, not rediscovered per check.

Every generated query carries eligibility EXPECTATIONS for the surfaces
the grammar is sure about (e.g. "partitioned + keyed length window =>
route-eligible", "two-stage pattern => route NFA_QUERY"): the runner
asserts the engine's census agrees, so a silent strategy fallback — an
eligible shape quietly taking the legacy path — is a detected coverage
gap even when outputs match.

Reproducible: same seed => same corpus, byte for byte (``random.Random``
only, no numpy RNG, no wall clock).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from siddhi_tpu.core.eligibility import (
    SURFACE_JOIN_ENGINE,
    SURFACE_JOIN_PIPELINE,
    SURFACE_ROUTE,
    ReasonCode,
)
from siddhi_tpu.fuzz.schema import (
    CaseSpec,
    JoinSpec,
    PatternSpec,
    QuerySpec,
    StreamSpec,
)

_SYMS = ("S0", "S1", "S2", "S3", "S4", "S5")
_NUMERIC = ("int", "long", "float", "double")
_AGGS = ("sum", "count", "avg", "min", "max")


class CaseGenerator:
    """Seeded generator of :class:`CaseSpec` corpora."""

    def __init__(self, seed: int, events_per_case: int = 80,
                 max_queries: int = 4):
        self.seed = seed
        self.events_per_case = events_per_case
        self.max_queries = max_queries

    def corpus(self, n_cases: int) -> List[CaseSpec]:
        return [self.case(i) for i in range(n_cases)]

    def case(self, index: int) -> CaseSpec:
        """Case ``index`` of this generator's corpus — a pure function
        of (seed, index)."""
        rng = random.Random((self.seed << 20) ^ index)
        streams = self._streams(rng)
        ctx = _TypedContext(streams)
        n_q = rng.randint(1, self.max_queries)
        queries = [self._query(rng, ctx, i) for i in range(n_q)]
        events = self._events(rng, streams)
        return CaseSpec(seed=self.seed, streams=streams, queries=queries,
                        events=events,
                        notes=f"generator seed={self.seed} case={index}")

    # ------------------------------------------------------------ schemas

    def _streams(self, rng: random.Random) -> List[StreamSpec]:
        out = []
        for i in range(rng.randint(1, 3)):
            # every stream shares the spine the grammar composes
            # against: ts (externalTime expiry clock), sym (join /
            # partition / group key), plus 2-4 random typed value attrs
            attrs: List[Tuple[str, str]] = [("ts", "long"), ("sym", "string")]
            attrs.append(("v0", rng.choice(("int", "long"))))
            for j in range(1, rng.randint(2, 4)):
                attrs.append((f"v{j}", rng.choice(
                    ("int", "long", "float", "double", "bool", "string"))))
            out.append(StreamSpec(f"In{i}", attrs))
        return out

    # ------------------------------------------------------------ queries

    def _query(self, rng: random.Random, ctx: "_TypedContext",
               i: int) -> QuerySpec:
        roll = rng.random()
        if roll < 0.18 and len(ctx.inputs) >= 2:
            return self._pattern_query(rng, ctx, i)
        if roll < 0.45 and len(ctx.inputs) >= 2:
            return self._join_query(rng, ctx, i)
        return self._single_query(rng, ctx, i)

    def _single_query(self, rng: random.Random, ctx: "_TypedContext",
                      i: int) -> QuerySpec:
        src = ctx.pick_source(rng)
        attrs = dict(ctx.schema(src))
        partitioned = rng.random() < 0.35 and "sym" in attrs \
            and src in ctx.inputs
        # windows: deterministic kinds only (fuzz.determinism); the
        # externalTime variants need the ts clock attribute
        win: Optional[List] = None
        ts_attr = "ts" if attrs.get("ts") == "long" else None
        w = rng.random()
        if partitioned:
            # keyed variants: length (route-eligible), lengthBatch /
            # externalTime (deterministic but not global-aware yet)
            if w < 0.5:
                win = ["length", rng.choice((4, 8, 16))]
            elif w < 0.7:
                win = ["lengthBatch", rng.choice((2, 4))]
            elif w < 0.85 and ts_attr:
                win = ["externalTime", rng.randint(1, 3)]
        else:
            if w < 0.35:
                win = ["length", rng.choice((4, 8, 16))]
            elif w < 0.55:
                win = ["lengthBatch", rng.choice((2, 4))]
            elif w < 0.75 and ts_attr:
                win = [rng.choice(("externalTime", "externalTimeBatch")),
                       rng.randint(1, 3)]
        flt = self._filter(rng, attrs) if rng.random() < 0.5 else None
        group = None
        if rng.random() < 0.45 and "sym" in attrs:
            group = ["sym"]
        select, out_schema, agg_aliases = self._select(rng, attrs, group)
        having = None
        if group and agg_aliases and rng.random() < 0.3:
            having = f"{rng.choice(agg_aliases)} > {rng.randint(1, 20)}"
        q = QuerySpec(
            name=f"q{i}", kind="single", insert_into=f"Out{i}",
            from_stream=src, window=win,
            ts_attr=ts_attr if win and win[0].startswith("external") else None,
            filter=flt, select_items=select, group_by=group, having=having,
            partition_key="sym" if partitioned else None)
        q.expect[SURFACE_ROUTE] = self._route_expectation(
            partitioned, win, group).value
        ctx.define_derived(q.insert_into, out_schema)
        return q

    def _route_expectation(self, partitioned: bool, win: Optional[List],
                           group) -> ReasonCode:
        """The v1 device-routing contract the generator KNOWS (mirrors
        ``parallel/mesh.route_ineligibility``; asserting the mirror is
        the point — drift = silent fallback)."""
        if partitioned:
            if win is None or win[0] == "length":
                return ReasonCode.ELIGIBLE
            return ReasonCode.WINDOW_NOT_GLOBAL_AWARE
        if win is not None:
            # the engine classifies window KIND before global-ness: any
            # non-keyed-length stage (plain Length/Time rings, the fused
            # sliding-agg stage a grouped window folds into) reports
            # WINDOW_NOT_GLOBAL_AWARE
            return ReasonCode.WINDOW_NOT_GLOBAL_AWARE
        if group:
            return ReasonCode.ELIGIBLE       # grouped agg, no window
        return ReasonCode.UNKEYED

    def _join_query(self, rng: random.Random, ctx: "_TypedContext",
                    i: int) -> QuerySpec:
        left, right = rng.sample(ctx.inputs, 2)
        la, ra = dict(ctx.schema(left)), dict(ctx.schema(right))
        partitioned = rng.random() < 0.25
        if partitioned:
            lwin: List = ["length", rng.choice((4, 8))]
            rwin: List = ["length", rng.choice((4, 8))]
        else:
            lwin = self._join_window(rng, la)
            rwin = self._join_window(rng, ra)
        join_type = "left outer join" if rng.random() < 0.3 else "join"
        uni = join_type == "join" and rng.random() < 0.2
        residual = None
        lnum = _numeric_attrs(la)
        rnum = _numeric_attrs(ra)
        if not partitioned and lnum and rnum and rng.random() < 0.35:
            residual = (f"{left}.{rng.choice(lnum)} > "
                        f"{right}.{rng.choice(rnum)}")
        group = None
        select: List[List[str]] = [[f"{left}.sym", "sym"]]
        agg_src = rng.choice(rnum) if rnum else None
        if rng.random() < 0.25 and agg_src:
            group = [f"{left}.sym"]
            select.append([f"sum({right}.{agg_src})", "total"])
        else:
            if lnum:
                a = rng.choice(lnum)
                select.append([f"{left}.{a}", f"l_{a}"])
            if rnum and join_type == "join":
                a = rng.choice(rnum)
                select.append([f"{right}.{a}", f"r_{a}"])
        q = QuerySpec(
            name=f"q{i}", kind="join", insert_into=f"Out{i}",
            ts_attr="ts",
            select_items=select, group_by=group,
            partition_key="sym" if partitioned else None,
            join=JoinSpec(left_stream=left, right_stream=right,
                          left_window=lwin, right_window=rwin,
                          key_attr="sym", join_type=join_type,
                          residual=residual, unidirectional=uni))
        if partitioned:
            q.expect[SURFACE_JOIN_ENGINE] = ReasonCode.PARTITIONED.value
            # a grouped selector forces the host keyed-select split even
            # inside a partition, which blocks the routed join path
            q.expect[SURFACE_ROUTE] = (
                ReasonCode.GROUPED_SELECT if group
                else ReasonCode.ELIGIBLE).value
        else:
            q.expect[SURFACE_JOIN_ENGINE] = ReasonCode.ELIGIBLE.value
            q.expect[SURFACE_ROUTE] = ReasonCode.JOIN_UNPARTITIONED.value
            q.expect[SURFACE_JOIN_PIPELINE] = (
                ReasonCode.GROUPED_SELECT if group
                else ReasonCode.ELIGIBLE).value
        return q

    def _join_window(self, rng: random.Random, attrs: Dict[str, str]) -> List:
        if attrs.get("ts") == "long" and rng.random() < 0.3:
            return ["externalTime", rng.randint(1, 2)]
        return ["length", rng.choice((4, 8, 16))]

    def _pattern_query(self, rng: random.Random, ctx: "_TypedContext",
                       i: int) -> QuerySpec:
        first, second = rng.sample(ctx.inputs, 2)
        fa, sa = dict(ctx.schema(first)), dict(ctx.schema(second))
        fnum, snum = _numeric_attrs(fa), _numeric_attrs(sa)
        c1 = (f"{rng.choice(fnum)} > {rng.randint(0, 30)}" if fnum
              else "sym == 'S0'")
        if snum and fnum and rng.random() < 0.5:
            c2 = f"{rng.choice(snum)} > e1.{rng.choice(fnum)}"
        else:
            c2 = (f"{rng.choice(snum)} > {rng.randint(0, 30)}" if snum
                  else "sym == 'S1'")
        select = [["e1.sym", "sym1"]]
        if fnum:
            select.append([f"e1.{rng.choice(fnum)}", "a1"])
        if snum:
            select.append([f"e2.{rng.choice(snum)}", "a2"])
        q = QuerySpec(
            name=f"q{i}", kind="pattern", insert_into=f"Out{i}",
            select_items=select,
            pattern=PatternSpec(first_stream=first, second_stream=second,
                                first_cond=c1, second_cond=c2,
                                every=rng.random() < 0.7))
        q.expect[SURFACE_ROUTE] = ReasonCode.NFA_QUERY.value
        return q

    # ----------------------------------------------------- typed fragments

    def _filter(self, rng: random.Random,
                attrs: Dict[str, str]) -> Optional[str]:
        terms = []
        num = _numeric_attrs(attrs)
        if num:
            terms.append(f"{rng.choice(num)} > {rng.randint(0, 40)}")
        if "sym" in attrs and rng.random() < 0.5:
            op = rng.choice(("==", "!="))
            terms.append(f"sym {op} '{rng.choice(_SYMS[:4])}'")
        bools = [n for n, t in attrs.items() if t == "bool"]
        if bools and rng.random() < 0.4:
            terms.append(f"{rng.choice(bools)} == true")
        if not terms:
            return None
        rng.shuffle(terms)
        take = terms[:rng.randint(1, min(2, len(terms)))]
        return f" {rng.choice(('and', 'or'))} ".join(take) \
            if len(take) > 1 else take[0]

    def _select(self, rng: random.Random, attrs: Dict[str, str],
                group) -> Tuple[List[List[str]], List[Tuple[str, str]],
                                List[str]]:
        """Typed projection/aggregation items. Returns (select_items,
        derived schema, aggregate aliases)."""
        from siddhi_tpu.ops.aggregators import agg_result_type
        from siddhi_tpu.query_api.definitions import AttrType

        items: List[List[str]] = []
        schema: List[Tuple[str, str]] = []
        agg_aliases: List[str] = []
        num = _numeric_attrs(attrs)
        if group:
            for g in group:
                items.append([g, g])
                schema.append((g, attrs[g]))
            for k in range(rng.randint(1, 2)):
                if num:
                    kind, src = rng.choice(_AGGS), rng.choice(num)
                elif "ts" in attrs:
                    kind, src = rng.choice(_AGGS), "ts"
                else:
                    kind, src = "count", group[0]
                alias = f"agg{k}"
                items.append([f"{kind}({src})", alias])
                rt = agg_result_type(kind, AttrType(attrs[src]))
                schema.append((alias, rt.value))
                agg_aliases.append(alias)
            return items, schema, agg_aliases
        # plain projection: a subset of attrs + at most one computed expr
        names = [n for n in attrs]
        rng.shuffle(names)
        for n in names[:rng.randint(1, max(1, len(names) - 1))]:
            items.append([n, n])
            schema.append((n, attrs[n]))
        ints = [n for n, t in attrs.items() if t in ("int", "long")]
        if ints and rng.random() < 0.45:
            roll = rng.random()
            a = rng.choice(ints)
            if roll < 0.4 and len(ints) >= 2:
                b = rng.choice([x for x in ints if x != a] or [a])
                expr, et = f"{a} + {b}", _promote_int(attrs[a], attrs[b])
            elif roll < 0.7:
                expr, et = f"{a} * {rng.randint(2, 5)}", attrs[a]
            else:
                lo, hi = sorted((rng.randint(0, 20), rng.randint(21, 50)))
                expr = f"ifThenElse({a} > {lo}, {a}, {hi})"
                et = attrs[a]
            items.append([expr, "calc"])
            schema.append(("calc", et))
        if not items:
            items.append(["sym", "sym"])
            schema.append(("sym", "string"))
        return items, schema, agg_aliases

    # ------------------------------------------------------------- events

    def _events(self, rng: random.Random,
                streams: List[StreamSpec]) -> List[List]:
        events: List[List] = []
        ts = 1_000_000
        for _ in range(self.events_per_case):
            s = rng.choice(streams)
            ts += rng.randint(1, 40)
            row = []
            for name, t in s.attrs:
                if name == "ts":
                    row.append(ts)
                elif t == "string":
                    row.append(rng.choice(_SYMS))
                elif t == "bool":
                    row.append(rng.random() < 0.5)
                elif t in ("float", "double"):
                    # multiples of 0.25: exactly representable, so sums
                    # stay exact and cross-strategy diffs are noise-free
                    row.append(rng.randint(0, 400) * 0.25)
                else:
                    row.append(rng.randint(0, 50))
            events.append([s.name, ts, row])
        return events


class _TypedContext:
    """The generator's stream-typing environment: input schemas plus the
    derived schemas of already-generated queries (chained pipelines)."""

    def __init__(self, streams: List[StreamSpec]):
        self.inputs = [s.name for s in streams]
        self._schemas: Dict[str, List[Tuple[str, str]]] = {
            s.name: list(s.attrs) for s in streams}
        self._derived: List[str] = []

    def schema(self, name: str) -> List[Tuple[str, str]]:
        return self._schemas[name]

    def define_derived(self, name: str, schema: List[Tuple[str, str]]):
        if name not in self._schemas:
            self._schemas[name] = schema
            self._derived.append(name)

    def pick_source(self, rng: random.Random) -> str:
        # mostly inputs; occasionally chain off a derived stream
        if self._derived and rng.random() < 0.2:
            return rng.choice(self._derived)
        return rng.choice(self.inputs)


def _numeric_attrs(attrs: Dict[str, str]) -> List[str]:
    return [n for n, t in attrs.items() if t in _NUMERIC and n != "ts"]


def _promote_int(a: str, b: str) -> str:
    return "long" if "long" in (a, b) else "int"
