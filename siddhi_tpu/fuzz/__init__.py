"""Semantic fuzzing: a typed SiddhiQL generator + cross-strategy
equivalence hunter with shrinking.

The engine runs one query under up to five execution strategies (legacy
/ fused fan-out / pipelined / device-routed / device joins) across shard
counts, pipeline depths, join partition counts and ingest pool sizes —
all of which must be **observationally interchangeable**: same app, same
input, bit-identical output in the identical order. The hand-written
quick checks cover ~6 shapes; this package generates thousands.

Modules:

- :mod:`siddhi_tpu.fuzz.determinism` — the deterministic-time window
  discipline every differential check must follow (the
  ``quick_join_check`` lesson, extracted);
- :mod:`siddhi_tpu.fuzz.schema` — typed stream/query/case specs that
  render to SiddhiQL and round-trip through JSON (the shrinker and the
  fixture format operate on these, never on raw query text);
- :mod:`siddhi_tpu.fuzz.generator` — the seeded typed generator:
  random schemas + a grammar of composable type-checked fragments that
  emits random-but-valid apps by construction, with eligibility
  expectations attached;
- :mod:`siddhi_tpu.fuzz.runner` — the strategy-matrix differential
  runner: enumerates every live strategy combination, runs the same
  deterministic feed through each, diffs emissions exactly (values AND
  order) against the all-legacy baseline, and audits the eligibility
  census for unexplained fallbacks;
- :mod:`siddhi_tpu.fuzz.shrink` — divergence reduction to a minimal
  repro (drop queries/clauses, shrink input, lower knobs) written as a
  self-contained fixture under ``tests/fixtures/fuzz/``.

Entry point: ``tools/fuzz_equivalence.py`` (seeded, budgeted, JSON
report); a fast seeded subset rides ``tools/quick_all.py`` as the
``fuzz`` check.
"""

from siddhi_tpu.fuzz.generator import CaseGenerator  # noqa: F401
from siddhi_tpu.fuzz.runner import (  # noqa: F401
    DiffReport,
    StrategyCombo,
    diff_outputs,
    run_case,
)
from siddhi_tpu.fuzz.schema import CaseSpec, QuerySpec, StreamSpec  # noqa: F401
from siddhi_tpu.fuzz.shrink import shrink_case  # noqa: F401
