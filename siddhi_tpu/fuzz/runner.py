"""Strategy-matrix differential runner: one case, every live strategy.

For one generated :class:`~siddhi_tpu.fuzz.schema.CaseSpec` this module
enumerates every *live* combination of the engine's execution-strategy
knobs — fan-out fusion on/off x pipeline depth {1,4} x device-routed
shard count {1,2,4} x join engine {legacy, device P=1, device P=8} x
ingest pool {0,2} — runs the same deterministic feed through each, and
diffs every output stream EXACTLY (values and order) against the
all-legacy baseline. The semantic-overlap contract ("On the Semantic
Overlap of Operators in Stream Processing Engines", PAPERS.md): the
variants are semantically-overlapping programs whose outputs must be
interchangeable, bit for bit.

Axis liveness: an axis whose knob cannot affect this case is collapsed
to its baseline value instead of multiplying the matrix — shard count
only matters when some query is route-eligible, the join axis only when
the app joins, fusion only when a junction has two-plus single-stream
subscribers (or a device join side can fuse). Collapsed axes and any
coverage-capped combos are REPORTED (``MatrixPlan.dropped``), never
silently skipped.

Eligibility census: each run also audits the app's build-time
``eligibility_census`` (core/eligibility.py) — a reason without a
stable code (``UNKNOWN``) or a census code that contradicts the
generator's declared expectation is an *unexplained eligibility
fallback*: the strategy silently fell back to a legacy path for a
reason no one declared. Those are findings even when outputs match.

Planted-divergence self-test: with ``SIDDHI_TPU_FUZZ_PLANT=1`` (or
``plant=True``) the runner deliberately skews the recorded output of
every pipelined (depth > 1) variant by duplicating its last emitted
row — at the collection layer, not in the engine — proving the differ
catches a real ordering/content skew and the shrinker converges, the
fuzzer's own regression test.
"""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.core.eligibility import (
    SURFACE_JOIN_ENGINE,
    SURFACE_JOIN_PIPELINE,
    SURFACE_ROUTE,
    ReasonCode,
)
from siddhi_tpu.core.util.knobs import env_knob
from siddhi_tpu.fuzz.schema import CaseSpec, np_dtype

_CHUNK_ROWS = 24          # max rows per send_columns batch
_ROWS_PER_SHARD = 512     # routed exchange per-shard receive quota


def plant_enabled() -> bool:
    """The planted-divergence env flag (typed read, graftlint R2)."""
    return bool(env_knob("SIDDHI_TPU_FUZZ_PLANT", "bool", False))


@dataclass(frozen=True)
class StrategyCombo:
    """One point of the strategy matrix (baseline = all defaults)."""

    fuse: bool = False
    depth: int = 1
    shards: int = 1
    join_engine: str = "legacy"
    join_partitions: int = 1
    pool: int = 0
    autopilot: bool = False

    def label(self) -> str:
        lbl = (f"fuse={int(self.fuse)},depth={self.depth},"
               f"shards={self.shards},join={self.join_engine}"
               f"/{self.join_partitions},pool={self.pool}")
        return lbl + ",ap" if self.autopilot else lbl

    def config(self) -> Dict[str, str]:
        cfg = {
            "siddhi_tpu.fuse_fanout": "true" if self.fuse else "false",
            "siddhi_tpu.pipeline_depth": str(self.depth),
            "siddhi_tpu.join_engine": self.join_engine,
            "siddhi_tpu.join_partitions": str(self.join_partitions),
            "siddhi_tpu.ingest_pool": str(self.pool),
            # small sub-batches so the fuzzer's modest chunks still
            # split across pool workers (>= 2 sub-batch eligibility)
            "siddhi_tpu.ingest_split": "8",
        }
        if self.autopilot:
            # deliberately aggressive cadence: many live actuations per
            # case, every one of which must keep bit-identity with the
            # all-legacy baseline
            cfg.update({
                "siddhi_tpu.autopilot": "on",
                "siddhi_tpu.autopilot_interval_s": "0.05",
                "siddhi_tpu.autopilot_cooldown_s": "0.2",
            })
        return cfg


BASELINE = StrategyCombo()


@dataclass
class MatrixPlan:
    """The enumerated matrix for one case + what was collapsed/capped."""

    combos: List[StrategyCombo]
    collapsed_axes: List[str]
    dropped: int = 0                 # combos removed by the coverage cap


@dataclass
class DiffReport:
    """First observed divergence between baseline and one variant."""

    stream: str
    index: int                       # first diverging row (-1 = lengths)
    baseline_row: Optional[List]
    variant_row: Optional[List]
    baseline_len: int = 0
    variant_len: int = 0
    kind: str = "rows"               # 'rows' | 'error'
    detail: str = ""

    def summary(self) -> str:
        if self.kind == "error":
            return f"{self.stream}: variant run failed: {self.detail}"
        return (f"{self.stream}[{self.index}]: baseline="
                f"{self.baseline_row} variant={self.variant_row} "
                f"(lengths {self.baseline_len} vs {self.variant_len})")


@dataclass
class CaseResult:
    """Outcome of one case across the matrix."""

    combos_run: List[str] = field(default_factory=list)
    pairs_diffed: int = 0
    divergences: List[Tuple[StrategyCombo, DiffReport]] = field(
        default_factory=list)
    census_findings: List[str] = field(default_factory=list)
    census: Dict[str, List[Tuple[str, str, str]]] = field(
        default_factory=dict)
    # the first device-join-mode run's census (the join surfaces read
    # DISABLED under the legacy baseline; reports want the device view)
    census_device: Optional[Dict] = None
    plan: Optional[MatrixPlan] = None


# ------------------------------------------------------------- matrix

def enumerate_matrix(case: CaseSpec, max_combos: Optional[int] = None,
                     max_shards: int = 4,
                     autopilot: bool = False) -> MatrixPlan:
    """Every live strategy combination for this case (baseline first).

    With ``autopilot=True`` the matrix becomes the autopilot axis: the
    all-legacy baseline plus an autopilot-ON twin of every enumerated
    combo (including the baseline itself) — the closed-loop controller
    actuating live knobs mid-feed must stay bit-identical to the
    untouched baseline run."""
    has_join = any(q.kind == "join" for q in case.queries)
    route_live = any(q.expect.get(SURFACE_ROUTE) == ReasonCode.ELIGIBLE.value
                     for q in case.queries)
    src_counts: Dict[str, int] = {}
    for q in case.queries:
        if q.kind == "single" and not q.partition_key:
            src_counts[q.from_stream] = src_counts.get(q.from_stream, 0) + 1
    fuse_live = has_join or any(v >= 2 for v in src_counts.values())

    collapsed = []
    fuse_axis = [False, True] if fuse_live else [False]
    if not fuse_live:
        collapsed.append("fuse (no junction with >= 2 fusable subscribers)")
    depth_axis = [1, 4]
    shard_axis = [1, 2, 4] if route_live else [1]
    shard_axis = [s for s in shard_axis if s <= max_shards]
    if not route_live:
        collapsed.append("shards (no route-eligible query)")
    join_axis = [("legacy", 1)]
    if has_join:
        join_axis += [("device", 1), ("device", 8)]
    else:
        collapsed.append("join (no join query)")
    pool_axis = [0, 2]

    combos = []
    for fuse, depth, shards, (je, jp), pool in itertools.product(
            fuse_axis, depth_axis, shard_axis, join_axis, pool_axis):
        combos.append(StrategyCombo(fuse=fuse, depth=depth, shards=shards,
                                    join_engine=je, join_partitions=jp,
                                    pool=pool))
    combos = [c for c in combos if c != BASELINE]
    dropped = 0
    if max_combos is not None and len(combos) > max_combos:
        # coverage-preserving deterministic sample: keep at least one
        # combo per (axis, value), fill the rest by seeded shuffle
        rng = random.Random(case.seed ^ len(case.events))
        keep: List[StrategyCombo] = []
        remaining = list(combos)
        rng.shuffle(remaining)

        def covers(c: StrategyCombo):
            return {("fuse", c.fuse), ("depth", c.depth),
                    ("shards", c.shards),
                    ("join", (c.join_engine, c.join_partitions)),
                    ("pool", c.pool)}

        needed = set()
        for c in combos:
            needed |= covers(c)
        covered: set = set()
        for c in remaining:
            if len(keep) >= max_combos and needed <= covered:
                break
            if not (covers(c) <= covered) or len(keep) < max_combos:
                keep.append(c)
                covered |= covers(c)
        dropped = len(combos) - len(keep)
        combos = keep
    if autopilot:
        from dataclasses import replace

        combos = [replace(c, autopilot=True)
                  for c in [BASELINE] + combos]
    return MatrixPlan(combos=[BASELINE] + combos, collapsed_axes=collapsed,
                      dropped=dropped)


# --------------------------------------------------------------- running

class _Collector:
    __slots__ = ("rows",)

    def __init__(self):
        self.rows: List[Tuple] = []

    def receive(self, events):
        self.rows.extend((e.timestamp, tuple(e.data)) for e in events)


def _chunked_feed(case: CaseSpec):
    """Group the global event sequence into runs of consecutive
    same-stream events (capped), preserving cross-stream order."""
    chunks: List[Tuple[str, List[List]]] = []
    for stream, ts, row in case.events:
        if chunks and chunks[-1][0] == stream \
                and len(chunks[-1][1]) < _CHUNK_ROWS:
            chunks[-1][1].append([ts, row])
        else:
            chunks.append((stream, [[ts, row]]))
    return chunks


def run_combo(case: CaseSpec, combo: StrategyCombo,
              plant: bool = False) -> Tuple[Dict[str, List[Tuple]],
                                            Dict, List[str]]:
    """Run the case's feed under one strategy combo. Returns
    ``(outputs, census, install_errors)``."""
    from siddhi_tpu.core.stream.output.stream_callback import StreamCallback
    from siddhi_tpu.core.manager import SiddhiManager
    from siddhi_tpu.core.util.config import InMemoryConfigManager

    class _CB(StreamCallback):
        def __init__(self, sink: _Collector):
            super().__init__()
            self._sink = sink

        def receive(self, events):
            self._sink.receive(events)

    m = SiddhiManager()
    install_errors: List[str] = []
    try:
        m.set_config_manager(InMemoryConfigManager(combo.config()))
        rt = m.create_siddhi_app_runtime(case.app_text())
        sinks = {s: _Collector() for s in case.out_streams()}
        for s, c in sinks.items():
            rt.add_callback(s, _CB(c))
        rt.start()
        census = dict(rt.eligibility_census)
        if combo.shards > 1:
            from siddhi_tpu.parallel.mesh import (
                device_route_query_step, make_mesh, route_ineligibility)

            for q in rt.query_runtimes.values():
                if route_ineligibility(q) is None:
                    try:
                        device_route_query_step(
                            q, make_mesh(combo.shards),
                            rows_per_shard=_ROWS_PER_SHARD)
                    except Exception as e:   # install failure = finding
                        install_errors.append(
                            f"device_route_query_step({q.name}, "
                            f"n={combo.shards}) failed: {e}")
        handlers = {s.name: rt.get_input_handler(s.name)
                    for s in case.streams}
        for stream, rows in _chunked_feed(case):
            spec = case.stream(stream)
            ts = np.array([r[0] for r in rows], dtype=np.int64)
            data = {}
            for j, (attr, atype) in enumerate(spec.attrs):
                vals = [r[1][j] for r in rows]
                data[attr] = np.array(vals, dtype=np_dtype(atype))
            handlers[stream].send_columns(data, timestamps=ts)
        outputs = {s: list(c.rows) for s, c in sinks.items()}
    finally:
        m.shutdown()
    if plant and combo.depth > 1:
        # the planted skew: duplicate the last emitted row of the first
        # non-empty stream — injected at the COLLECTION layer so the
        # engine stays untouched while differ + shrinker prove they
        # catch a real content/order divergence
        for s in case.out_streams():
            if outputs.get(s):
                outputs[s] = outputs[s] + [outputs[s][-1]]
                break
    return outputs, census, install_errors


def run_cluster_case(case: CaseSpec, cluster, name: str
                     ) -> Dict[str, List[Tuple]]:
    """Run the case's feed through a live 2-worker ``ClusterRuntime``
    (cluster/router.py) and return outputs shaped like ``run_combo``'s.

    Placement is PINNED (no partition keys): the whole app lands on
    ``crc32(name) % n`` — exact for ANY generated app, because the one
    owning worker receives the IDENTICAL ``send_columns`` sequence the
    in-process baseline makes (same ``_chunked_feed`` chunks), so even
    batch-association-sensitive float accumulations must match bit for
    bit after the wire round-trip and the ordered egress re-merge."""
    cluster.deploy(case.app_text(), name=name,
                   sinks=case.out_streams())
    for stream, rows in _chunked_feed(case):
        spec = case.stream(stream)
        ts = np.array([r[0] for r in rows], dtype=np.int64)
        data = {}
        for j, (attr, atype) in enumerate(spec.attrs):
            data[attr] = np.array([r[1][j] for r in rows],
                                  dtype=np_dtype(atype))
        cluster.send_columns(name, stream, data, timestamps=ts)
    if not cluster.quiesce(120):
        raise RuntimeError(f"cluster egress never quiesced for {name}")
    return {s: [(ts_, tuple(vals)) for ts_, vals in
                cluster.egress.stream_rows(name, s)]
            for s in case.out_streams()}


def diff_outputs(base: Dict[str, List[Tuple]],
                 variant: Dict[str, List[Tuple]]) -> Optional[DiffReport]:
    """Exact, order-sensitive diff. Returns the FIRST divergence."""
    for stream in base:
        b, v = base[stream], variant.get(stream, [])
        n = min(len(b), len(v))
        for i in range(n):
            if not _rows_equal(b[i], v[i]):
                return DiffReport(stream=stream, index=i,
                                  baseline_row=_jsonable(b[i]),
                                  variant_row=_jsonable(v[i]),
                                  baseline_len=len(b), variant_len=len(v))
        if len(b) != len(v):
            i = n
            return DiffReport(
                stream=stream, index=i,
                baseline_row=_jsonable(b[i]) if i < len(b) else None,
                variant_row=_jsonable(v[i]) if i < len(v) else None,
                baseline_len=len(b), variant_len=len(v))
    return None


def _rows_equal(a: Tuple, b: Tuple) -> bool:
    if a[0] != b[0] or len(a[1]) != len(b[1]):
        return False
    for x, y in zip(a[1], b[1]):
        if isinstance(x, float) and isinstance(y, float):
            # exact bit comparison on purpose (NaN == NaN holds): the
            # strategies promise BIT-identity, not approximate equality
            if np.isnan(x) and np.isnan(y):
                continue
            if x != y:
                return False
        elif x != y:
            return False
    return True


def _jsonable(row: Optional[Tuple]) -> Optional[List]:
    if row is None:
        return None
    ts, data = row
    return [int(ts), [v.item() if isinstance(v, np.generic)
                      else v for v in data]]


# ---------------------------------------------------------------- census

def audit_census(case: CaseSpec, census: Dict, combo: StrategyCombo,
                 install_errors: List[str]) -> List[str]:
    """Unexplained-fallback audit of one run's build-time census."""
    findings = list(install_errors)
    for qname, rows in census.items():
        for surface, code, detail in rows:
            cval = code.value if isinstance(code, ReasonCode) else str(code)
            if cval == ReasonCode.UNKNOWN.value:
                findings.append(
                    f"{qname}/{surface}: reason without a stable code "
                    f"(free text: {detail!r}) — declare it in "
                    f"core/eligibility.py")
    for q in case.queries:
        rows = census.get(q.name)
        if rows is None:
            # partitioned queries may register under decorated names;
            # expectation auditing only covers exact-name runtimes
            continue
        by_surface: Dict[str, List[str]] = {}
        for surface, code, _detail in rows:
            cval = code.value if isinstance(code, ReasonCode) else str(code)
            by_surface.setdefault(surface, []).append(cval)
        for surface, expected in q.expect.items():
            if surface in (SURFACE_JOIN_ENGINE, SURFACE_JOIN_PIPELINE) \
                    and combo.join_engine != "device":
                continue  # legacy mode rewrites these to DISABLED
            got = by_surface.get(surface)
            if got is None:
                continue
            if expected not in got:
                findings.append(
                    f"{q.name}/{surface}: generator expected "
                    f"{expected}, engine classified {got} — silent "
                    f"strategy fallback or stale expectation")
    return findings


# ------------------------------------------------------------- case loop

def run_case(case: CaseSpec, max_combos: Optional[int] = None,
             max_shards: int = 4, plant: Optional[bool] = None,
             stop_on_divergence: bool = False,
             deadline: Optional[float] = None,
             autopilot: bool = False) -> CaseResult:
    """Run the whole matrix for one case and diff every variant against
    the baseline. ``deadline`` (``time.monotonic()`` value) aborts the
    REMAINING combos cleanly once passed — truncation is visible as a
    shorter ``combos_run`` than the plan, never a hang past the
    caller's budget."""
    import time as _time

    if plant is None:
        plant = plant_enabled()
    plan = enumerate_matrix(case, max_combos=max_combos,
                            max_shards=max_shards, autopilot=autopilot)
    result = CaseResult(plan=plan)
    base_out, base_census, base_errs = run_combo(
        case, plan.combos[0], plant=plant)
    result.combos_run.append(plan.combos[0].label())
    result.census = base_census
    result.census_findings.extend(
        audit_census(case, base_census, plan.combos[0], base_errs))
    for combo in plan.combos[1:]:
        if deadline is not None and _time.monotonic() > deadline:
            break
        try:
            out, census, errs = run_combo(case, combo, plant=plant)
        except Exception as e:
            result.combos_run.append(combo.label())
            result.pairs_diffed += 1
            result.divergences.append((combo, DiffReport(
                stream="*", index=-1, baseline_row=None, variant_row=None,
                kind="error", detail=f"{type(e).__name__}: {e}")))
            if stop_on_divergence:
                return result
            continue
        result.combos_run.append(combo.label())
        result.pairs_diffed += 1
        if combo.join_engine == "device" and result.census_device is None:
            result.census_device = census
        for f in audit_census(case, census, combo, errs):
            if f not in result.census_findings:   # dedupe across combos
                result.census_findings.append(f)
        d = diff_outputs(base_out, out)
        if d is not None:
            result.divergences.append((combo, d))
            if stop_on_divergence:
                return result
    return result
