"""Typed case specs: streams, queries, events — the fuzzer's AST.

Everything downstream of the generator — the differential runner, the
shrinker, the on-disk fixture format — operates on these specs, never
on raw SiddhiQL text: the shrinker drops a clause by clearing a FIELD
and re-rendering, so every reduction step is well-formed by
construction (the "Stream Types" discipline: a spec that renders is a
spec that type-checked when it was built).

A :class:`CaseSpec` is fully self-contained and JSON-round-trippable:
app + deterministic input feed + eligibility expectations + the strategy
knobs that exposed a divergence. That is the fixture format under
``tests/fixtures/fuzz/`` (graftlint's known-bad-set pattern: a shrunk
divergence is committed as data the regression suite replays).
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

ATTR_TYPES = ("int", "long", "float", "double", "string", "bool")

_NP_DTYPES = {
    "int": np.int32, "long": np.int64, "float": np.float32,
    "double": np.float64, "bool": np.bool_, "string": object,
}


def np_dtype(attr_type: str):
    """numpy dtype for one SiddhiQL attribute type (string = object)."""
    return _NP_DTYPES[attr_type]


@dataclass
class StreamSpec:
    """One input stream definition: name + typed attributes."""

    name: str
    attrs: List[Tuple[str, str]]          # (attr_name, attr_type)

    def attr_type(self, attr: str) -> str:
        for n, t in self.attrs:
            if n == attr:
                return t
        raise KeyError(f"{self.name} has no attribute {attr!r}")

    def render(self) -> str:
        cols = ", ".join(f"{n} {t}" for n, t in self.attrs)
        return f"define stream {self.name} ({cols});"


@dataclass
class JoinSpec:
    """Stream-stream window join: sides, windows, key, optional extras."""

    left_stream: str
    right_stream: str
    left_window: Optional[List] = None    # [kind, param] or None
    right_window: Optional[List] = None
    key_attr: str = "sym"                 # equality attr (both sides)
    join_type: str = "join"               # 'join' | 'left outer join'
    residual: Optional[str] = None        # extra on-condition conjunct
    unidirectional: bool = False


@dataclass
class PatternSpec:
    """Two-stage NFA pattern: every e1=A[c1] -> e2=B[c2]."""

    first_stream: str
    second_stream: str
    first_cond: str
    second_cond: str
    every: bool = True


@dataclass
class QuerySpec:
    """One query: a typed composition of optional clauses."""

    name: str
    kind: str                             # 'single' | 'join' | 'pattern'
    insert_into: str
    from_stream: Optional[str] = None     # single-stream source
    window: Optional[List] = None         # [kind, param] or None
    ts_attr: Optional[str] = None         # externalTime expiry attribute
    filter: Optional[str] = None          # condition text (no brackets)
    select_items: List[List[str]] = field(default_factory=list)  # [expr, alias]
    group_by: Optional[List[str]] = None
    having: Optional[str] = None
    partition_key: Optional[str] = None   # wraps query in a partition
    join: Optional[JoinSpec] = None
    pattern: Optional[PatternSpec] = None
    # generator-declared eligibility expectations the runner must verify:
    # {surface: ReasonCode-value} — only surfaces the generator is SURE
    # about (a mismatch is a silent strategy fallback = a finding)
    expect: Dict[str, str] = field(default_factory=dict)

    # ------------------------------------------------------------ render

    def _window_clause(self, win: Optional[List],
                      ts_attr: Optional[str]) -> str:
        from siddhi_tpu.fuzz.determinism import window_clause

        if win is None:
            return ""
        return window_clause(win[0], win[1], ts_attr)

    def _select_clause(self) -> str:
        items = ", ".join(f"{expr} as {alias}" if alias and alias != expr
                          else expr
                          for expr, alias in self.select_items)
        sel = f"select {items}"
        if self.group_by:
            sel += f" group by {', '.join(self.group_by)}"
        if self.having:
            sel += f" having {self.having}"
        return sel

    def render(self) -> str:
        if self.kind == "join":
            j = self.join
            lw = self._window_clause(j.left_window, self.ts_attr)
            rw = self._window_clause(j.right_window, self.ts_attr)
            on = (f"{j.left_stream}.{j.key_attr} == "
                  f"{j.right_stream}.{j.key_attr}")
            if j.residual:
                on += f" and {j.residual}"
            uni = " unidirectional" if j.unidirectional else ""
            body = (f"@info(name='{self.name}') "
                    f"from {j.left_stream}{lw} {j.join_type} "
                    f"{j.right_stream}{rw}{uni} on {on} "
                    f"{self._select_clause()} insert into {self.insert_into};")
        elif self.kind == "pattern":
            p = self.pattern
            every = "every " if p.every else ""
            body = (f"@info(name='{self.name}') "
                    f"from {every}e1={p.first_stream}[{p.first_cond}] "
                    f"-> e2={p.second_stream}[{p.second_cond}] "
                    f"{self._select_clause()} insert into {self.insert_into};")
        else:
            flt = f"[{self.filter}]" if self.filter else ""
            win = self._window_clause(self.window, self.ts_attr)
            body = (f"@info(name='{self.name}') "
                    f"from {self.from_stream}{flt}{win} "
                    f"{self._select_clause()} insert into {self.insert_into};")
        if self.partition_key:
            src = self.from_stream if self.kind == "single" \
                else self.join.left_stream
            keys = f"{self.partition_key} of {src}"
            if self.kind == "join" \
                    and self.join.right_stream != src:
                keys += f", {self.partition_key} of {self.join.right_stream}"
            return f"partition with ({keys})\nbegin\n  {body}\nend;"
        return body

    # ------------------------------------------------------------ shape

    def clause_count(self) -> int:
        """How many grammar clauses this query is built from — the
        shrinker's minimality metric (a planted divergence must shrink
        to <= 3 clauses). The mandatory from/select skeleton counts 1."""
        n = 1
        for present in (self.window, self.filter, self.group_by,
                        self.having, self.partition_key):
            if present:
                n += 1
        if self.join is not None:
            n += 1                          # the join clause itself
            if self.join.left_window is not None:
                n += 1
            if self.join.right_window is not None:
                n += 1
            if self.join.residual:
                n += 1
        if self.pattern is not None:
            n += 1
        return n


@dataclass
class CaseSpec:
    """One self-contained fuzz case: schemas + queries + input feed."""

    seed: int
    streams: List[StreamSpec]
    queries: List[QuerySpec]
    # deterministic feed: (stream_name, timestamp, [values]) — one entry
    # per event, timestamps strictly increasing across the whole feed
    events: List[List] = field(default_factory=list)
    notes: str = ""

    def app_text(self) -> str:
        parts = [s.render() for s in self.streams]
        parts += [q.render() for q in self.queries]
        return "\n".join(parts) + "\n"

    def out_streams(self) -> List[str]:
        # dedupe, preserve order
        seen, out = set(), []
        for q in self.queries:
            if q.insert_into not in seen:
                seen.add(q.insert_into)
                out.append(q.insert_into)
        return out

    def stream(self, name: str) -> StreamSpec:
        for s in self.streams:
            if s.name == name:
                return s
        raise KeyError(name)

    def clause_count(self) -> int:
        return sum(q.clause_count() for q in self.queries)

    # ------------------------------------------------------- serialization

    def to_json(self) -> str:
        return json.dumps(asdict(self), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict) -> "CaseSpec":
        streams = [StreamSpec(s["name"], [tuple(a) for a in s["attrs"]])
                   for s in d["streams"]]
        queries = []
        for q in d["queries"]:
            join = JoinSpec(**q["join"]) if q.get("join") else None
            pattern = PatternSpec(**q["pattern"]) if q.get("pattern") else None
            q2 = {k: v for k, v in q.items() if k not in ("join", "pattern")}
            queries.append(QuerySpec(join=join, pattern=pattern, **q2))
        return cls(seed=d["seed"], streams=streams, queries=queries,
                   events=[list(e) for e in d["events"]],
                   notes=d.get("notes", ""))

    @classmethod
    def from_json(cls, text: str) -> "CaseSpec":
        return cls.from_dict(json.loads(text))
