"""Divergence shrinking: reduce a failing case to a minimal repro.

Given a case + the strategy combo that diverged from baseline, greedily
reduce while the divergence persists, in cost order:

1. **drop whole queries** (the unrelated members of a generated app);
2. **drop clauses** of the surviving queries — filter, having,
   group-by, join residual, window — and halve window parameters,
   always by clearing a FIELD of the typed spec and re-rendering, so
   every candidate is well-formed by construction;
3. **shrink the input feed** ddmin-style (drop halves, then quarters,
   ...), keeping cross-stream interleaving order;
4. **lower the strategy knobs** (shards 4 -> 2 -> 1, join partitions
   8 -> 1, depth 4 -> 2, pool 2 -> 0, fusion off, join engine legacy) so
   the repro names the SMALLEST configuration that still diverges.

Every candidate is verified by actually re-running baseline + variant
(``runner.run_combo``) — a reduction that makes the divergence vanish
(or turns it into a different failure kind) is reverted. The run budget
bounds total engine runs, so shrinking a pathological case degrades to
"less minimal", never to "hangs".

The minimal repro is written as a self-contained JSON fixture under
``tests/fixtures/fuzz/`` (graftlint's known-bad-set pattern): app text +
typed spec + feed + combo + the observed first divergence. Promote one
by committing it — ``tests/test_fuzz.py`` replays every committed
fixture through the differ and asserts the stored divergence is still
detected (or, for repaired bugs, moves to an ``expected_fixed`` list).
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import asdict, dataclass, field
from typing import List, Optional

from siddhi_tpu.fuzz.runner import (
    BASELINE,
    DiffReport,
    StrategyCombo,
    diff_outputs,
    run_combo,
)
from siddhi_tpu.fuzz.schema import CaseSpec


@dataclass
class ShrinkResult:
    case: CaseSpec
    combo: StrategyCombo
    diff: DiffReport
    runs_used: int = 0
    steps: List[str] = field(default_factory=list)
    fixture_path: Optional[str] = None


class _Budget:
    def __init__(self, max_runs: int):
        self.left = max_runs
        self.used = 0

    def take(self, n: int = 2) -> bool:
        if self.left < n:
            return False
        self.left -= n
        self.used += n
        return True


def _check(case: CaseSpec, combo: StrategyCombo, plant: Optional[bool],
           budget: _Budget) -> Optional[DiffReport]:
    """Does this candidate still diverge (rows-kind)? None = no/over
    budget/candidate failed to run at all."""
    if not budget.take():
        return None
    try:
        base, _c, _e = run_combo(case, BASELINE, plant=bool(plant))
        out, _c2, _e2 = run_combo(case, combo, plant=bool(plant))
    except Exception:
        return None                  # candidate broke the app: revert
    d = diff_outputs(base, out)
    if d is not None and d.kind == "rows":
        return d
    return None


def _consumed_streams(case: CaseSpec) -> set:
    used = set()
    for q in case.queries:
        if q.kind == "single":
            used.add(q.from_stream)
        elif q.kind == "join":
            used.add(q.join.left_stream)
            used.add(q.join.right_stream)
        elif q.kind == "pattern":
            used.add(q.pattern.first_stream)
            used.add(q.pattern.second_stream)
    return used


def _with_queries(case: CaseSpec, queries) -> CaseSpec:
    return CaseSpec(seed=case.seed, streams=case.streams,
                    queries=queries, events=case.events, notes=case.notes)


def _with_events(case: CaseSpec, events) -> CaseSpec:
    return CaseSpec(seed=case.seed, streams=case.streams,
                    queries=case.queries, events=events, notes=case.notes)


def shrink_case(case: CaseSpec, combo: StrategyCombo,
                diff: DiffReport, plant: Optional[bool] = None,
                max_runs: int = 120) -> ShrinkResult:
    """Greedy fixpoint reduction; see module docstring for the passes."""
    budget = _Budget(max_runs)
    res = ShrinkResult(case=case, combo=combo, diff=diff)

    # -- pass 1: drop whole queries ---------------------------------
    changed = True
    while changed and len(res.case.queries) > 1:
        changed = False
        for i in range(len(res.case.queries) - 1, -1, -1):
            cand_queries = res.case.queries[:i] + res.case.queries[i + 1:]
            dropped = res.case.queries[i]
            cand = _with_queries(res.case, cand_queries)
            # keep producers of still-consumed derived streams
            if dropped.insert_into in _consumed_streams(cand):
                continue
            d = _check(cand, res.combo, plant, budget)
            if d is not None:
                res.case, res.diff, changed = cand, d, True
                res.steps.append(f"dropped query {dropped.name}")

    # -- pass 1.5: drop streams no surviving query reads ------------
    used = _consumed_streams(res.case)
    keep_streams = [s for s in res.case.streams if s.name in used]
    if len(keep_streams) < len(res.case.streams):
        cand = CaseSpec(
            seed=res.case.seed, streams=keep_streams,
            queries=res.case.queries,
            events=[e for e in res.case.events
                    if e[0] in {s.name for s in keep_streams}],
            notes=res.case.notes)
        n_dropped = len(res.case.streams) - len(keep_streams)
        d = _check(cand, res.combo, plant, budget)
        if d is not None:
            res.case, res.diff = cand, d
            res.steps.append(f"dropped {n_dropped} unused streams")

    # -- pass 2: drop clauses / shrink windows ----------------------
    changed = True
    while changed:
        changed = False
        for qi, q in enumerate(res.case.queries):
            for cand_q, step in _clause_candidates(q):
                cand = _with_queries(
                    res.case, res.case.queries[:qi] + [cand_q]
                    + res.case.queries[qi + 1:])
                d = _check(cand, res.combo, plant, budget)
                if d is not None:
                    res.case, res.diff, changed = cand, d, True
                    res.steps.append(f"{q.name}: {step}")
                    break
            if changed:
                break

    # -- pass 3: ddmin the feed -------------------------------------
    n_chunks = 2
    while n_chunks <= len(res.case.events):
        events = res.case.events
        size = max(1, len(events) // n_chunks)
        removed_any = False
        start = 0
        while start < len(res.case.events):
            events = res.case.events
            cand_events = events[:start] + events[start + size:]
            if not cand_events:
                break
            d = _check(_with_events(res.case, cand_events),
                       res.combo, plant, budget)
            if d is not None:
                res.case = _with_events(res.case, cand_events)
                res.diff = d
                res.steps.append(
                    f"removed events [{start}:{start + size}]")
                removed_any = True
            else:
                start += size
        if not removed_any:
            if size <= 1:
                break
            n_chunks *= 2
        if budget.left < 2:
            break

    # -- pass 4: lower the strategy knobs ---------------------------
    # the case is frozen from here on: run the baseline ONCE and diff
    # each lowered-knob candidate against the cached result (one engine
    # run per candidate instead of two)
    base_cached = None
    if budget.take(1):
        try:
            base_cached, _c, _e = run_combo(res.case, BASELINE,
                                            plant=bool(plant))
        except Exception:
            base_cached = None
    if base_cached is not None:
        # fixpoint: re-derive candidates from the CURRENT combo after
        # each acceptance — a later candidate built from the original
        # combo would silently revert earlier accepted lowerings
        progressed = True
        while progressed:
            progressed = False
            for lowered, step in _combo_candidates(res.combo):
                if not budget.take(1):
                    break
                try:
                    out, _c, _e = run_combo(res.case, lowered,
                                            plant=bool(plant))
                except Exception:
                    continue
                d = diff_outputs(base_cached, out)
                if d is not None and d.kind == "rows":
                    res.combo, res.diff = lowered, d
                    res.steps.append(f"combo: {step}")
                    progressed = True
                    break

    res.runs_used = budget.used
    return res


def _clause_candidates(q):
    """Single-clause reductions of one QuerySpec (typed: clear a field,
    never edit text). Every mutated candidate DROPS the generator's
    eligibility expectations — they described the original shape, and a
    stale expect dict in a committed fixture would make its replay
    report phantom census fallbacks."""
    import copy

    out = []

    def variant(step, **changes):
        c = copy.deepcopy(q)
        for k, v in changes.items():
            setattr(c, k, v)
        c.expect = {}
        out.append((c, step))

    if q.filter:
        variant("dropped filter", filter=None)
    if q.having:
        variant("dropped having", having=None)
    if q.group_by and not any("(" in e for e, _a in q.select_items):
        variant("dropped group by", group_by=None)
    if q.window and q.window[1] > 2:
        c = copy.deepcopy(q)
        c.window = [c.window[0], max(2, c.window[1] // 2)]
        c.expect = {}
        out.append((c, f"window param -> {c.window[1]}"))
    if q.join is not None:
        if q.join.residual:
            c = copy.deepcopy(q)
            c.join.residual = None
            c.expect = {}
            out.append((c, "dropped join residual"))
        for side in ("left_window", "right_window"):
            w = getattr(q.join, side)
            if w and w[1] > 2:
                c = copy.deepcopy(q)
                setattr(c.join, side, [w[0], max(2, w[1] // 2)])
                c.expect = {}
                out.append((c, f"{side} param -> {max(2, w[1] // 2)}"))
    if len(q.select_items) > 1:
        c = copy.deepcopy(q)
        c.select_items = c.select_items[:1]
        c.expect = {}
        out.append((c, "select -> first item"))
    return out


def _combo_candidates(combo: StrategyCombo):
    if combo.shards > 1:
        yield (StrategyCombo(**{**asdict(combo),
                                "shards": combo.shards // 2}),
               f"shards -> {combo.shards // 2}")
    if combo.join_partitions > 1:
        yield (StrategyCombo(**{**asdict(combo), "join_partitions": 1}),
               "join_partitions -> 1")
    if combo.depth > 2:
        yield (StrategyCombo(**{**asdict(combo), "depth": 2}),
               "depth -> 2")
    if combo.pool > 0:
        yield (StrategyCombo(**{**asdict(combo), "pool": 0}), "pool -> 0")
    if combo.fuse:
        yield (StrategyCombo(**{**asdict(combo), "fuse": False}),
               "fuse -> off")
    if combo.join_engine == "device":
        yield (StrategyCombo(**{**asdict(combo), "join_engine": "legacy",
                                "join_partitions": 1}),
               "join_engine -> legacy")


# ------------------------------------------------------------- fixtures

def fixture_dict(case: CaseSpec, combo: StrategyCombo,
                 diff: DiffReport) -> dict:
    return {
        "format": "siddhi-tpu-fuzz-divergence-v1",
        "app": case.app_text(),
        "case": asdict(case),
        "combo": asdict(combo),
        "baseline": asdict(BASELINE),
        "diff": asdict(diff),
        "clause_count": case.clause_count(),
    }


def write_fixture(case: CaseSpec, combo: StrategyCombo, diff: DiffReport,
                  directory: str) -> str:
    """Write the shrunk repro as a self-contained JSON fixture; the
    filename is content-addressed so re-finding the same bug is
    idempotent."""
    payload = fixture_dict(case, combo, diff)
    blob = json.dumps(payload, indent=2, sort_keys=True)
    digest = hashlib.sha1(
        (case.app_text() + combo.label()).encode()).hexdigest()[:10]
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(
        directory, f"divergence_seed{case.seed}_{digest}.json")
    with open(path, "w") as f:
        f.write(blob + "\n")
    return path
