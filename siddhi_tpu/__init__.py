"""siddhi_tpu — a TPU-native streaming & Complex Event Processing framework.

A from-scratch re-design (NOT a port) of the capabilities of the reference
Siddhi engine (/root/reference, Java): SiddhiQL compiles to a columnar,
batched dataflow whose hot path is a fused JAX/XLA step function per query,
with per-key state held in dense ``[num_keys, ...]`` device arrays instead of
per-key heap objects behind thread-locals.

Public API surface mirrors the reference's (``SiddhiManager``
-> ``SiddhiAppRuntime`` -> ``InputHandler`` / ``StreamCallback`` /
``QueryCallback``; reference: siddhi-core ``SiddhiManager.java:49``,
``SiddhiAppRuntime.java``, ``stream/input/InputHandler.java``).
"""

# The window/NFA hot path swaps ring-buffer slots in place (gather old
# value, scatter new one into the SAME donated [K*W] buffer). XLA:CPU's
# default copy-insertion cannot prove the gather-before-scatter ordering
# and materializes two full-buffer copies per column per step (O(K*W)
# bytes — 33x slower at the bench shape); region analysis proves it.
# CPU-only flag, inert on TPU. Must be set before backend init.
import os as _os
import sys as _sys


def _jax_backend_initialized() -> bool:
    """True when the embedding application already initialized a JAX
    backend before importing siddhi_tpu — XLA_FLAGS set below are then
    inert (XLA parsed them at backend init)."""
    xb = getattr(_sys.modules.get("jax._src.xla_bridge"), "__dict__", None)
    if xb is None:
        return False
    try:
        fn = xb.get("backends_are_initialized")
        if fn is not None:
            return bool(fn())
    except Exception:  # pragma: no cover — version-dependent introspection
        pass
    return bool(xb.get("_backends"))


_FLAG = "--xla_cpu_copy_insertion_use_region_analysis"
if _FLAG not in _os.environ.get("XLA_FLAGS", ""):
    # name-only check: an explicit user setting (either value) wins
    _os.environ["XLA_FLAGS"] = (
        _os.environ.get("XLA_FLAGS", "") + " " + _FLAG + "=true").strip()
    if _jax_backend_initialized():
        # the mutation came too late: the CPU backend already parsed its
        # flags, so the ring-swap fix (two full-buffer copies per window
        # column per step, 33x at the bench shape — see the comment
        # above) is silently OFF. Warn once so the regression cannot be
        # reintroduced unnoticed; see README "Observability" for the fix
        # (import siddhi_tpu before any jax computation, or set the flag
        # in the environment).
        import warnings as _warnings

        _warnings.warn(
            "siddhi_tpu: a JAX backend was initialized before importing "
            f"siddhi_tpu, so '{_FLAG}=true' cannot take effect — the "
            "XLA:CPU window/NFA ring-swap path will run up to 33x slower. "
            "Import siddhi_tpu before running any jax computation, or set "
            f"XLA_FLAGS={_FLAG}=true in the environment.",
            RuntimeWarning, stacklevel=2)

# Millisecond epoch timestamps need int64; enable x64 before any jax use.
import jax

jax.config.update("jax_enable_x64", True)

# SIDDHI_TPU_SANITIZE=1 arms the runtime sanitizers (transfer-guard
# host-pull detection, post-warmup recompile watchdog, lock-order
# assertions — siddhi_tpu/analysis/sanitize.py). Config-only: the
# backend is NOT initialized here (that being the R1 bug class).
from siddhi_tpu.analysis import sanitize as _sanitize

if _sanitize.enabled():
    _sanitize.enable()

__version__ = "0.1.0"

__all__ = [
    "SiddhiManager",
    "StreamCallback",
    "QueryCallback",
    "Event",
    "__version__",
]


def __getattr__(name):
    # Lazy to keep `import siddhi_tpu.compiler` light and cycle-free.
    if name == "SiddhiManager":
        from siddhi_tpu.core.manager import SiddhiManager
        return SiddhiManager
    if name == "StreamCallback":
        from siddhi_tpu.core.stream.output.stream_callback import StreamCallback
        return StreamCallback
    if name == "QueryCallback":
        from siddhi_tpu.core.query.callback import QueryCallback
        return QueryCallback
    if name == "Event":
        from siddhi_tpu.core.event import Event
        return Event
    raise AttributeError(name)
