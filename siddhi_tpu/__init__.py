"""siddhi_tpu — a TPU-native streaming & Complex Event Processing framework.

A from-scratch re-design (NOT a port) of the capabilities of the reference
Siddhi engine (/root/reference, Java): SiddhiQL compiles to a columnar,
batched dataflow whose hot path is a fused JAX/XLA step function per query,
with per-key state held in dense ``[num_keys, ...]`` device arrays instead of
per-key heap objects behind thread-locals.

Public API surface mirrors the reference's (``SiddhiManager``
-> ``SiddhiAppRuntime`` -> ``InputHandler`` / ``StreamCallback`` /
``QueryCallback``; reference: siddhi-core ``SiddhiManager.java:49``,
``SiddhiAppRuntime.java``, ``stream/input/InputHandler.java``).
"""

# The window/NFA hot path swaps ring-buffer slots in place (gather old
# value, scatter new one into the SAME donated [K*W] buffer). XLA:CPU's
# default copy-insertion cannot prove the gather-before-scatter ordering
# and materializes two full-buffer copies per column per step (O(K*W)
# bytes — 33x slower at the bench shape); region analysis proves it.
# CPU-only flag, inert on TPU. Must be set before backend init.
import os as _os

_FLAG = "--xla_cpu_copy_insertion_use_region_analysis"
if _FLAG not in _os.environ.get("XLA_FLAGS", ""):
    # name-only check: an explicit user setting (either value) wins
    _os.environ["XLA_FLAGS"] = (
        _os.environ.get("XLA_FLAGS", "") + " " + _FLAG + "=true").strip()

# Millisecond epoch timestamps need int64; enable x64 before any jax use.
import jax

jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

__all__ = [
    "SiddhiManager",
    "StreamCallback",
    "QueryCallback",
    "Event",
    "__version__",
]


def __getattr__(name):
    # Lazy to keep `import siddhi_tpu.compiler` light and cycle-free.
    if name == "SiddhiManager":
        from siddhi_tpu.core.manager import SiddhiManager
        return SiddhiManager
    if name == "StreamCallback":
        from siddhi_tpu.core.stream.output.stream_callback import StreamCallback
        return StreamCallback
    if name == "QueryCallback":
        from siddhi_tpu.core.query.callback import QueryCallback
        return QueryCallback
    if name == "Event":
        from siddhi_tpu.core.event import Event
        return Event
    raise AttributeError(name)
