// Native string-dictionary encoder: the hot half of columnar string
// ingest. The reference pays a per-event string cost at every group-by
// (GroupByKeyGenerator.java:37 string keys) and on every attribute read;
// the TPU build dictionary-encodes whole string columns at the ingest
// edge instead (SURVEY §7 decision 1) — this file makes that edge native:
// one C++ pass over a numpy object array, one open-addressing hash probe
// per string, no Python per-row work. Python stays authoritative for the
// id space: NEW strings come back as misses, Python allocates their ids
// (StringDictionary.encode) and inserts them here, so snapshots/restores
// only ever deal with the Python-side list.
//
// Compiled against the CPython C API (PyUnicode readers); loaded with
// ctypes.PyDLL so calls run under the GIL, which the PyObject* accesses
// require. No pybind11 in this image (see native/__init__.py).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

inline uint64_t fnv1a(const char* s, size_t n) {
    uint64_t h = 1469598103934665603ull;
    for (size_t i = 0; i < n; ++i) {
        h ^= (uint8_t)s[i];
        h *= 1099511628211ull;
    }
    return h;
}

struct Entry {
    uint64_t hash;
    int64_t id;       // -1 == empty
    uint64_t off;     // into arena
    uint32_t len;
};

// Open-addressing (linear probe) string -> id map with an append-only
// byte arena. ~3x faster probes than std::unordered_map<std::string,..>
// at 65k-row batches of short keys (no per-node allocation, no bucket
// pointer chase).
struct StrDict {
    std::vector<Entry> table;
    std::string arena;
    size_t count = 0;

    StrDict() : table(1 << 12) { clear(); }

    void clear() {
        for (auto& e : table) e.id = -1;
        arena.clear();
        count = 0;
    }

    void grow() {
        std::vector<Entry> old;
        old.swap(table);
        table.resize(old.size() * 2);
        for (auto& e : table) e.id = -1;
        size_t mask = table.size() - 1;
        for (const auto& e : old) {
            if (e.id < 0) continue;
            size_t i = e.hash & mask;
            while (table[i].id >= 0) i = (i + 1) & mask;
            table[i] = e;
        }
    }

    // -1 == absent
    inline int64_t find(const char* s, size_t n, uint64_t h) const {
        size_t mask = table.size() - 1;
        size_t i = h & mask;
        while (true) {
            const Entry& e = table[i];
            if (e.id < 0) return -1;
            if (e.hash == h && e.len == n &&
                std::memcmp(arena.data() + e.off, s, n) == 0)
                return e.id;
            i = (i + 1) & mask;
        }
    }

    void insert(const char* s, size_t n, int64_t id) {
        uint64_t h = fnv1a(s, n);
        if (find(s, n, h) >= 0) return;
        if ((count + 1) * 4 >= table.size() * 3) grow();  // load < 0.75
        size_t mask = table.size() - 1;
        size_t i = h & mask;
        while (table[i].id >= 0) i = (i + 1) & mask;
        table[i] = Entry{h, id, (uint64_t)arena.size(), (uint32_t)n};
        arena.append(s, n);
        ++count;
    }
};

}  // namespace

extern "C" {

StrDict* strdict_new() { return new StrDict(); }
void strdict_free(StrDict* d) { delete d; }
void strdict_clear(StrDict* d) { d->clear(); }
int64_t strdict_count(StrDict* d) { return (int64_t)d->count; }

void strdict_insert(StrDict* d, const char* s, int64_t n, int64_t id) {
    d->insert(s, (size_t)n, id);
}

// Encode a numpy object array (items = its PyObject** data) into out.
// None -> null_id; known strings -> their id; NEW strings and non-str
// values -> miss_marker (Python resolves those, then strdict_insert's
// them). Returns the number of misses. Requires the GIL (load with
// ctypes.PyDLL).
int64_t strdict_encode(StrDict* d, PyObject** items, int64_t n,
                       int64_t* out, int64_t null_id, int64_t miss_marker) {
    int64_t misses = 0;
    // tiny inline cache: consecutive rows often repeat the same object
    // (np.take of a small symbol universe shares PyObject pointers)
    PyObject* last_obj = nullptr;
    int64_t last_id = 0;
    for (int64_t i = 0; i < n; ++i) {
        PyObject* o = items[i];
        if (o == last_obj) {
            out[i] = last_id;
            continue;
        }
        if (o == Py_None) {
            out[i] = null_id;
            last_obj = o;
            last_id = null_id;
            continue;
        }
        if (!PyUnicode_Check(o)) {
            out[i] = miss_marker;
            ++misses;
            last_obj = nullptr;
            continue;
        }
        Py_ssize_t len;
        const char* s = PyUnicode_AsUTF8AndSize(o, &len);
        if (s == nullptr) {
            PyErr_Clear();
            out[i] = miss_marker;
            ++misses;
            last_obj = nullptr;
            continue;
        }
        int64_t id = d->find(s, (size_t)len, fnv1a(s, (size_t)len));
        if (id < 0) {
            out[i] = miss_marker;
            ++misses;
            last_obj = nullptr;
        } else {
            out[i] = id;
            last_obj = o;
            last_id = id;
        }
    }
    return misses;
}

}  // extern "C"
