// Native columnar ingest: CSV byte buffers -> typed column arrays.
//
// The runtime-side analog of the reference's event construction path
// (transport bytes -> Event objects -> per-attribute conversion): here a
// whole buffer parses in one C++ pass directly into the columnar layout
// the device step consumes (int64/double/int32-dict columns + null
// masks), with string attributes dictionary-encoded against a native
// hash map. Python touches strings only once per NEW unique (to sync the
// app's StringDictionary), never per row.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

int hex_val(char c) {
    if (c >= '0' && c <= '9') return c - '0';
    if (c >= 'a' && c <= 'f') return c - 'a' + 10;
    if (c >= 'A' && c <= 'F') return c - 'A' + 10;
    return -1;
}

void append_utf8(std::string& s, uint32_t cp) {
    if (cp < 0x80) {
        s.push_back((char)cp);
    } else if (cp < 0x800) {
        s.push_back((char)(0xC0 | (cp >> 6)));
        s.push_back((char)(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
        s.push_back((char)(0xE0 | (cp >> 12)));
        s.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
        s.push_back((char)(0x80 | (cp & 0x3F)));
    } else {
        s.push_back((char)(0xF0 | (cp >> 18)));
        s.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
        s.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
        s.push_back((char)(0x80 | (cp & 0x3F)));
    }
}

struct Loader {
    std::unordered_map<std::string, int64_t> dict;
    std::vector<std::string> strings;   // id -> string

    int64_t encode(const char* s, size_t n) {
        std::string key(s, n);
        auto it = dict.find(key);
        if (it != dict.end()) return it->second;
        int64_t id = (int64_t)strings.size();
        dict.emplace(std::move(key), id);
        strings.emplace_back(s, n);
        return id;
    }
};

}  // namespace

extern "C" {

// column type codes (mirror siddhi_tpu.ops.types)
enum { COL_LONG = 0, COL_DOUBLE = 1, COL_STRING = 2, COL_BOOL = 3 };

Loader* loader_new() { return new Loader(); }
void loader_free(Loader* l) { delete l; }

int64_t loader_dict_size(Loader* l) { return (int64_t)l->strings.size(); }

// copy string `id` into out (cap bytes incl. NUL); returns its length
int64_t loader_dict_get(Loader* l, int64_t id, char* out, int64_t cap) {
    if (id < 0 || id >= (int64_t)l->strings.size()) return -1;
    const std::string& s = l->strings[(size_t)id];
    int64_t n = (int64_t)s.size();
    if (n + 1 <= cap) {
        std::memcpy(out, s.data(), (size_t)n);
        out[n] = '\0';
    }
    return n;
}

// Parse up to max_rows CSV lines from buf[0:len).
//   types[c]   : column type code
//   out_cols[c]: int64* (LONG), double* (DOUBLE), int64* dict ids (STRING),
//                uint8* (BOOL) — caller-allocated, max_rows each
//   out_masks[c]: uint8* null mask (1 = null), max_rows each
// Empty fields are null. Returns rows parsed (< 0 on error).
int64_t loader_parse_csv(Loader* l, const char* buf, int64_t len,
                         const int32_t* types, int32_t ncols,
                         void** out_cols, uint8_t** out_masks,
                         int64_t max_rows) {
    int64_t row = 0;
    int64_t i = 0;
    while (i < len && row < max_rows) {
        for (int32_t c = 0; c < ncols; ++c) {
            int64_t start = i;
            while (i < len && buf[i] != ',' && buf[i] != '\n' && buf[i] != '\r')
                ++i;
            int64_t n = i - start;
            bool is_null = (n == 0);
            out_masks[c][row] = is_null ? 1 : 0;
            switch (types[c]) {
                case COL_LONG: {
                    int64_t* col = (int64_t*)out_cols[c];
                    col[row] = is_null ? 0 : strtoll(buf + start, nullptr, 10);
                    break;
                }
                case COL_DOUBLE: {
                    double* col = (double*)out_cols[c];
                    col[row] = is_null ? 0.0 : strtod(buf + start, nullptr);
                    break;
                }
                case COL_STRING: {
                    int64_t* col = (int64_t*)out_cols[c];
                    col[row] = is_null ? 0 : l->encode(buf + start, (size_t)n);
                    break;
                }
                case COL_BOOL: {
                    uint8_t* col = (uint8_t*)out_cols[c];
                    col[row] = (!is_null && (buf[start] == 't' || buf[start] == 'T' ||
                                             buf[start] == '1'))
                                   ? 1
                                   : 0;
                    break;
                }
                default:
                    return -1;
            }
            if (i < len && buf[i] == ',') ++i;   // field separator
        }
        // consume the line terminator(s)
        while (i < len && (buf[i] == '\r' || buf[i] == '\n')) {
            if (buf[i] == '\n') { ++i; break; }
            ++i;
        }
        ++row;
    }
    return row;
}


// JSON-lines: one flat object per line. Fields resolve by name against
// the stream definition; missing keys / JSON null -> null mask; unknown
// keys are skipped. String values handle \" \\ \/ \n \t \r escapes and
// \uXXXX (incl. surrogate pairs), encoded to UTF-8.
//   names: concatenated field names; name_lens[c] their lengths
// Returns rows parsed (< 0 on error).
int64_t loader_parse_jsonl(Loader* l, const char* buf, int64_t len,
                           const char* names, const int32_t* name_lens,
                           const int32_t* types, int32_t ncols,
                           void** out_cols, uint8_t** out_masks,
                           int64_t max_rows) {
    std::vector<std::pair<const char*, int32_t>> fields(ncols);
    {
        const char* p = names;
        for (int32_t c = 0; c < ncols; ++c) {
            fields[c] = {p, name_lens[c]};
            p += name_lens[c];
        }
    }
    std::string sval;
    int64_t row = 0, i = 0;
    while (i < len && row < max_rows) {
        // skip blank space before the object
        while (i < len && (buf[i] == ' ' || buf[i] == '\t' ||
                           buf[i] == '\r' || buf[i] == '\n'))
            ++i;
        if (i >= len) break;
        if (buf[i] != '{') return -1;
        ++i;
        for (int32_t c = 0; c < ncols; ++c) out_masks[c][row] = 1;
        bool done = false;
        while (!done) {
            while (i < len && (buf[i] == ' ' || buf[i] == '\t')) ++i;
            if (i < len && buf[i] == '}') { ++i; done = true; break; }
            if (i >= len || buf[i] != '"') return -1;
            ++i;
            int64_t kstart = i;
            while (i < len && buf[i] != '"') {
                if (buf[i] == '\\') ++i;
                ++i;
            }
            int64_t klen = i - kstart;
            if (i >= len) return -1;
            ++i;  // closing quote
            while (i < len && (buf[i] == ' ' || buf[i] == '\t')) ++i;
            if (i >= len || buf[i] != ':') return -1;
            ++i;
            while (i < len && (buf[i] == ' ' || buf[i] == '\t')) ++i;
            int32_t col = -1;
            for (int32_t c = 0; c < ncols; ++c)
                if (fields[c].second == klen &&
                    memcmp(fields[c].first, buf + kstart, (size_t)klen) == 0) {
                    col = c;
                    break;
                }
            bool is_null = false;
            sval.clear();
            bool have_str = false;
            int64_t vstart = i, vlen = 0;
            if (i < len && buf[i] == '"') {
                ++i;
                have_str = true;
                while (i < len && buf[i] != '"') {
                    char ch = buf[i];
                    if (ch == '\\' && i + 1 < len) {
                        ++i;
                        char e = buf[i];
                        if (e == 'u' && i + 4 < len) {
                            int h0 = hex_val(buf[i + 1]), h1 = hex_val(buf[i + 2]);
                            int h2 = hex_val(buf[i + 3]), h3 = hex_val(buf[i + 4]);
                            if (h0 < 0 || h1 < 0 || h2 < 0 || h3 < 0) return -1;
                            uint32_t cp = (uint32_t)((h0 << 12) | (h1 << 8) |
                                                     (h2 << 4) | h3);
                            i += 4;
                            if (cp >= 0xD800 && cp <= 0xDBFF && i + 6 < len &&
                                buf[i + 1] == '\\' && buf[i + 2] == 'u') {
                                int g0 = hex_val(buf[i + 3]), g1 = hex_val(buf[i + 4]);
                                int g2 = hex_val(buf[i + 5]), g3 = hex_val(buf[i + 6]);
                                if (g0 < 0 || g1 < 0 || g2 < 0 || g3 < 0) return -1;
                                uint32_t lo = (uint32_t)((g0 << 12) | (g1 << 8) |
                                                         (g2 << 4) | g3);
                                if (lo >= 0xDC00 && lo <= 0xDFFF) {
                                    cp = 0x10000 + ((cp - 0xD800) << 10) +
                                         (lo - 0xDC00);
                                    i += 6;
                                }
                            }
                            append_utf8(sval, cp);
                            ++i;
                            continue;
                        }
                        switch (e) {
                            case 'n': ch = '\n'; break;
                            case 't': ch = '\t'; break;
                            case 'r': ch = '\r'; break;
                            case 'b': ch = '\b'; break;
                            case 'f': ch = '\f'; break;
                            default: ch = e; break;   // " \\ /
                        }
                    }
                    sval.push_back(ch);
                    ++i;
                }
                if (i >= len) return -1;
                ++i;  // closing quote
            } else if (i < len && buf[i] == 'n') {
                is_null = true;
                while (i < len && buf[i] != ',' && buf[i] != '}' &&
                       buf[i] != '\n')
                    ++i;
                if (i >= len || buf[i] == '\n') return -1;  // missing '}'
            } else {
                vstart = i;
                while (i < len && buf[i] != ',' && buf[i] != '}' &&
                       buf[i] != '\n')
                    ++i;
                vlen = i - vstart;
                if (vlen == 0) is_null = true;
            }
            if (col >= 0) {
                out_masks[col][row] = is_null ? 1 : 0;
                const char* vp = have_str ? sval.data() : buf + vstart;
                size_t vn = have_str ? sval.size() : (size_t)vlen;
                switch (types[col]) {
                    case COL_LONG: {
                        int64_t* out = (int64_t*)out_cols[col];
                        out[row] = is_null ? 0 : strtoll(vp, nullptr, 10);
                        break;
                    }
                    case COL_DOUBLE: {
                        double* out = (double*)out_cols[col];
                        out[row] = is_null ? 0.0 : strtod(vp, nullptr);
                        break;
                    }
                    case COL_STRING: {
                        int64_t* out = (int64_t*)out_cols[col];
                        out[row] = is_null ? 0 : l->encode(vp, vn);
                        break;
                    }
                    case COL_BOOL: {
                        uint8_t* out = (uint8_t*)out_cols[col];
                        out[row] = (!is_null && vn > 0 &&
                                    (vp[0] == 't' || vp[0] == 'T' ||
                                     vp[0] == '1'))
                                       ? 1
                                       : 0;
                        break;
                    }
                    default:
                        return -1;
                }
            }
            while (i < len && (buf[i] == ' ' || buf[i] == '\t')) ++i;
            if (i < len && buf[i] == ',') { ++i; continue; }
            if (i < len && buf[i] == '}') { ++i; done = true; }
        }
        while (i < len && buf[i] != '\n') ++i;
        if (i < len) ++i;
        ++row;
    }
    return row;
}

}  // extern "C"
