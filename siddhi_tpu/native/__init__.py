"""Native (C++) runtime components, bound via ctypes.

The compute path is JAX/XLA; the host runtime around it uses native code
where the per-row work would otherwise be interpreted Python — here the
columnar ingest loader (``CsvLoader``): transport byte buffers parse in
one C++ pass into the typed column arrays ``InputHandler.send_columns``
consumes, with native dictionary encoding for string attributes (Python
syncs the app StringDictionary once per NEW unique string, never per
row).

The shared library builds on first use with the image's g++ and is cached
next to the source (no pip/pybind11 dependency).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.query_api.definitions import AttrType

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "csv_loader.cpp")
_SO = os.path.join(_HERE, "_csv_loader.so")
_LOCK = threading.Lock()
_LIB = None
_STRDICT_SRC = os.path.join(_HERE, "strdict.cpp")
_STRDICT_SO = os.path.join(_HERE, "_strdict.so")
_STRDICT_LIB = None
_STRDICT_FAILED = False

_TYPE_CODES = {
    AttrType.INT: 0, AttrType.LONG: 0,
    AttrType.FLOAT: 1, AttrType.DOUBLE: 1,
    AttrType.STRING: 2,
    AttrType.BOOL: 3,
}


def _lib():
    global _LIB
    with _LOCK:
        if _LIB is not None:
            return _LIB
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                 _SRC, "-o", _SO],
                check=True, capture_output=True)
        lib = ctypes.CDLL(_SO)
        lib.loader_new.restype = ctypes.c_void_p
        lib.loader_free.argtypes = [ctypes.c_void_p]
        lib.loader_dict_size.restype = ctypes.c_int64
        lib.loader_dict_size.argtypes = [ctypes.c_void_p]
        lib.loader_dict_get.restype = ctypes.c_int64
        lib.loader_dict_get.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64]
        lib.loader_parse_csv.restype = ctypes.c_int64
        lib.loader_parse_csv.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64,
        ]
        lib.loader_parse_jsonl.restype = ctypes.c_int64
        lib.loader_parse_jsonl.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64,
        ]
        _LIB = lib
        return lib


def strdict_lib():
    """The native string-dictionary encoder (strdict.cpp), or None when it
    can't build — callers fall back to the pure-Python path. Loaded with
    PyDLL: strdict_encode walks PyObject* arrays and must hold the GIL."""
    global _STRDICT_LIB, _STRDICT_FAILED
    with _LOCK:
        if _STRDICT_LIB is not None or _STRDICT_FAILED:
            return _STRDICT_LIB
        try:
            import sysconfig

            if (not os.path.exists(_STRDICT_SO)
                    or os.path.getmtime(_STRDICT_SO)
                    < os.path.getmtime(_STRDICT_SRC)):
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                     "-I", sysconfig.get_paths()["include"],
                     _STRDICT_SRC, "-o", _STRDICT_SO],
                    check=True, capture_output=True)
            lib = ctypes.PyDLL(_STRDICT_SO)
            lib.strdict_new.restype = ctypes.c_void_p
            lib.strdict_free.argtypes = [ctypes.c_void_p]
            lib.strdict_clear.argtypes = [ctypes.c_void_p]
            lib.strdict_count.restype = ctypes.c_int64
            lib.strdict_count.argtypes = [ctypes.c_void_p]
            lib.strdict_insert.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                ctypes.c_int64]
            lib.strdict_encode.restype = ctypes.c_int64
            lib.strdict_encode.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                ctypes.c_int64]
            _STRDICT_LIB = lib
        except Exception:
            _STRDICT_FAILED = True
        return _STRDICT_LIB


class CsvLoader:
    """Parse CSV byte buffers into send_columns-ready column dicts.

    String columns come back dictionary-encoded; ids are remapped into the
    app's StringDictionary (one Python round trip per new unique)."""

    def __init__(self, definition, dictionary):
        self.definition = definition
        self.dictionary = dictionary
        self._lib = _lib()
        self._loader = ctypes.c_void_p(self._lib.loader_new())
        self._codes = np.array(
            [_TYPE_CODES[a.type] for a in definition.attributes], np.int32)
        # native-dict id -> app StringDictionary id
        self._remap = np.zeros(0, np.int64)

    def __del__(self):
        try:
            if self._loader:
                self._lib.loader_free(self._loader)
        except Exception:
            pass

    def _sync_dictionary(self):
        n = int(self._lib.loader_dict_size(self._loader))
        if n <= len(self._remap):
            return
        grown = np.zeros(n, np.int64)
        grown[: len(self._remap)] = self._remap
        buf = ctypes.create_string_buffer(1 << 16)
        for i in range(len(self._remap), n):
            ln = self._lib.loader_dict_get(self._loader, i, buf, len(buf))
            grown[i] = self.dictionary.encode(buf.raw[:ln].decode("utf-8"))
        self._remap = grown

    def _native_parse(self, data, out_cols, out_masks, max_rows) -> int:
        return int(self._lib.loader_parse_csv(
            self._loader, data, len(data),
            self._codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(self.definition.attributes), out_cols, out_masks, max_rows))

    def parse(self, data: bytes, max_rows: Optional[int] = None
              ) -> Tuple[Dict[str, np.ndarray], int]:
        """-> (columns dict incl. null masks, n_rows)."""
        attrs = self.definition.attributes
        ncols = len(attrs)
        if max_rows is None:
            max_rows = data.count(b"\n") + 1
        from siddhi_tpu.ops.types import dtype_of

        natives: List[np.ndarray] = []
        out_cols = (ctypes.c_void_p * ncols)()
        out_masks = (ctypes.POINTER(ctypes.c_uint8) * ncols)()
        masks: List[np.ndarray] = []
        for c, a in enumerate(attrs):
            code = self._codes[c]
            arr = np.zeros(max_rows,
                           {0: np.int64, 1: np.float64, 2: np.int64,
                            3: np.uint8}[int(code)])
            natives.append(arr)
            out_cols[c] = arr.ctypes.data_as(ctypes.c_void_p)
            mk = np.zeros(max_rows, np.uint8)
            masks.append(mk)
            out_masks[c] = mk.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        n = self._native_parse(data, out_cols, out_masks, max_rows)
        if n < 0:
            raise ValueError(f"{type(self).__name__}: parse failed")
        self._sync_dictionary()
        cols: Dict[str, np.ndarray] = {}
        for c, a in enumerate(attrs):
            v = natives[c][:n]
            if a.type == AttrType.STRING:
                v = self._remap[v]
            elif a.type == AttrType.BOOL:
                v = v.astype(bool)
            else:
                v = v.astype(dtype_of(a.type))
            cols[a.name] = v
            cols[a.name + "?"] = masks[c][:n].astype(bool)
        return cols, n


class JsonlLoader(CsvLoader):
    """Parse JSON-lines byte buffers (one flat object per line) into
    send_columns-ready column dicts — the native analog of the json
    SourceMapper for bulk ingest. Fields resolve by attribute name;
    missing keys / JSON null become null-masked."""

    def __init__(self, definition, dictionary):
        super().__init__(definition, dictionary)
        names = "".join(a.name for a in definition.attributes).encode("utf-8")
        self._names = names
        self._name_lens = np.array(
            [len(a.name.encode("utf-8")) for a in definition.attributes],
            np.int32)

    def _native_parse(self, data, out_cols, out_masks, max_rows) -> int:
        return int(self._lib.loader_parse_jsonl(
            self._loader, data, len(data), self._names,
            self._name_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self._codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(self.definition.attributes), out_cols, out_masks, max_rows))
