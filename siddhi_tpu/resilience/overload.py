"""Overload armor: per-app quotas, shed-policy backpressure, memory budgets.

The north star is one process hosting a fleet of tenant apps under heavy
traffic. Before this layer, only the REST ``/query`` edge had admission
control (``serving/query_tier.AdmissionPool``); the INGEST path blocked
producers unboundedly when an ``@Async`` junction queue filled, and every
capacity-growth site (dense key capacity, routed shard capacity,
aggregation bucket stores, tables) grew without a ceiling — one hot or
hostile tenant could wedge producers or OOM-abort the whole process. This
module generalizes the AdmissionPool idea to the whole ingest surface,
applying the bounded-buffer/backpressure discipline of "Scaling Ordered
Stream Processing on Shared-Memory Multicores" (PAPERS.md) end to end:

- **Per-app ingest quotas.** ``OverloadConfig`` bounds @Async junction
  queue depth (per stream or app-wide), app-wide dispatch-pipeline depth
  (CompletionPump entries in flight), and an approximate device-memory
  budget charged at every capacity-growth site. Exceeding the queue quota
  triggers a per-stream policy:

  * ``block`` — the producer waits (bounded); every ``block_timeout_s``
    of no progress it ESCALATES to the app supervisor
    (``AppSupervisor.notify_backpressure`` restarts a dead/wedged
    consumer) and counts ``resilience.enqueue_timeouts`` — a wedged
    consumer becomes a repaired consumer, not a deadlocked producer.
  * ``shed_oldest`` — the oldest queued unit is evicted to make room
    (freshest data wins — dashboards, tickers).
  * ``shed_newest`` — the incoming unit is dropped (in-order history
    wins — audit feeds).

  Sheds count events into ``resilience.shed_events`` (and the per-stream
  ``junction.<sid>.shed_events`` telemetry counter) and their ingest-WAL
  records are DISCARDED (``IngestWAL.discard``), so a checkpoint/restore
  cycle replays exactly the non-shed suffix — shed events are never
  resurrected.

- **Weighted fair scheduling.** Registered apps share the host cores and
  device through their @Async junction workers and CompletionPump slots.
  The ``FairScheduler`` tracks a decayed per-app delivery rate; an app
  whose share of recent work exceeds its weighted fair share — while a
  sibling app is backlogged — has its worker briefly yield before each
  delivery, so one flooded tenant cannot starve its siblings' workers of
  the core (or the device of dispatch slots).

- **Graceful budget exhaustion.** ``ensure_memory_budget`` is consulted
  at every capacity-growth site (``QueryRuntime._ensure_capacity``,
  ``mesh.ensure_routed_capacity``, aggregation bucket folds, table
  ``_ensure_room``) BEFORE allocating: past the budget, growth is denied
  with a ``FatalQueryError`` naming ``siddhi_tpu.quota_memory_mb`` (the
  ``QueryRuntime.overflow_knob_msg`` convention) instead of letting XLA
  abort the process. The ledger is approximate by design — it tracks the
  dominant dense-state allocations, not every host byte.

Zero-cost when off: an app with no quota config never registers, its
``app_context.overload`` stays ``None``, and every call site is a single
``getattr`` check — default behavior is bit-identical to the pre-quota
engine (verified by ``tools/quick_all.py``).

Config keys (ConfigManager; see README "Overload protection & quotas"):
``siddhi_tpu.quota_queue_depth[.<stream>]``, ``siddhi_tpu.shed_policy
[.<stream>]``, ``siddhi_tpu.quota_pipeline_depth``,
``siddhi_tpu.quota_memory_mb``, ``siddhi_tpu.quota_block_timeout_s``,
``siddhi_tpu.fair_weight``, ``siddhi_tpu.quota_query_cap``.
Programmatic: ``SiddhiAppRuntime.enable_overload(...)``.
"""

from __future__ import annotations

import logging
import math
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

log = logging.getLogger(__name__)

from siddhi_tpu.analysis.guards import guarded  # noqa: E402
from siddhi_tpu.analysis.locks import make_lock  # noqa: E402

# declared next to the config parser so the accepted spellings cannot
# drift from what the typed knob registry rejects (graftlint R2 class)
from siddhi_tpu.core.util.knobs import SHED_POLICIES  # noqa: E402,F401

# bounded-wait slice for quota/block waits: short enough that a drained
# queue admits promptly, long enough not to spin the core
_WAIT_SLICE_S = 0.002
# producer-side blocking-put slice (junction._enqueue fallback): each
# slice re-checks _fatal so a dying worker surfaces to a blocked producer
BLOCK_PUT_SLICE_S = 0.25
# default escalation period for producers blocked on a full queue — also
# used by the un-quota'd bounded-put fallback in junction._enqueue
DEFAULT_BLOCK_TIMEOUT_S = 5.0


def _units(item) -> int:
    """Event count of one junction queue unit (event chunk or HostBatch)."""
    if item is None:
        return 0
    if isinstance(item, list):
        return len(item)
    size = getattr(item, "size", None)
    return int(size) if size is not None else 1


@dataclass
class OverloadConfig:
    """Per-app overload-protection quotas. ``None`` disables a bound."""

    # max queued units per @Async junction before the shed policy engages
    # (distinct from @Async buffer.size: the quota is the ADMISSION bound,
    # the buffer is the allocation)
    queue_quota: Optional[int] = None
    queue_quota_per_stream: Dict[str, int] = field(default_factory=dict)
    # what happens past the queue quota: block | shed_oldest | shed_newest
    shed_policy: str = "block"
    shed_policy_per_stream: Dict[str, str] = field(default_factory=dict)
    # app-wide cap on CompletionPump entries in flight: past it, each
    # submitting query collapses to ONE riding entry, bounding the
    # steady-state total at max(quota, one per active query) instead of
    # pipeline_depth x N_queries (core/query/completion.py)
    pipeline_quota: Optional[int] = None
    # approximate device-memory budget (bytes) charged at capacity-growth
    # sites; exceeded growth raises FatalQueryError naming the knob
    memory_budget_bytes: Optional[int] = None
    # bounded wait before a blocked producer escalates to the supervisor
    block_timeout_s: float = DEFAULT_BLOCK_TIMEOUT_S
    # weighted fair share across registered apps (FairScheduler)
    fair_weight: float = 1.0
    # per-app REST /query admission cap (AdmissionPool generalization)
    query_cap: Optional[int] = None

    def __post_init__(self):
        policies = [self.shed_policy, *self.shed_policy_per_stream.values()]
        for p in policies:
            if p not in SHED_POLICIES:
                raise ValueError(
                    f"unknown shed policy '{p}' — expected one of "
                    f"{SHED_POLICIES}")


@guarded
class FairScheduler:
    """Weighted fair throttling across registered apps.

    Each delivery charges its app's decayed usage (events, half-life
    ``tau_s``); ``throttle`` sleeps a worker briefly when its app's share
    of total recent usage exceeds its weighted fair share while a SIBLING
    app has backlog. With fewer than two registered apps (or no sibling
    backlog) it never sleeps — solo tenants run at full speed."""

    _SLACK = 1.25            # tolerated overshoot before throttling
    _MAX_SLEEP_S = 0.02      # per-call yield bound (p99-safe)

    GUARDED_BY = {"_apps": "overload"}

    def __init__(self, tau_s: float = 1.0):
        self.tau_s = float(tau_s)
        self._lock = make_lock("overload")
        # name -> {"weight", "usage", "last", "backlog_fn"}
        self._apps: Dict[str, dict] = {}

    def register(self, name: str, weight: float, backlog_fn) -> None:
        with self._lock:
            self._apps[name] = {"weight": max(float(weight), 1e-6),
                                "usage": 0.0, "last": time.monotonic(),
                                "backlog_fn": backlog_fn}

    def unregister(self, name: str) -> None:
        with self._lock:
            self._apps.pop(name, None)

    def _decayed(self, st: dict, now: float) -> float:
        dt = now - st["last"]
        return st["usage"] * math.exp(-dt / self.tau_s) if dt > 0 \
            else st["usage"]

    def throttle(self, name: str, units: int) -> float:
        """Charge ``units`` to ``name`` and return (after sleeping) the
        yield this call paid, in seconds. Cheap when the app runs alone
        or under its fair share."""
        now = time.monotonic()
        delay = 0.0
        with self._lock:
            st = self._apps.get(name)
            if st is None:
                return 0.0
            st["usage"] = self._decayed(st, now) + float(units)
            st["last"] = now
            if len(self._apps) >= 2:
                total_u = total_w = 0.0
                others_backlogged = False
                for n, s in self._apps.items():
                    total_u += self._decayed(s, now)
                    total_w += s["weight"]
                    if n != name and not others_backlogged:
                        try:
                            others_backlogged = bool(s["backlog_fn"]())
                        except Exception:  # noqa: BLE001 — dead probe
                            pass
                if total_u > 0 and others_backlogged:
                    share = st["usage"] / total_u
                    fair = st["weight"] / total_w
                    if share > fair * self._SLACK:
                        delay = min(self._MAX_SLEEP_S,
                                    0.005 * share / fair)
        if delay:
            time.sleep(delay)
        return delay


@guarded
class AppOverloadControl:
    """One registered app's overload state: quota admission for its
    junctions, the memory-budget ledger, and shed/denial accounting.
    Installed as ``app_context.overload`` by ``OverloadManager.register``;
    every engine call site treats ``None`` as "no quotas"."""

    # the shed/denial counters stay undeclared: written under the lock,
    # read lock-free by reports and tests
    GUARDED_BY = {"_ledger": "overload"}

    def __init__(self, manager: "OverloadManager", app_runtime,
                 config: OverloadConfig):
        self.manager = manager
        self.app_runtime = app_runtime
        self.app_context = app_runtime.app_context
        self.config = config
        self._lock = make_lock("overload")
        # component -> charged bytes (capacity-growth ledger)
        self._ledger: Dict[str, int] = {}
        self.shed_events = 0          # events shed across all streams
        self.shed_units = 0           # queue units (batches) shed
        self.quota_denials = 0        # memory-budget growth denials
        self.enqueue_timeouts = 0     # block-policy supervisor escalations

    # ------------------------------------------------------------- lookup

    @property
    def name(self) -> str:
        return self.app_context.name

    @property
    def pipeline_quota(self) -> Optional[int]:
        return self.config.pipeline_quota

    @property
    def memory_budget_bytes(self) -> Optional[int]:
        return self.config.memory_budget_bytes

    @property
    def query_cap(self) -> Optional[int]:
        return self.config.query_cap

    @property
    def block_timeout_s(self) -> float:
        return self.config.block_timeout_s

    def queue_quota_of(self, junction) -> Optional[int]:
        sid = junction.definition.id
        q = self.config.queue_quota_per_stream.get(sid)
        return q if q is not None else self.config.queue_quota

    def policy_of(self, junction) -> str:
        sid = junction.definition.id
        return self.config.shed_policy_per_stream.get(
            sid, self.config.shed_policy)

    # ---------------------------------------------------------- admission

    def admit(self, junction, item, wal_seq=None) -> bool:
        """Quota admission for one @Async enqueue. Returns False when the
        unit was SHED (already counted, WAL record discarded) — the
        junction must not enqueue it. ``block`` policy returns True after
        a bounded wait that escalates to the supervisor on timeout."""
        quota = self.queue_quota_of(junction)
        if quota is None:
            return True
        q = junction._queue
        if q is None or q.qsize() < quota:
            return True
        wal = getattr(self.app_context, "ingest_wal", None)
        if wal is not None and wal.in_replay():
            # a WAL replay re-feeds the ACCEPTED suffix; shedding or
            # re-blocking it would break effectively-once recovery
            return True
        policy = self.policy_of(junction)
        if policy == "shed_newest":
            self._record_shed(junction, _units(item), wal_seq, wal)
            return False
        if policy == "shed_oldest":
            while q.qsize() >= quota:
                try:
                    old = q.get_nowait()
                except queue.Empty:
                    break
                if old is None:
                    # stop sentinel mid-shutdown: keep it, shed the
                    # incoming unit instead (the worker is about to exit)
                    try:
                        q.put_nowait(None)
                    except queue.Full:
                        pass
                    self._record_shed(junction, _units(item), wal_seq, wal)
                    return False
                seq = junction._wal_seq_of.pop(id(old), None) \
                    if junction._wal_seq_of else None
                self._record_shed(junction, _units(old), seq, wal)
            return True
        # block: bounded wait below the quota, escalating each timeout
        waited = 0.0
        while q.qsize() >= quota:
            if junction._fatal is not None:
                raise junction._fatal
            if not junction._running:
                return True          # shutdown: let the put path decide
            time.sleep(_WAIT_SLICE_S)
            waited += _WAIT_SLICE_S
            if waited >= self.config.block_timeout_s:
                waited = 0.0
                self.escalate(junction)
        return True

    def _record_shed(self, junction, n_events: int, wal_seq, wal) -> None:
        from siddhi_tpu.resilience import stat_count

        if wal is not None and wal_seq is not None:
            # never WAL-recorded: a restore must replay exactly the
            # non-shed suffix, not resurrect what admission dropped
            wal.discard(wal_seq)
        with self._lock:
            self.shed_events += n_events
            self.shed_units += 1
        sid = junction.definition.id
        tel = getattr(self.app_context, "telemetry", None)
        if tel is not None:
            tel.count(f"junction.{sid}.shed_events", n_events)
        stat_count(self.app_context, "resilience.shed_events", n_events)

    def escalate(self, junction) -> None:
        """A producer made no progress for ``block_timeout_s``: count it,
        and hand the junction to the supervisor — which restarts a dead
        or beat-stalled consumer — instead of deadlocking silently."""
        from siddhi_tpu.resilience import stat_count

        with self._lock:
            self.enqueue_timeouts += 1
        sid = junction.definition.id
        tel = getattr(self.app_context, "telemetry", None)
        if tel is not None:
            tel.count(f"junction.{sid}.enqueue_timeouts")
        stat_count(self.app_context, "resilience.enqueue_timeouts")
        sup = getattr(self.app_context, "supervisor", None)
        if sup is not None and hasattr(sup, "notify_backpressure"):
            try:
                sup.notify_backpressure(junction)
            except Exception:  # noqa: BLE001 — escalation must not mask
                log.exception("backpressure escalation failed")
        else:
            log.warning(
                "producer blocked on full queue of junction '%s' for "
                "%.1fs and no supervisor is attached — call "
                "rt.supervise() so a wedged consumer can be replaced",
                sid, self.config.block_timeout_s)

    # ------------------------------------------------------ memory budget

    def charged_bytes(self) -> int:
        with self._lock:
            return sum(self._ledger.values())

    def charge(self, component: str, nbytes: int) -> None:
        with self._lock:
            self._ledger[component] = max(int(nbytes), 0)

    def ensure_budget(self, component: str, projected_bytes: int,
                      what: str) -> None:
        """Deny growth past the budget with a ``FatalQueryError`` naming
        the knob (``overflow_knob_msg`` convention) — BEFORE allocating,
        so a hostile tenant's growth dies cleanly instead of OOM-aborting
        the process."""
        budget = self.config.memory_budget_bytes
        if budget is None:
            return
        with self._lock:
            used_others = sum(v for k, v in self._ledger.items()
                              if k != component)
        total = used_others + max(int(projected_bytes), 0)
        if total <= budget:
            return
        from siddhi_tpu.core.stream.junction import FatalQueryError
        from siddhi_tpu.resilience import stat_count

        with self._lock:
            self.quota_denials += 1
        tel = getattr(self.app_context, "telemetry", None)
        if tel is not None:
            tel.count("overload.quota_denials")
        stat_count(self.app_context, "resilience.quota_denials")
        raise FatalQueryError(
            f"app '{self.name}': {what} denied — device-memory budget "
            f"exhausted ({component} needs {int(projected_bytes)} B, "
            f"{used_others} B already charged elsewhere, budget "
            f"{budget} B) — raise siddhi_tpu.quota_memory_mb "
            f"(enable_overload(memory_budget_mb=...))")

    # ----------------------------------------------------- fair scheduling

    def throttle(self, units: int) -> None:
        self.manager.fair.throttle(self.name, units)

    def backlog(self) -> int:
        """Queued @Async units across the app's junctions (the fair
        scheduler's are-siblings-starving probe)."""
        total = 0
        for j in self.app_runtime.junctions.values():
            q = getattr(j, "_queue", None)
            if q is not None:
                total += q.qsize()
        return total

    # ----------------------------------------------------------- gauges

    def utilization(self) -> Dict[str, float]:
        # presence, not truthiness: an explicit quota of 0 is enforced
        # (every submit drains / every growth denies) and reads as
        # saturated the moment anything is in use
        out = {}
        pq = self.config.pipeline_quota
        if pq is not None:
            pump = getattr(self.app_context, "completion_pump", None)
            n = pump._n_pending if pump is not None else 0
            out["pipeline"] = n / pq if pq > 0 else float(n > 0)
        budget = self.config.memory_budget_bytes
        if budget is not None:
            c = self.charged_bytes()
            out["memory"] = c / budget if budget > 0 else float(c > 0)
        return out


@guarded
class OverloadManager:
    """Process-global registry of overload-protected apps — one per
    process, like the serving tier's scatter pool."""

    _inst: Optional["OverloadManager"] = None
    _inst_lock = threading.Lock()

    GUARDED_BY = {"_apps": "overload"}

    def __init__(self):
        self._lock = make_lock("overload")
        self._apps: Dict[str, AppOverloadControl] = {}
        self.fair = FairScheduler()

    @classmethod
    def instance(cls) -> "OverloadManager":
        with cls._inst_lock:
            if cls._inst is None:
                cls._inst = OverloadManager()
            return cls._inst

    def register(self, app_runtime,
                 config: OverloadConfig) -> AppOverloadControl:
        """Install quota control on ``app_runtime`` (idempotent — a
        re-register replaces the config, keeping counters)."""
        name = app_runtime.app_context.name
        with self._lock:
            ctl = self._apps.get(name)
            if ctl is not None and ctl.app_context is app_runtime.app_context:
                ctl.config = config
            else:
                ctl = AppOverloadControl(self, app_runtime, config)
                self._apps[name] = ctl
        app_runtime.app_context.overload = ctl
        self.fair.register(name, config.fair_weight, ctl.backlog)
        self._register_gauges(ctl)
        return ctl

    def unregister(self, name: str, ctl=None) -> None:
        """Drop a registration. ``ctl`` pins the expected control: a NEWER
        app registered under the same name (blue/green redeploys) must not
        lose ITS registration when the old app shuts down."""
        with self._lock:
            cur = self._apps.get(name)
            if cur is None or (ctl is not None and cur is not ctl):
                cur = None
            else:
                del self._apps[name]
        if cur is None:
            return
        self.fair.unregister(name)
        if getattr(cur.app_context, "overload", None) is cur:
            cur.app_context.overload = None

    def control_of(self, name: str) -> Optional[AppOverloadControl]:
        with self._lock:
            return self._apps.get(name)

    def _register_gauges(self, ctl: AppOverloadControl) -> None:
        """Per-app quota-utilization gauges on the app's telemetry
        registry (``GET /metrics`` → ``siddhi_quota_utilization``):
        how close each bounded resource runs to its quota."""
        tel = getattr(ctl.app_context, "telemetry", None)
        if tel is None:
            return
        cfg = ctl.config
        for sid, j in ctl.app_runtime.junctions.items():
            # presence, not truthiness: an explicit per-stream quota of
            # 0 is enforced by admit() and must gauge as saturated, not
            # fall through to the app-wide quota (typed-knob contract)
            quota = cfg.queue_quota_per_stream.get(sid)
            if quota is None:
                quota = cfg.queue_quota
            if quota is not None and getattr(j, "_queue", None) is not None:
                tel.gauge(
                    f"quota.queue_utilization.{sid}",
                    lambda jn=j, q=quota: (
                        (jn._queue.qsize() / q if q > 0
                         else float(jn._queue.qsize() > 0))
                        if jn._queue is not None else 0.0))
        if cfg.pipeline_quota is not None:
            pump = getattr(ctl.app_context, "completion_pump", None)
            if pump is not None:
                tel.gauge("quota.pipeline_utilization",
                          lambda p=pump, q=cfg.pipeline_quota:
                          (p._n_pending / q if q > 0
                           else float(p._n_pending > 0)))
        if cfg.memory_budget_bytes is not None:
            tel.gauge("quota.memory_utilization",
                      lambda c=ctl, b=cfg.memory_budget_bytes:
                      (c.charged_bytes() / b if b > 0
                       else float(c.charged_bytes() > 0)))


# --------------------------------------------------- module-level helpers
# Engine call sites use these so the default (unregistered) path costs one
# getattr and returns.

def ensure_memory_budget(app_context, component: str, projected_bytes: int,
                         what: str) -> None:
    """Budget gate for a capacity-growth site: raises ``FatalQueryError``
    naming ``siddhi_tpu.quota_memory_mb`` when growing ``component`` to
    ``projected_bytes`` would exceed the app's device-memory budget."""
    ctl = getattr(app_context, "overload", None)
    if ctl is None:
        return
    ctl.ensure_budget(component, projected_bytes, what)


def charge_memory(app_context, component: str, nbytes: int) -> None:
    """Record ``component``'s current approximate dense-state footprint
    in the app's budget ledger (call after a growth actually happened)."""
    ctl = getattr(app_context, "overload", None)
    if ctl is None:
        return
    ctl.charge(component, nbytes)
