"""Ingest WAL: a bounded, per-process replay log for effectively-once
recovery.

Full snapshots are already O(state) for dense-array runtimes (SURVEY.md
§5.4), so the only thing a checkpoint loses is the ingest SUFFIX — every
batch accepted after the last barrier. The WAL records that suffix at the
``InputHandler``/``StreamJunction`` boundary (inside the snapshot quiesce
barrier, so a checkpoint always cuts at a batch boundary), is trimmed at
every durable checkpoint, and is replayed in arrival order after
``restore_revision``. Region-based-state streaming (PAPERS.md) makes this
the cheap half of recovery: state restore is one pytree copy, replay is a
re-send of host-side columnar batches.

Bounds and overflow: the log is bounded by ``max_batches`` (and optionally
``max_events``). On overflow the OLDEST record is dropped and
``dropped_batches`` is bumped — recovery from the previous checkpoint then
has a hole, which the counter (and the ``resilience.wal_dropped_batches``
statistic) makes visible. Operators should checkpoint at least as often
as the WAL can hold; the bound trades recovery completeness for a hard
memory ceiling, never blocking ingest.

Trim protocol: appends and checkpoint cuts both happen under the app's
ingestion barrier, but the durable save happens OUTSIDE it (persist()
releases the barrier before writing the store). ``cut()`` under the
barrier marks the sequence number the snapshot covers; ``trim(cut)``
after the save removes exactly the covered prefix — a batch accepted
between capture and save survives in the log.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import List, Optional

from siddhi_tpu.analysis.guards import guarded
from siddhi_tpu.analysis.locks import make_lock
from siddhi_tpu.core.event import Event


class _Record:
    __slots__ = ("seq", "stream_id", "kind", "payload", "timestamps", "size")

    def __init__(self, seq, stream_id, kind, payload, timestamps, size):
        self.seq = seq
        self.stream_id = stream_id
        self.kind = kind              # 'events' | 'columns'
        self.payload = payload
        self.timestamps = timestamps
        self.size = size


def _copy_columns(data):
    """Defensive copy: producers reuse/mutate their column buffers."""
    import numpy as np

    out = {}
    for k, v in data.items():
        if hasattr(v, "dtype"):
            out[k] = np.array(v, copy=True)
        else:
            out[k] = list(v)
    return out


def register_wal_gauges(app_context) -> None:
    """Expose the context's attached WAL on its telemetry registry
    (``wal.batches`` / ``wal.pending_events`` / ``wal.dropped_batches``
    on GET /metrics). Called wherever a WAL is ATTACHED to a context —
    ``SiddhiAppRuntime.enable_wal`` and the peer-recovery rebuild
    (``supervisor.PeerRecovery.recover``) — so a post-recovery runtime,
    where WAL growth matters most, is never scraped blind. Idempotent
    (gauges are keyed by name)."""
    wal = getattr(app_context, "ingest_wal", None)
    tel = getattr(app_context, "telemetry", None)
    if wal is None or tel is None:
        return
    tel.gauge("wal.batches", wal.__len__)
    tel.gauge("wal.pending_events", lambda w=wal: w.pending_events)
    tel.gauge("wal.dropped_batches", lambda w=wal: w.dropped_batches)


@guarded
class IngestWAL:
    """Per-process bounded ingest log (see module docstring)."""

    # the overflow/shed/replay counters stay undeclared: monotonic,
    # single-writer, read lock-free by gauges and reports
    GUARDED_BY = {"_log": "wal", "_seq": "wal", "_events": "wal"}

    def __init__(self, max_batches: int = 4096,
                 max_events: Optional[int] = None,
                 app_context=None):
        if max_batches <= 0:
            raise ValueError("IngestWAL needs max_batches > 0")
        self.max_batches = int(max_batches)
        self.max_events = max_events
        self.app_context = app_context    # statistics hookup (optional)
        self._log: deque = deque()
        self._lock = make_lock("wal")
        self._seq = 0
        self._events = 0                  # events currently held
        self.dropped_batches = 0          # overflow evictions (lossy!)
        self.shed_records = 0             # admission sheds (overload.py)
        self.replayed_batches = 0
        self.recorded_batches = 0
        # revision whose snapshot the retained suffix FOLLOWS (set by the
        # checkpoint trim); restore_revision consults it so a restore of
        # an OLDER revision does not graft the suffix onto a stale base
        self.checkpoint_revision: Optional[str] = None
        # highest sequence any checkpoint trim has covered: a restore of a
        # snapshot whose cut predates this must SKIP the replay (the
        # retained suffix follows a newer base) — consulted by the serving
        # tier's per-shard rebuild (serving/sharded_aggregation.py)
        self.checkpoint_seq = 0
        # re-record suppression is scoped to the REPLAYING THREAD only:
        # live ingest accepted concurrently on other threads must still
        # be recorded, or the next failure silently loses it
        self._replay_thread: Optional[int] = None

    def in_replay(self) -> bool:
        """True on the thread currently executing ``replay()`` — consulted
        by the record paths (suppress re-recording) and by the
        InputHandler's @app:enforceOrder watermark (a replayed suffix
        re-enters with its ORIGINAL timestamps, behind the watermark)."""
        return self._replay_thread == threading.get_ident()

    # ------------------------------------------------------------- record

    def record_events(self, stream_id: str,
                      events: List[Event]) -> Optional[int]:
        """Returns the record's sequence number (None when suppressed) —
        the handle ``discard`` takes if admission later SHEDS the batch
        (resilience/overload.py: shed events are never replayed)."""
        if self.in_replay() or not events:
            return None
        copies = [Event(timestamp=e.timestamp, data=list(e.data))
                  for e in events]
        return self._append(_Record(None, stream_id, "events", copies, None,
                                    len(copies)))

    def record_columns(self, stream_id: str, data,
                       timestamps=None) -> Optional[int]:
        if self.in_replay():
            return None
        import numpy as np

        n = 0
        for v in data.values():
            n = len(v)
            break
        ts = np.array(timestamps, np.int64) if timestamps is not None else None
        return self._append(_Record(None, stream_id, "columns",
                                    _copy_columns(data), ts, n))

    def _append(self, rec: _Record) -> int:
        with self._lock:
            self._seq += 1
            rec.seq = self._seq
            self._log.append(rec)
            self._events += rec.size
            self.recorded_batches += 1
            while (len(self._log) > self.max_batches
                   or (self.max_events is not None
                       and self._events > self.max_events
                       and len(self._log) > 1)):
                old = self._log.popleft()
                self._events -= old.size
                self.dropped_batches += 1
                self._count("resilience.wal_dropped_batches")
            return rec.seq

    def discard(self, seq: int) -> bool:
        """Remove one retained record by sequence number — the shed path
        (``resilience/overload.py``): a batch that admission dropped was
        never processed, so replaying it after a restore would resurrect
        events the live run shed. No-op (False) when the record was
        already trimmed or evicted. Replay iterates records, so the seq
        gap this leaves is harmless."""
        with self._lock:
            for i, rec in enumerate(self._log):
                if rec.seq == seq:
                    del self._log[i]
                    self._events -= rec.size
                    self.shed_records += 1
                    return True
        return False

    # ------------------------------------------------- checkpoint protocol

    def cut(self) -> int:
        """Sequence mark of everything a snapshot captured — call while
        holding the app barrier, alongside the state capture."""
        with self._lock:
            return self._seq

    def trim(self, upto_seq: int) -> int:
        """Drop records covered by a durably-saved checkpoint; returns how
        many were trimmed."""
        n = 0
        with self._lock:
            while self._log and self._log[0].seq <= upto_seq:
                rec = self._log.popleft()
                self._events -= rec.size
                n += 1
            if upto_seq > self.checkpoint_seq:
                self.checkpoint_seq = upto_seq
        return n

    def mark_checkpoint(self, revision: Optional[str] = None) -> int:
        """Unconditional trim of the whole log (checkpoint under a held
        barrier, or restore completing — the restored state supersedes);
        records ``revision`` as the base the (now empty) suffix follows."""
        n = self.trim(self.cut())
        if revision is not None:
            self.checkpoint_revision = revision
        return n

    # -------------------------------------------------------------- replay

    def __len__(self) -> int:
        with self._lock:
            return len(self._log)

    @property
    def pending_events(self) -> int:
        with self._lock:
            return self._events

    def records_after(self, seq: int) -> List[_Record]:
        """Retained records with sequence > ``seq`` (oldest first) — the
        suffix a restored snapshot with cut ``seq`` is missing. Used by
        shard-scoped rebuilds that re-fold records directly instead of
        re-sending through input handlers."""
        with self._lock:
            return [r for r in self._log if r.seq > seq]

    def replay(self, app_runtime) -> int:
        """Re-send the retained suffix in arrival order through the given
        runtime's input handlers. Returns the number of replayed batches.
        The records stay in the log (they are still the post-checkpoint
        suffix of the restored state, and must survive a second failure);
        re-recording is suppressed only for THIS wal — a different wal on
        the target runtime correctly records the replay as fresh ingest."""
        with self._lock:
            records = list(self._log)
        self._replay_thread = threading.get_ident()
        try:
            for rec in records:
                h = app_runtime.get_input_handler(rec.stream_id)
                if rec.kind == "events":
                    h.send([Event(timestamp=e.timestamp, data=list(e.data))
                            for e in rec.payload])
                else:
                    h.send_columns(_copy_columns(rec.payload),
                                   timestamps=rec.timestamps)
                self.replayed_batches += 1
                self._count("resilience.wal_replayed_batches")
        finally:
            self._replay_thread = None
        return len(records)

    def _count(self, name: str) -> None:
        from siddhi_tpu.resilience import stat_count

        if self.app_context is not None:
            stat_count(self.app_context, name)
