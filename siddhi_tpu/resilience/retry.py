"""Shared retry/backoff policy.

The reference hand-rolls exponential reconnect backoff inside every
transport (``Source.java:155-185``); here the policy is one object shared
by sources, sinks, and the peer transport, so deployment config tunes one
knob set. Backoff is exponential with a multiplicative jitter CAP: the
k-th delay is ``min(initial * multiplier**k, max) * (1 + jitter * u_k)``
with ``u_k`` drawn from a seeded RNG — deterministic for tests, decorrelated
across real deployments that seed differently.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type


class RetryExhausted(Exception):
    """``max_attempts`` retries failed; carries the last cause."""


class RetryPolicy:
    def __init__(self, initial_ms: float = 100, max_ms: float = 5_000,
                 multiplier: float = 2.0, jitter: float = 0.0,
                 max_attempts: Optional[int] = None, seed: int = 0):
        if initial_ms <= 0 or max_ms < initial_ms or multiplier < 1.0:
            raise ValueError("retry policy needs initial_ms > 0, "
                             "max_ms >= initial_ms, multiplier >= 1")
        self.initial_ms = float(initial_ms)
        self.max_ms = float(max_ms)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.max_attempts = max_attempts
        self.seed = seed

    def delays_ms(self) -> Iterator[float]:
        """The (possibly unbounded) backoff schedule, jitter applied."""
        rng = random.Random(self.seed)
        delay = self.initial_ms
        k = 0
        while self.max_attempts is None or k < self.max_attempts:
            capped = min(delay, self.max_ms)
            yield capped * (1.0 + self.jitter * rng.random())
            delay = min(delay * self.multiplier, self.max_ms)
            k += 1

    def run(self, fn: Callable, retry_on: Tuple[Type[BaseException], ...],
            stop: Optional[Callable[[], bool]] = None,
            on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
            sleep: Callable[[float], None] = time.sleep):
        """Call ``fn`` until it succeeds, retrying on ``retry_on`` with this
        policy's backoff. ``stop()`` (checked before every sleep) aborts the
        loop — returns None, the shutdown path of a reconnect loop.
        ``on_retry(attempt, exc, delay_ms)`` observes each failure. Raises
        ``RetryExhausted`` when ``max_attempts`` delays are spent."""
        for attempt, delay in enumerate(self.delays_ms(), start=1):
            if stop is not None and stop():
                return None
            try:
                return fn()
            except retry_on as ex:
                if on_retry is not None:
                    on_retry(attempt, ex, delay)
                if stop is not None and stop():
                    return None
                sleep(delay / 1000.0)
        # a bounded schedule ran dry (unbounded schedules never reach here):
        # one final attempt, then surface the failure
        if stop is not None and stop():
            return None
        try:
            return fn()
        except retry_on as ex:
            raise RetryExhausted(
                f"{self.max_attempts} retries exhausted: {ex}") from ex
