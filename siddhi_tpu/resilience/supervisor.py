"""App-level supervision: worker heartbeats and the peer-death protocol.

The reference keeps its engine alive with per-transport retry loops
(``Source.java:155-185``) and leaves worker threads to the Disruptor; our
``@Async`` junctions run plain host threads, and the multi-process mesh
adds a failure mode the reference never had — a peer dying mid-collective
wedges every other host inside XLA (``parallel/distributed.py``). The
supervisor owns both:

- **Worker heartbeats.** Every async junction worker bumps a beats
  counter each drain iteration and polls its queue with a bounded wait,
  so a healthy worker beats at least ~2 Hz even when idle. A worker whose
  thread died is restarted immediately; a worker whose beats stalled past
  ``wedge_timeout_s`` is presumed wedged and REPLACED — the queue and any
  in-flight batch stay on the junction, and the junction's worker
  generation token makes a later-waking stale worker exit without
  double-delivering (``core/stream/junction.py``).

- **Peer recovery.** ``StreamJunction.handle_error`` notifies the
  supervisor of every processing error; on ``ClusterPeerError`` the
  supervisor runs the protocol ``distributed.py`` promises, exactly once:
  abandon the wedged runtime (collectives are not cancellable — the stuck
  waits stay parked in daemon threads), rebuild on the surviving hosts
  (caller-supplied: a fresh runtime over ``local_survivor_mesh()`` or a
  re-formed ``jax.distributed`` incarnation), ``restore_last_revision()``
  from the replicated snapshot store, replay the ingest WAL suffix, and
  resume feeds.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from siddhi_tpu.analysis.guards import guarded
from siddhi_tpu.analysis.locks import make_lock

log = logging.getLogger(__name__)


@guarded
class PeerMonitor:
    """Socket liveness heartbeats between cluster processes.

    A peer dying mid-collective is detected by the bounded device pull
    (``distributed.guarded_pull``) — but only when a collective is in
    flight. The monitor closes that gap: every process binds a tiny TCP
    listener, every supervisor probes its peers' listeners each tick, and
    a peer that was reachable once and then refuses ``misses`` consecutive
    probes is declared dead (an abruptly killed process's listener drops
    instantly, so detection is ~``misses`` ticks — typically faster than a
    pull timeout). The supervisor folds confirmed deaths into the same
    ``ClusterPeerError`` recovery path as a blocked pull."""

    # watch/unwatch/rearm run on supervisor threads while poll_dead's
    # bookkeeping runs on the tick thread; probes happen OUTSIDE the
    # lock (a slow connect must not block an unwatch)
    GUARDED_BY = {"_peers": "app_supervisor", "_dead": "app_supervisor"}

    def __init__(self, listen_port: int = 0, probe_timeout_s: float = 1.0,
                 misses: int = 3):
        import socket

        self.probe_timeout_s = float(probe_timeout_s)
        self.misses = int(misses)
        self._lock = make_lock("app_supervisor")
        self._peers = {}          # addr -> {"seen": bool, "missed": int}
        self._dead = set()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", listen_port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._accepting = True
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"peer-monitor-:{self.port}")
        t.start()

    def _accept_loop(self) -> None:
        while self._accepting:
            try:
                conn, _addr = self._sock.accept()
                conn.close()          # the successful connect IS the beat
            except OSError:
                return

    def watch(self, host: str, port: int) -> None:
        with self._lock:
            self._peers[(host, int(port))] = {"seen": False, "missed": 0}

    def unwatch(self, host: str, port: int) -> None:
        """Stop probing an address (a respawned peer binds a NEW port;
        the old listener must not linger as a perpetual corpse)."""
        addr = (host, int(port))
        with self._lock:
            self._peers.pop(addr, None)
            self._dead.discard(addr)

    def rearm(self, host: str, port: int) -> None:
        """Forget a peer's death and watch its address from scratch —
        the cluster supervisor's respawn path (``cluster/supervisor.py``):
        the replacement worker is 'not up yet' until its listener is
        first reached, never instantly re-declared dead."""
        addr = (host, int(port))
        with self._lock:
            self._dead.discard(addr)
            self._peers[addr] = {"seen": False, "missed": 0}

    def poll_dead(self) -> list:
        """Probe every watched peer once; returns NEWLY dead addresses."""
        import socket

        with self._lock:
            targets = [(addr, st) for addr, st in self._peers.items()
                       if addr not in self._dead]
        newly = []
        for addr, st in targets:
            try:
                s = socket.create_connection(addr, self.probe_timeout_s)
                s.close()
                ok = True
            except OSError:
                ok = False
            with self._lock:
                if self._peers.get(addr) is not st:
                    continue    # unwatched/rearmed mid-probe: stale result
                if ok:
                    st["seen"] = True
                    st["missed"] = 0
                elif st["seen"]:  # never-reached peers are "not up yet"
                    st["missed"] += 1
                    if st["missed"] >= self.misses:
                        self._dead.add(addr)
                        newly.append(addr)
        return newly

    def close(self) -> None:
        self._accepting = False
        try:
            self._sock.close()
        except OSError:
            pass


def is_peer_failure(error: Exception) -> bool:
    """ClusterPeerError is the guarded-pull timeout; a dead peer's
    transport can also surface FASTER as a raw runtime error from inside
    the collective ("Connection closed by peer" / "connection reset by
    peer" — gloo noticing the closed socket before the bounded wait
    expires). Both mean the same thing for supervision. The substring
    match is scoped to jax/jaxlib exception types: an app-level socket
    error (a flaky SINK client also says "reset by peer", errno 104) must
    not tear down a healthy runtime."""
    from siddhi_tpu.parallel.distributed import ClusterPeerError

    if isinstance(error, ClusterPeerError):
        return True
    mod = getattr(type(error), "__module__", "") or ""
    if not mod.startswith(("jax", "xla")):
        return False
    msg = str(error).lower()
    return "closed by peer" in msg or "reset by peer" in msg


def abandon_runtime(app_runtime) -> None:
    """Best-effort, non-blocking teardown of a runtime presumed wedged on
    a dead peer: no deferred flushes (they would block on the same dead
    collective), no worker joins. Stops ingest, sources, timers."""
    app_runtime.app_context.stopped = True
    try:
        app_runtime.app_context.timestamp_generator.stop_heartbeat()
    except Exception:
        pass
    for sr in getattr(app_runtime, "source_runtimes", []):
        try:
            sr.shutdown()
        except Exception:
            pass
    for tr in getattr(app_runtime, "trigger_runtimes", []):
        try:
            tr.stop()
        except Exception:
            pass
    for j in app_runtime.junctions.values():
        j._running = False
        j._gen += 1          # any parked worker exits on its next wake
    sched = app_runtime.app_context.scheduler
    if sched is not None:
        try:
            sched.shutdown()
        except Exception:
            pass


class PeerRecovery:
    """One execution of the peer-death recovery protocol.

    ``rebuild()`` must return a FRESH ``SiddhiAppRuntime`` for the same
    app, already wired to the replicated persistence store and with its
    callbacks re-attached — on the survivor's own devices
    (``distributed.local_survivor_mesh()``) or a re-formed cluster. The
    old runtime is abandoned, the last revision restored, the WAL suffix
    replayed, and sources resumed.
    """

    def __init__(self, rebuild: Callable[[], object],
                 wal=None,
                 on_recovered: Optional[Callable[[object, Optional[str]],
                                                 None]] = None):
        self.rebuild = rebuild
        self.wal = wal
        self.on_recovered = on_recovered

    def recover(self, old_runtime=None):
        """Returns ``(new_runtime, restored_revision)``."""
        from siddhi_tpu.resilience import stat_count

        if old_runtime is not None:
            abandon_runtime(old_runtime)
        new_rt = self.rebuild()
        if self.wal is not None and getattr(
                new_rt.app_context, "ingest_wal", None) is None:
            # the survivor's log must also guard the NEW incarnation —
            # gauges included: after a recovery is exactly when WAL
            # growth/drops must be scrapeable
            new_rt.app_context.ingest_wal = self.wal
            from siddhi_tpu.resilience.replay import register_wal_gauges

            register_wal_gauges(new_rt.app_context)
        revision = new_rt.restore_last_revision()
        # restore_last_revision replays the wal attached to new_rt; replay
        # explicitly only when ours is a different object (or nothing was
        # restored — a WAL-only recovery still re-feeds the suffix)
        if self.wal is not None and (
                revision is None
                or getattr(new_rt.app_context, "ingest_wal", None)
                is not self.wal):
            self.wal.replay(new_rt)
        for sr in getattr(new_rt, "source_runtimes", []):
            sr.resume()
        stat_count(new_rt.app_context, "resilience.peer_recoveries")
        if self.on_recovered is not None:
            self.on_recovered(new_rt, revision)
        return new_rt, revision


@guarded
class AppSupervisor:
    """Heartbeats one app's async junction workers and drives peer
    recovery. ``SiddhiAppRuntime.supervise()`` is the usual entry."""

    # the tick thread and producer-backpressure escalations
    # (notify_backpressure, any sender thread) both read-modify-write
    # the beat table — the pre-R8 tick wrote it with no lock at all
    GUARDED_BY = {"_beat_seen": "app_supervisor"}

    def __init__(self, app_runtime, interval_s: float = 0.25,
                 wedge_timeout_s: float = 5.0,
                 peer_recovery: Optional[PeerRecovery] = None,
                 peer_monitor: Optional[PeerMonitor] = None):
        from siddhi_tpu.core.stream.junction import _IDLE_POLL_S

        self.app_runtime = app_runtime
        self.interval_s = float(interval_s)
        # below 3 idle-poll periods an IDLE worker (which only beats when
        # its bounded queue wait times out) would look wedged
        self.wedge_timeout_s = max(float(wedge_timeout_s),
                                   3.0 * _IDLE_POLL_S)
        self.peer_monitor = peer_monitor
        self.peer_recovery = peer_recovery
        self.worker_restarts = 0
        self.pump_wedges = 0              # pipeline-drain stalls detected
        self._pump_wedge_flagged = False  # one count/log per episode
        self.recovery_result = None       # (new_runtime, revision)
        self._beat_seen = {}              # junction id -> (beats, t_changed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._recovering = threading.Event()
        self._recovered = threading.Event()
        self._lock = make_lock("app_supervisor")

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "AppSupervisor":
        if self._thread is not None:
            return self
        self.app_runtime.app_context.supervisor = self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"supervisor-{self.app_runtime.name}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
        if self.peer_monitor is not None:
            self.peer_monitor.close()
        if getattr(self.app_runtime.app_context, "supervisor", None) is self:
            self.app_runtime.app_context.supervisor = None

    # --------------------------------------------------------- heartbeats

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._tick()
            except Exception:                     # noqa: BLE001
                log.exception("supervisor tick failed")

    def _tick(self) -> None:
        from siddhi_tpu.resilience import stat_count

        if self.peer_monitor is not None:
            from siddhi_tpu.parallel.distributed import ClusterPeerError

            for addr in self.peer_monitor.poll_dead():
                self.notify_error(None, ClusterPeerError(
                    f"cluster peer {addr[0]}:{addr[1]} lost its heartbeat "
                    f"— presumed dead; restore from the last snapshot "
                    f"revision"))
        now = time.monotonic()
        for sid, j in list(self.app_runtime.junctions.items()):
            if not (getattr(j, "_async", False) and j._running):
                continue
            # the beat table is shared with notify_backpressure (sender
            # threads): the whole read-judge-restart sequence must be
            # one atom or a concurrent escalation double-restarts
            with self._lock:
                worker = j._worker
                beats = j._beats
                seen = self._beat_seen.get(sid)
                if seen is None or seen[0] != beats:
                    self._beat_seen[sid] = (beats, now)
                    stalled = False
                else:
                    stalled = (now - seen[1]) > self.wedge_timeout_s
                dead = worker is None or not worker.is_alive()
                if j._fatal is not None:
                    continue    # framework failure: surfaced to
                    #             senders, not a restartable fault
                if not (dead or stalled):
                    continue
                log.warning("supervisor: restarting %s worker of "
                            "junction '%s'",
                            "dead" if dead else "wedged", sid)
                j.restart_worker()
                self.worker_restarts += 1
                self._beat_seen[sid] = (j._beats, now)
            stat_count(self.app_runtime.app_context,
                       "resilience.worker_restarts")
        # ingest pack-pool workers are supervised like junction workers:
        # a dead packer already had its sub-batch re-packed by the merge
        # thread (never lost); the tick respawns the thread so capacity
        # recovers without waiting for the next submit
        pool = getattr(self.app_runtime.app_context, "ingest_pack_pool",
                       None)
        if pool is not None:
            healed = pool.heal()
            if healed:
                log.warning("supervisor: respawned %d dead ingest pack "
                            "worker(s)", healed)
                self.worker_restarts += healed
                stat_count(self.app_runtime.app_context,
                           "resilience.worker_restarts", healed)
        self._check_pump()

    def _check_pump(self) -> None:
        """Wedged-pipeline detection: a CompletionPump entry whose meta
        never arrives means the device step (or a cluster collective
        behind it) hung — the producers keep packing while nothing
        emits, a failure mode the worker heartbeats cannot see (the
        worker is healthy; it just never drains). Detection only: with
        ``cluster_step_timeout`` set the drain itself surfaces a labeled
        ``ClusterPeerError`` through the junction's fault machinery; the
        in-flight pipeline survives worker replacement untouched (its
        entries belong to the pump, not the worker thread), so the
        replacement drains it in order without loss or double-emit."""
        from siddhi_tpu.resilience import stat_count

        pump = getattr(self.app_runtime.app_context, "completion_pump",
                       None)
        if pump is None:
            return
        age = pump.oldest_age_s()
        if age is not None and age > self.wedge_timeout_s:
            if not self._pump_wedge_flagged:
                self._pump_wedge_flagged = True
                self.pump_wedges += 1
                log.warning(
                    "supervisor: completion pump of app '%s' looks "
                    "wedged — oldest in-flight batch is %.1fs old and "
                    "its __meta__ never arrived (hung device step or "
                    "dead collective peer)",
                    self.app_runtime.name, age)
                stat_count(self.app_runtime.app_context,
                           "resilience.pump_wedges")
        else:
            self._pump_wedge_flagged = False

    # -------------------------------------------------- producer backpressure

    def notify_backpressure(self, junction) -> bool:
        """A producer's bounded enqueue wait timed out
        (``StreamJunction._enqueue`` / the overload layer's ``block``
        policy): check the junction's consumer NOW instead of waiting for
        the next tick, and replace it when dead or beat-stalled. Returns
        True when a restart was issued — the blocked producer's queue
        starts draining again; a healthy-but-slow consumer is left alone
        (the wait was genuine backpressure, not a wedge)."""
        from siddhi_tpu.resilience import stat_count

        if not (getattr(junction, "_async", False) and junction._running):
            return False
        if junction._fatal is not None:
            return False      # surfaced to senders, not restartable
        sid = junction.definition.id
        with self._lock:
            now = time.monotonic()
            worker = junction._worker
            dead = worker is None or not worker.is_alive()
            seen = self._beat_seen.get(sid)
            if seen is None:
                # first sighting: record a baseline so the NEXT timeout
                # can distinguish stalled from slow
                self._beat_seen[sid] = (junction._beats, now)
                stalled = False
            else:
                stalled = (seen[0] == junction._beats
                           and (now - seen[1]) > self.wedge_timeout_s)
            if not (dead or stalled):
                return False
            log.warning(
                "supervisor: producer backpressure escalation — "
                "restarting %s worker of junction '%s'",
                "dead" if dead else "wedged", sid)
            junction.restart_worker()
            self.worker_restarts += 1
            self._beat_seen[sid] = (junction._beats, now)
        stat_count(self.app_runtime.app_context,
                   "resilience.worker_restarts")
        return True

    # ------------------------------------------------------ peer recovery

    def notify_error(self, junction, error: Exception) -> None:
        """Called by ``StreamJunction.handle_error`` for every processing
        error; reacts (once) to cluster-peer failures."""
        from siddhi_tpu.resilience import stat_count

        if not is_peer_failure(error):
            return
        stat_count(self.app_runtime.app_context,
                   "resilience.peer_failures")
        if self.peer_recovery is None:
            return
        with self._lock:
            if self._recovering.is_set():
                return
            self._recovering.set()
        threading.Thread(target=self._recover, daemon=True,
                         name=f"peer-recovery-{self.app_runtime.name}"
                         ).start()

    def _recover(self) -> None:
        try:
            self.recovery_result = self.peer_recovery.recover(
                old_runtime=self.app_runtime)
        except Exception:                         # noqa: BLE001
            log.exception("peer recovery failed")
        finally:
            self._recovered.set()

    def wait_recovered(self, timeout: Optional[float] = None):
        """Block until a triggered peer recovery finished; returns the
        ``(new_runtime, revision)`` result, or None."""
        self._recovered.wait(timeout)
        return self.recovery_result
