"""Resilience subsystem: supervised recovery, replayable ingest, faults.

The reference engine survives worker and connection failure through
periodic state persistence plus source retry/reconnect
(``stream/input/source/Source.java:155-185``). This package is the
TPU-native completion of that story, built around the fact that dense
array state makes full snapshots O(state) (SURVEY.md §5.4) — so the only
missing pieces for effectively-once recovery are a bounded host-side
replay log and a supervisor that drives the protocol:

- ``retry``:      shared exponential-backoff policy (sources, sinks,
                  peer transport) — the ``connectWithRetry`` philosophy.
- ``replay``:     per-stream bounded ingest WAL recorded at the
                  ``InputHandler`` boundary, trimmed at every checkpoint,
                  replayed after ``restore_revision``.
- ``supervisor``: heartbeats ``@Async`` junction workers and cluster
                  peers; restarts dead workers with their queues intact;
                  executes the peer-death recovery protocol promised in
                  ``parallel/distributed.py`` (tear down → re-form cluster
                  with survivors → restore last revision → replay WAL →
                  resume feeds).
- ``faults``:     deterministic fault injection (kill a junction worker,
                  drop a peer, fail the Nth sink publish, delay a device
                  step, flood a stream) for the resilience test suite.
- ``overload``:   per-app ingest quotas with shed-policy backpressure
                  (block / shed_oldest / shed_newest), weighted fair
                  scheduling across tenant apps, and a device-memory
                  budget gating every capacity-growth site.
"""

from siddhi_tpu.resilience.faults import FaultInjector, WorkerKilled
from siddhi_tpu.resilience.overload import (
    AppOverloadControl,
    OverloadConfig,
    OverloadManager,
)
from siddhi_tpu.resilience.replay import IngestWAL
from siddhi_tpu.resilience.retry import RetryPolicy
from siddhi_tpu.resilience.supervisor import (
    AppSupervisor,
    PeerMonitor,
    PeerRecovery,
)

__all__ = [
    "AppOverloadControl",
    "AppSupervisor",
    "FaultInjector",
    "IngestWAL",
    "OverloadConfig",
    "OverloadManager",
    "PeerMonitor",
    "PeerRecovery",
    "RetryPolicy",
    "WorkerKilled",
]


def stat_count(app_context, name: str, n: int = 1) -> None:
    """Bump a recovery counter on the app's StatisticsManager (no-op when
    statistics are not configured). Resilience events are rare and
    operationally load-bearing, so they count at every level above OFF."""
    sm = getattr(app_context, "statistics_manager", None)
    if sm is not None and getattr(sm, "level", 0) > 0:
        sm.count(name, n)
