"""Deterministic fault injection for the resilience test suite.

Every injection is an explicit hook at a point the production code already
owns — no monkeypatching of framework internals from tests:

- junction workers poll an optional ``fault_hook`` at the top of each
  drain iteration (``core/stream/junction.py``): a hook can raise
  (simulated worker crash) or block (simulated wedge);
- ``parallel/distributed.guarded_pull`` consults a module-level fault
  slot before waiting (simulated peer death);
- sink publishes go through the Sink SPI object, which the injector
  wraps to fail the first N calls with the transport's own
  ``ConnectionUnavailableException``.

All injections are one-shot or counted, so tests are deterministic; the
injector restores everything it touched on ``clear()``.
"""

from __future__ import annotations

import threading
from typing import Optional


class WorkerKilled(Exception):
    """Raised inside a junction worker by ``kill_worker`` — simulates the
    worker thread dying mid-drain (the junction treats ANY exception out
    of the fault hook as a worker death)."""


class FaultInjector:  # graftlint: disable=R8 — deterministic test
    # tooling: arming happens on the test thread before the faulted
    # component runs, and every injection is one-shot or counted; the
    # bookkeeping lists are never touched by two threads at once
    def __init__(self):
        self._wedge_release = threading.Event()
        self._wedged = threading.Event()
        self._patched_sinks = []          # (sink, original_publish)
        self._peer_fault_armed = False
        self._flood_threads = []          # non-blocking flood producers
        self._delayed_junctions = []      # persistent delay_worker targets
        self._delayed_pools = []          # armed ingest pack pools

    # ------------------------------------------------- junction workers

    def kill_worker(self, junction) -> None:
        """Arm a one-shot crash: the next drain iteration raises
        ``WorkerKilled`` and the worker thread exits. Any in-flight batch
        stays parked on the junction for the replacement worker."""
        def hook(j):
            j.fault_hook = None
            raise WorkerKilled(f"injected kill on junction "
                               f"'{j.definition.id}'")

        junction.fault_hook = hook

    def wedge_worker(self, junction) -> None:
        """Arm a one-shot wedge: the next drain iteration blocks until
        ``release()``. The thread stays alive but stops heartbeating —
        exactly the failure the supervisor's beat-stall detector targets.
        A released stale worker exits on its generation check without
        touching the queue."""
        self._wedge_release.clear()
        self._wedged.clear()

        def hook(j):
            j.fault_hook = None
            self._wedged.set()
            self._wedge_release.wait()

        junction.fault_hook = hook

    def wait_wedged(self, timeout: float = 10.0) -> bool:
        """Block until a wedged worker actually entered the wedge."""
        return self._wedged.wait(timeout)

    def release(self) -> None:
        """Wake every worker currently blocked in a wedge hook."""
        self._wedge_release.set()

    def delay_worker(self, junction, seconds: float,
                     persistent: bool = False) -> None:
        """Arm a delivery delay (a slow device step seen from the
        junction's side): the next drain iteration sleeps ``seconds``.
        ``persistent=True`` keeps the delay armed on EVERY iteration —
        the deterministic way to make the @Async queue the bottleneck
        (the critical-path profiler's queue-attribution tests plant
        exactly this); disarmed by :meth:`clear`."""
        import time

        if persistent:
            def hook(j):
                time.sleep(seconds)

            self._delayed_junctions.append(junction)
        else:
            def hook(j):
                j.fault_hook = None
                time.sleep(seconds)

        junction.fault_hook = hook

    # ---------------------------------------------- ingest pack-pool workers

    def kill_packer(self, pool) -> None:
        """Arm a one-shot crash on the ingest pack pool
        (``core/stream/input/pack_pool.py``): the next sub-batch task's
        worker dies mid-claim — the merging thread re-packs that
        sub-batch inline (never lost) and the pool/supervisor respawn
        the thread."""
        def hook(p):
            p.fault_hook = None
            raise WorkerKilled("injected kill on ingest pack worker")

        pool.fault_hook = hook
        self._delayed_pools.append(pool)

    def delay_packer(self, pool, seconds: float) -> None:
        """Arm a one-shot delivery delay on ONE ingest pack worker: the
        next sub-batch completes ``seconds`` late, forcing out-of-order
        sub-batch completion — the scenario the pool's ordered merge
        must absorb bit-identically."""
        import time as _time

        def hook(p):
            p.fault_hook = None
            _time.sleep(seconds)

        pool.fault_hook = hook
        self._delayed_pools.append(pool)

    def delay_stage(self, stage: str, seconds: float) -> None:
        """Plant a persistent service delay inside an instrumented
        batch-journey stage (``observability/journey.py`` — ``'pack'``
        today): every ``HostBatch`` pack sleeps ``seconds`` while
        journey tracing is enabled, making that stage the known
        bottleneck the critical-path report must name. Disarmed by
        :meth:`clear`."""
        from siddhi_tpu.observability import journey

        journey.inject_delay(stage, seconds)

    def flood_stream(self, junction, ratio: float = 10.0,
                     base_events: Optional[int] = None,
                     make_data=None, chunk: int = 256,
                     block: bool = True):
        """Deterministic overload injection: publish ``ratio ×`` the
        junction's @Async buffer size (or ``ratio × base_events``) events
        through ``junction.send_events`` — the exact path real producers
        use, so quota admission, shed policies, and backpressure all
        engage (``resilience/overload.py``). The soak tool
        (``tools/overload_soak.py``) and the tests share this one
        injection path, alongside kill/wedge/delay.

        ``make_data(i)`` supplies each event's data row; the default
        synthesizes one from the stream definition's attribute types.
        ``block=True`` sends inline and returns the event count;
        ``block=False`` floods from a daemon thread and returns it (the
        caller joins) — the producer-blocking case IS the scenario some
        tests flood for. Events are timestamped by the app clock."""
        import time as _time

        from siddhi_tpu.core.event import Event
        from siddhi_tpu.query_api.definitions import AttrType

        q = getattr(junction, "_queue", None)
        base = (base_events if base_events is not None
                else (q.maxsize if q is not None and q.maxsize > 0
                      else 1024))
        total = max(int(ratio * base), 1)
        if make_data is None:
            attrs = junction.definition.attributes

            def make_data(i, _attrs=attrs):
                row = []
                for a in _attrs:
                    if a.type == AttrType.STRING:
                        row.append(f"f{i % 8}")
                    elif a.type in (AttrType.FLOAT, AttrType.DOUBLE):
                        row.append(float(i))
                    elif a.type == AttrType.BOOL:
                        row.append(bool(i % 2))
                    else:
                        row.append(i)
                return row

        def _flood():
            tsg = junction.app_context.timestamp_generator
            sent = 0
            while sent < total:
                n = min(chunk, total - sent)
                now = tsg.current_time()
                junction.send_events([
                    Event(timestamp=now, data=make_data(sent + k))
                    for k in range(n)])
                sent += n
            return sent

        if block:
            return _flood()
        t = threading.Thread(target=_flood, daemon=True,
                             name=f"flood-{junction.definition.id}")
        t.start()
        self._flood_threads.append(t)
        return t

    # ------------------------------------------------------ cluster peers

    def drop_peer(self, what: str = "injected peer death") -> None:
        """Make every subsequent ``guarded_pull`` raise ``ClusterPeerError``
        immediately — a peer process presumed dead without waiting out the
        pull timeout. Cleared by ``restore_peer()``/``clear()``."""
        from siddhi_tpu.parallel import distributed

        def hook(label):
            raise distributed.ClusterPeerError(
                f"{label}: {what} — restart the cluster and restore from "
                f"the last snapshot revision")

        distributed._fault_hook = hook
        self._peer_fault_armed = True

    def restore_peer(self) -> None:
        from siddhi_tpu.parallel import distributed

        distributed._fault_hook = None
        self._peer_fault_armed = False

    # -------------------------------------------------------------- sinks

    def fail_publishes(self, sink, n: int = 1,
                       exception: Optional[Exception] = None) -> None:
        """Fail the next ``n`` ``publish`` calls on this Sink with
        ``ConnectionUnavailableException`` (or the given exception), then
        pass through — the shape of a transport blip the retry policy must
        absorb."""
        from siddhi_tpu.core.stream.input.source import (
            ConnectionUnavailableException,
        )

        original = sink.publish
        box = {"left": int(n)}

        def publish(payload):
            if box["left"] > 0:
                box["left"] -= 1
                raise (exception if exception is not None
                       else ConnectionUnavailableException(
                           "injected publish failure"))
            return original(payload)

        sink.publish = publish
        self._patched_sinks.append((sink, original))

    # ------------------------------------------------------------ cleanup

    def clear(self) -> None:
        self.release()
        if self._peer_fault_armed:
            self.restore_peer()
        for sink, original in self._patched_sinks:
            sink.publish = original
        self._patched_sinks.clear()
        for t in self._flood_threads:
            t.join(timeout=10)
        self._flood_threads.clear()
        for j in self._delayed_junctions:
            j.fault_hook = None
        self._delayed_junctions.clear()
        for p in self._delayed_pools:
            p.fault_hook = None
        self._delayed_pools.clear()
        from siddhi_tpu.observability import journey

        journey.clear_delays()
