"""Cluster router: ingest sequencing, key partitioning, ordered egress.

The front-door process of the fabric. One ``ClusterRuntime`` owns:

- an **ingest socket** accepting the PR-13 zero-copy columnar wire
  format (clients open with the wire hello; every frame is decoded with
  ``np.frombuffer`` views against a router-side per-app
  ``StringDictionary``, acked with a ``CTRL_SEQ_ACK`` carrying the
  assigned global sequence);
- the **global ingest sequence**: every accepted batch is stamped, then
  split into maximal contiguous same-owner row runs by
  ``crc32(key) % n_workers`` — the same owner-by-modulus convention
  device routing uses in-process (``parallel/mesh.py``), generalized
  from PanJoin's partition directories to worker processes;
- one **worker link** per worker process: a ``RelayEncoder`` per
  (app, stream) keeps the dictionary-delta state of that link, and a
  router-side per-worker ``IngestWAL`` (resilience/replay.py) records
  every run SENT — the worker itself holds no log, so a kill loses
  nothing the router cannot resend;
- the **ordered egress merger** (``egress.py``): emissions re-merge
  into exact global (seq, run) order with a deterministic heapq stitch;
- **checkpoint barriers**: quiesce (every outstanding run acked), send
  ``CTRL_CHECKPOINT_CUT`` to all workers, collect their persisted
  revisions, then cut + trim each worker WAL — the PR-6 shard
  checkpoint protocol, across processes;
- **recovery** (with ``cluster/supervisor.py``): a respawned worker is
  re-deployed with ``restore=true``, its WAL suffix replayed with the
  ORIGINAL tags, and its key range resumed; the egress merger's
  completed-tag set absorbs the duplicate emissions.

Split vs pinned deployment: an app whose every input stream has a
declared partition key is SPLIT row-wise across all workers (exact for
the key-local query class — partitioned queries, GK==PK aggregations —
the same eligibility class device routing supports); an app with no
partition keys is PINNED whole to ``crc32(app_name) % n`` (exact for
ANY app — this is how a fleet hosts a mixed app population, ROADMAP
item 6's per-process app mix).
"""

from __future__ import annotations

import re
import socket
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from siddhi_tpu.analysis.guards import guarded
from siddhi_tpu.analysis.locks import make_lock
from siddhi_tpu.cluster import protocol as P
from siddhi_tpu.cluster.egress import OrderedEgress
from siddhi_tpu.cluster.protocol import RelayEncoder, encode_for_link
from siddhi_tpu.core.event import StringDictionary
from siddhi_tpu.core.stream.input.wire import (
    CAP_CONTROL, CAP_DICT_DELTA, CTRL_CHECKPOINT_CUT, CTRL_SEQ_ACK,
    DecoderRegistry, WireEncoder, decode_control, decode_frame,
    encode_control, encode_hello, negotiate_hello)
from siddhi_tpu.query_api.definitions import (
    Attribute, AttrType, StreamDefinition)
from siddhi_tpu.resilience.replay import IngestWAL

_APP_NAME = re.compile(r"@app:name\(\s*['\"]([^'\"]+)['\"]\s*\)")


def _count(name: str, n: int = 1) -> None:
    from siddhi_tpu.observability.telemetry import global_registry

    global_registry().count(name, n)


def owner_of_key(value, n_workers: int) -> int:
    """The fabric's owner-by-modulus convention: ``crc32(key) % n``."""
    return zlib.crc32(str(value).encode("utf-8")) % n_workers


@guarded
class _WorkerLink:
    """Router-side state of one worker process' link."""

    # `up`/`acked_seq`/`last_heartbeat` stay undeclared: they are
    # lock-free liveness probes read by gauges and status snapshots
    GUARDED_BY = {"encoders": "link", "tags": "link"}

    def __init__(self, idx: int, wal_batches: int):
        self.idx = idx
        self.sock: Optional[P.MessageSocket] = None
        self.up = False
        self.ready = threading.Event()       # cleared while down/recovering
        self._lock = make_lock("link")       # serializes send vs recovery
        self.wal = IngestWAL(max_batches=wal_batches)
        self.tags: Dict[int, Tuple[Tuple[int, int], str, str]] = {}
        self.encoders: Dict[Tuple[str, str], RelayEncoder] = {}
        self.apps = set()
        self.deploy_waits: Dict[str, tuple] = {}   # app -> (Event, box)
        self.barrier_waits: Dict[int, tuple] = {}  # barrier -> (Event, box)
        self.last_heartbeat = 0.0
        self.acked_seq = 0
        self.sent_runs = 0
        self.pid: Optional[int] = None
        self.hb_port: Optional[int] = None

    def trim_tags(self, cut: int) -> None:
        """Drop WAL-tag entries a checkpoint cut has covered."""
        with self._lock:
            self.tags = {s: t for s, t in self.tags.items() if s > cut}

    def invalidate_session_locked(self) -> None:
        """Caller holds this link's lock (rank ``link``)."""
        self.up = False
        self.ready.clear()
        self.encoders = {}
        # a deploy/barrier waiter must not hang on a dead link
        for ev, box in list(self.deploy_waits.values()):
            box.setdefault("error", "worker link lost")
            ev.set()
        for ev, box in list(self.barrier_waits.values()):
            box.setdefault("error", "worker link lost")
            ev.set()


class _AppSpec:
    """One deployed app as the router sees it."""

    def __init__(self, name: str, text: str, sinks: List[str],
                 partition_keys: Optional[Dict[str, str]],
                 config: Optional[dict], n_workers: int):
        self.name = name
        self.text = text
        self.sinks = list(sinks)
        self.partition_keys = dict(partition_keys or {})
        self.config = dict(config) if config else None
        self.mode = "split" if self.partition_keys else "pinned"
        self.home = owner_of_key(name, n_workers)
        self.workers = (list(range(n_workers)) if self.mode == "split"
                        else [self.home])
        self.dictionary = StringDictionary()
        self.definitions: Dict[str, StreamDefinition] = {}
        self.string_attrs: Dict[str, frozenset] = {}
        # partition attr per stream: (attr_name, is_string)
        self.part_attr: Dict[str, Tuple[str, bool]] = {}
        # router-id -> owner cache (string keys) / value -> owner cache
        self.owner_lut = np.full(0, -1, np.int64)
        self.owner_cache: Dict[object, int] = {}
        self.encoder = WireEncoder()     # in-process loopback framing

    def learn_definitions(self, streams: Dict[str, list]) -> None:
        for sid, attrs in streams.items():
            if sid in self.definitions:
                continue
            d = StreamDefinition(sid, attributes=[
                Attribute(n, AttrType[t]) for n, t in attrs])
            self.definitions[sid] = d
            self.string_attrs[sid] = frozenset(
                a.name for a in d.attributes
                if a.type == AttrType.STRING)
        for sid, key in self.partition_keys.items():
            d = self.definitions.get(sid)
            if d is None:
                raise ValueError(
                    f"partition key declared for unknown stream "
                    f"'{sid}' of app '{self.name}'")
            kinds = {a.name: a.type for a in d.attributes}
            if key not in kinds:
                raise ValueError(
                    f"partition key '{key}' is not an attribute of "
                    f"stream '{sid}'")
            self.part_attr[sid] = (key, kinds[key] == AttrType.STRING)


@guarded
class ClusterRuntime:
    """The router process' in-process handle on the whole fabric."""

    GUARDED_BY = {
        "_seq": "cluster_ingest", "_barrier_id": "cluster_ingest",
        "_conn_seq": "router", "_qid": "router",
        "_query_waits": "router", "apps": "router",
    }

    def __init__(self, n_workers: Optional[int] = None,
                 config: Optional[dict] = None,
                 persist_root: Optional[str] = None,
                 heartbeat_s: Optional[float] = None,
                 checkpoint_s: Optional[float] = None,
                 wal_batches: Optional[int] = None,
                 spawn: bool = True):
        from siddhi_tpu.core.util.config import InMemoryConfigManager
        from siddhi_tpu.core.util.knobs import read_knob

        cm = InMemoryConfigManager(config) if config else None
        self.n_workers = int(
            n_workers if n_workers is not None
            else (read_knob(cm, "cluster_workers") or 2))
        if self.n_workers < 1:
            raise ValueError("ClusterRuntime needs n_workers >= 1")
        self.heartbeat_s = float(
            heartbeat_s if heartbeat_s is not None
            else (read_knob(cm, "cluster_heartbeat_s") or 0.5))
        self.checkpoint_s = float(
            checkpoint_s if checkpoint_s is not None
            else (read_knob(cm, "cluster_checkpoint_s") or 0.0))
        self._wal_batches = int(
            wal_batches if wal_batches is not None
            else (read_knob(cm, "cluster_wal_batches") or 4096))

        self.egress = OrderedEgress()
        self.apps: Dict[str, _AppSpec] = {}
        self.links = [_WorkerLink(i, self._wal_batches)
                      for i in range(self.n_workers)]
        self._ingest_lock = make_lock("cluster_ingest")  # global sequencing
        self._seq = 0
        self._barrier_id = 0
        self._qid = 0
        self._query_waits: Dict[int, tuple] = {}
        self._closing = False
        self._lock = make_lock("router")

        # worker-link listener
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        threading.Thread(target=self._accept_workers, daemon=True,
                         name="cluster-router-accept").start()

        # ingest front door (wire frames from external clients)
        self._ingest_sock = socket.socket(socket.AF_INET,
                                          socket.SOCK_STREAM)
        self._ingest_sock.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_REUSEADDR, 1)
        self._ingest_sock.bind(("127.0.0.1", 0))
        self._ingest_sock.listen(64)
        self.ingest_port = self._ingest_sock.getsockname()[1]
        self._ingest_registry = DecoderRegistry()
        self._conn_seq = 0
        threading.Thread(target=self._accept_ingest, daemon=True,
                         name="cluster-router-ingest").start()

        self._register_gauges()

        self.supervisor = None
        if spawn:
            from siddhi_tpu.cluster.supervisor import WorkerSupervisor

            self.supervisor = WorkerSupervisor(
                self, persist_root=persist_root,
                heartbeat_s=self.heartbeat_s)
            self.supervisor.start()
        if self.checkpoint_s > 0:
            threading.Thread(target=self._auto_checkpoint, daemon=True,
                             name="cluster-router-checkpoint").start()

    # ------------------------------------------------------------ telemetry

    def _register_gauges(self) -> None:
        from siddhi_tpu.observability.telemetry import global_registry

        g = global_registry()
        g.gauge("cluster.workers.live",
                lambda: sum(1 for li in self.links if li.up))
        for link in self.links:
            g.gauge(f"cluster.worker.acked_seq.{link.idx}",
                    lambda li=link: li.acked_seq)
            g.gauge(f"cluster.worker.wal_batches.{link.idx}",
                    lambda li=link: len(li.wal))

    def _remove_gauges(self) -> None:
        from siddhi_tpu.observability.telemetry import global_registry

        g = global_registry()
        g.remove_gauge("cluster.workers.live")
        for link in self.links:
            g.remove_gauge(f"cluster.worker.acked_seq.{link.idx}")
            g.remove_gauge(f"cluster.worker.wal_batches.{link.idx}")

    # ------------------------------------------------------- worker links

    def _accept_workers(self) -> None:
        while not self._closing:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._attach_worker, args=(conn,),
                             daemon=True,
                             name="cluster-router-attach").start()

    def _attach_worker(self, conn: socket.socket) -> None:
        try:
            msock = P.MessageSocket(conn)
            mtype, body = msock.recv() or (None, b"")
            if mtype != P.MSG_HELLO:
                msock.close()
                return
            negotiate_hello(body, required=CAP_CONTROL | CAP_DICT_DELTA)
            msock.send(P.MSG_HELLO, encode_hello())
            mtype, body = msock.recv() or (None, b"")
            if mtype != P.MSG_HELLO:
                msock.close()
                return
            info = P.jload(decode_control(body).body)
            idx = int(info["index"])
            link = self.links[idx]
        except (P.ProtocolError, OSError, ValueError, KeyError,
                IndexError) as e:
            print(f"[cluster-router] rejected worker link: {e}",
                  flush=True)
            try:
                conn.close()
            except OSError:
                pass
            return
        with self._lock:
            link.sock = msock
            link.pid = info.get("pid")
            link.hb_port = info.get("hb_port")
            link.last_heartbeat = time.monotonic()
            reconnect = bool(link.apps)
            # on reconnect `up` stays false until recovery has replayed
            # the WAL suffix (under the session lock) — a fresh send must
            # never overtake the replay
            link.up = not reconnect
        threading.Thread(target=self._reader, args=(link, msock),
                         daemon=True,
                         name=f"cluster-router-reader-{link.idx}").start()
        if reconnect:
            threading.Thread(target=self._recover_worker, args=(link,),
                             daemon=True,
                             name=f"cluster-recover-{link.idx}").start()
        else:
            link.ready.set()
        if self.supervisor is not None:
            self.supervisor.worker_attached(link.idx)

    def _reader(self, link: _WorkerLink, msock: P.MessageSocket) -> None:
        while True:
            try:
                msg = msock.recv()
            except P.ProtocolError:
                msg = None
            if msg is None:
                break
            mtype, body = msg
            if mtype == P.MSG_EMIT:
                e = P.jload(body)
                tag = (int(e["seq"]), int(e["run"]))
                rows = [(int(ts), vals) for ts, vals in e["rows"]]
                if self.egress.emit(tag, e["app"], e["stream"], rows):
                    _count("cluster.egress_rows", len(rows))
                else:
                    _count("cluster.duplicate_emits_dropped")
            elif mtype == P.MSG_ACK:
                cf = decode_control(body)
                tag = (cf.b, cf.a)
                link.acked_seq = max(link.acked_seq, cf.b)
                if self.egress.complete(tag):
                    _count("cluster.runs_acked")
            elif mtype == P.MSG_CHECKPOINT_OK:
                cf = decode_control(body)
                waiter = link.barrier_waits.get(cf.b)
                if waiter is not None:
                    ev, box = waiter
                    box.update(P.jload(cf.body))
                    ev.set()
            elif mtype == P.MSG_DEPLOY_OK:
                ok = P.jload(body)
                waiter = link.deploy_waits.get(ok.get("app"))
                if waiter is not None:
                    ev, box = waiter
                    box.update(ok)
                    ev.set()
            elif mtype == P.MSG_QUERY_RESULT:
                r = P.jload(body)
                with self._lock:
                    waiter = self._query_waits.get(r.get("qid"))
                if waiter is not None:
                    ev, box, pending = waiter
                    box[link.idx] = r
                    pending.discard(link.idx)
                    if not pending:
                        ev.set()
            elif mtype == P.MSG_HEARTBEAT:
                link.last_heartbeat = time.monotonic()
            elif mtype == P.MSG_ERROR:
                print(f"[cluster-router] worker {link.idx} error: "
                      f"{P.jload(body)}", flush=True)
        with link._lock:
            with self._lock:
                if link.sock is msock and not self._closing:
                    link.invalidate_session_locked()
                    _count(f"cluster.worker.link_drops.{link.idx}")
                    if self.supervisor is not None:
                        self.supervisor.worker_lost(link.idx)

    # ---------------------------------------------------------- deployment

    def deploy(self, text: str, name: Optional[str] = None,
               partition_keys: Optional[Dict[str, str]] = None,
               sinks: Optional[List[str]] = None,
               config: Optional[dict] = None,
               timeout: float = 60.0) -> _AppSpec:
        """Deploy one SiddhiQL app on the fabric. ``partition_keys``
        ({input stream: key attribute}) selects SPLIT mode; without it
        the whole app is PINNED to one worker. ``sinks`` lists the
        output streams whose emissions flow back through the ordered
        egress."""
        if name is None:
            m = _APP_NAME.search(text)
            if m is None:
                raise ValueError("deploy needs name= (or an @app:name "
                                 "annotation in the app text)")
            name = m.group(1)
        with self._lock:
            if name in self.apps:
                raise ValueError(f"app '{name}' is already deployed")
        app = _AppSpec(name, text, sinks or [], partition_keys, config,
                       self.n_workers)
        for idx in app.workers:
            if not self.links[idx].ready.wait(timeout):
                raise TimeoutError(f"worker {idx} never came up")
        first_box = None
        for idx in app.workers:
            box = self._deploy_on(self.links[idx], app, restore=False,
                                  timeout=timeout)
            if first_box is None:
                first_box = box
        app.learn_definitions(first_box.get("streams", {}))
        with self._lock:
            self.apps[name] = app
        return app

    def _deploy_on(self, link: _WorkerLink, app: _AppSpec,
                   restore: bool, timeout: float) -> dict:
        ev, box = threading.Event(), {}
        link.deploy_waits[app.name] = (ev, box)
        try:
            link.sock.send(P.MSG_DEPLOY, P.jdump({
                "app": app.name, "text": app.text, "sinks": app.sinks,
                "config": app.config, "restore": restore}))
            if not ev.wait(timeout):
                raise TimeoutError(
                    f"worker {link.idx} did not ack deploy of "
                    f"'{app.name}'")
        finally:
            link.deploy_waits.pop(app.name, None)
        if box.get("error"):
            raise RuntimeError(
                f"worker {link.idx} failed to deploy '{app.name}': "
                f"{box['error']}")
        link.apps.add(app.name)
        return box

    # -------------------------------------------------------------- ingest

    def send_columns(self, app_name: str, stream: str,
                     data: Dict[str, np.ndarray], timestamps=None) -> int:
        """In-process ingest: frames through the app's loopback encoder
        so BOTH ingest paths (socket and in-process) share one decode +
        split + relay pipeline. Returns the assigned global sequence."""
        with self._lock:
            app = self.apps[app_name]
        frame = app.encoder.encode(
            dict(data), timestamps=timestamps)
        return self._ingest_frame(app, stream, frame,
                                  scope=(app_name, "@local"))

    def _ingest_frame(self, app: _AppSpec, stream: str, frame: bytes,
                      scope) -> int:
        d = app.definitions.get(stream)
        if d is None:
            raise KeyError(f"app '{app.name}' has no stream '{stream}'")
        data, ts = decode_frame(frame, d, app.dictionary,
                                self._ingest_registry, scope=scope)
        n_rows = 0
        for v in data.values():
            n_rows = len(v)
            break
        with self._ingest_lock:
            self._seq += 1
            seq = self._seq
            _count("cluster.ingest_batches")
            _count("cluster.ingest_rows", n_rows)
            for run, (widx, rdata, rts) in enumerate(
                    self._split_runs(app, stream, data, ts)):
                tag = (seq, run)
                self.egress.expect(tag)
                self._send_run(self.links[widx], tag, app, stream,
                               rdata, rts)
        return seq

    def _split_runs(self, app: _AppSpec, stream: str, data, ts):
        """Maximal contiguous same-owner row runs, in row order."""
        if app.mode == "pinned" or not data:
            yield app.home, data, ts
            return
        part = app.part_attr.get(stream)
        if part is None:
            raise ValueError(
                f"split app '{app.name}' has no partition key for "
                f"stream '{stream}' — declare it in partition_keys")
        owners = self._owners_of(app, data[part[0]], part[1])
        if len(owners) == 0:
            yield app.home, data, ts
            return
        cuts = np.flatnonzero(np.diff(owners)) + 1
        bounds = np.concatenate(([0], cuts, [len(owners)]))
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            lo, hi = int(lo), int(hi)
            rdata = {k: v[lo:hi] for k, v in data.items()}
            rts = ts[lo:hi] if ts is not None else None
            yield int(owners[lo]), rdata, rts

    def _owners_of(self, app: _AppSpec, col, is_string: bool):
        col = np.asarray(col)
        if is_string:
            ids = col.astype(np.int64)
            hi = int(ids.max(initial=-1))
            if hi >= len(app.owner_lut):
                grown = np.full(hi + 1, -1, np.int64)
                grown[:len(app.owner_lut)] = app.owner_lut
                app.owner_lut = grown
            valid = ids >= 0
            safe = np.where(valid, ids, 0)
            for rid in np.unique(safe[valid & (app.owner_lut[safe] < 0)]
                                 ) if valid.any() else ():
                app.owner_lut[int(rid)] = owner_of_key(
                    app.dictionary.decode(int(rid)), self.n_workers)
            return np.where(valid, app.owner_lut[safe], 0)
        owners = np.empty(len(col), np.int64)
        cache = app.owner_cache
        for i, v in enumerate(col):
            key = v.item() if isinstance(v, np.generic) else v
            o = cache.get(key)
            if o is None:
                o = cache[key] = owner_of_key(key, self.n_workers)
            owners[i] = o
        return owners

    def _send_run(self, link: _WorkerLink, tag, app: _AppSpec,
                  stream: str, data, ts, record: bool = True) -> None:
        # the WAL record and its tag must land under the link lock:
        # recovery iterates `link.tags` under the same lock, and an
        # ingest racing a replay must not mutate the dict mid-iteration
        with link._lock:
            if record:
                wal_seq = link.wal.record_columns(stream, data,
                                                  timestamps=ts)
                link.tags[wal_seq] = (tag, app.name, stream)
            if not link.up:
                return          # down: the WAL replay will deliver it
            try:
                self._relay(link, tag, app, stream, data, ts)
            except OSError:
                with self._lock:
                    if not self._closing:
                        link.invalidate_session_locked()
                        if self.supervisor is not None:
                            self.supervisor.worker_lost(link.idx)

    def _relay(self, link: _WorkerLink, tag, app: _AppSpec, stream: str,
               data, ts) -> None:
        enc = link.encoders.get((app.name, stream))
        if enc is None:
            enc = link.encoders[(app.name, stream)] = \
                RelayEncoder(app.dictionary)
        frame = encode_for_link(enc, data, app.string_attrs[stream],
                                timestamps=ts)
        link.sock.send(P.MSG_DATA, P.pack_data(
            tag[0], tag[1], app.name, stream, frame))
        link.sent_runs += 1
        _count("cluster.runs_sent")

    # ------------------------------------------------------ ingest socket

    def _accept_ingest(self) -> None:
        while not self._closing:
            try:
                conn, _addr = self._ingest_sock.accept()
            except OSError:
                return
            with self._lock:
                self._conn_seq += 1
                cid = self._conn_seq
            threading.Thread(target=self._serve_ingest,
                             args=(conn, cid), daemon=True,
                             name=f"cluster-ingest-conn-{cid}").start()

    def _serve_ingest(self, conn: socket.socket, cid: int) -> None:
        msock = P.MessageSocket(conn)
        try:
            mtype, body = msock.recv() or (None, b"")
            if mtype != P.MSG_HELLO:
                raise P.ProtocolError("ingest link must open with hello")
            negotiate_hello(body, required=CAP_DICT_DELTA)
            msock.send(P.MSG_HELLO, encode_hello())
            while True:
                msg = msock.recv()
                if msg is None:
                    return
                mtype, body = msg
                if mtype != P.MSG_INGEST:
                    raise P.ProtocolError(
                        f"unexpected message {mtype} on ingest link")
                _s, _r, app_name, stream, frame = P.unpack_data(body)
                with self._lock:
                    app = self.apps.get(app_name)
                if app is None:
                    raise P.ProtocolError(f"unknown app '{app_name}'")
                seq = self._ingest_frame(app, stream, frame,
                                         scope=(app_name, cid))
                msock.send(P.MSG_INGEST_ACK,
                           encode_control(CTRL_SEQ_ACK, b=seq))
        except Exception as e:     # noqa: BLE001 — per-connection scope
            if not self._closing:
                try:
                    msock.send(P.MSG_ERROR, P.jdump(
                        {"context": "ingest", "error": str(e)}))
                except OSError:
                    pass
        finally:
            msock.close()

    # --------------------------------------------------------- checkpoints

    def checkpoint(self, timeout: float = 120.0) -> Dict[int, dict]:
        """One cluster checkpoint barrier: quiesce, cut every worker,
        trim every WAL at its cut. Returns {worker: revisions}."""
        with self._ingest_lock:
            if not self.egress.wait_quiesced(timeout):
                raise TimeoutError(
                    f"checkpoint barrier: "
                    f"{self.egress.outstanding()} runs still outstanding")
            self._barrier_id += 1
            barrier = self._barrier_id
            cuts, waiters, out = {}, {}, {}
            live = [li for li in self.links if li.apps]
            for link in live:
                if not link.ready.wait(timeout):
                    raise TimeoutError(
                        f"checkpoint barrier: worker {link.idx} not up")
                cuts[link.idx] = link.wal.cut()
                ev, box = threading.Event(), {}
                link.barrier_waits[barrier] = (ev, box)
                waiters[link.idx] = (ev, box)
                link.sock.send(P.MSG_CHECKPOINT, encode_control(
                    CTRL_CHECKPOINT_CUT, b=barrier))
            try:
                for link in live:
                    ev, box = waiters[link.idx]
                    if not ev.wait(timeout):
                        raise TimeoutError(
                            f"checkpoint barrier {barrier}: worker "
                            f"{link.idx} never cut")
                    if box.get("error"):
                        raise RuntimeError(
                            f"checkpoint barrier {barrier}: worker "
                            f"{link.idx}: {box['error']}")
            finally:
                for link in live:
                    link.barrier_waits.pop(barrier, None)
            for link in live:
                cut = cuts[link.idx]
                link.wal.trim(cut)
                revs = waiters[link.idx][1].get("revisions", {})
                link.wal.checkpoint_revision = \
                    next(iter(revs.values()), None)
                link.trim_tags(cut)
                out[link.idx] = revs
            _count("cluster.checkpoints")
            return out

    def _auto_checkpoint(self) -> None:
        while not self._closing:
            time.sleep(self.checkpoint_s)
            if self._closing:
                return
            try:
                self.checkpoint()
            except Exception as e:   # noqa: BLE001 — periodic, retried
                print(f"[cluster-router] auto-checkpoint failed: {e}",
                      flush=True)

    # ------------------------------------------------------------ recovery

    def _recover_worker(self, link: _WorkerLink) -> None:
        """The PR-1 protocol, router-driven: re-deploy with restore,
        replay the WAL suffix with ORIGINAL tags, resume the key range."""
        _count(f"cluster.worker.respawns.{link.idx}")
        with link._lock:
            with self._lock:
                apps = dict(self.apps)
            try:
                for app_name in sorted(link.apps):
                    self._deploy_on(link, apps[app_name],
                                    restore=True, timeout=120.0)
                records = link.wal.records_after(0)
                retained = {rec.seq for rec in records}
                # runs the bounded WAL lost to overflow can never
                # complete: surface the gap, release the merge head
                for wal_seq in sorted(link.tags):
                    if wal_seq not in retained:
                        tag, _a, _s = link.tags.pop(wal_seq)
                        self.egress.forget(tag)
                        _count(f"cluster.worker.replay_gaps.{link.idx}")
                # rows the dead incarnation emitted for tags it never
                # acked are about to be regenerated — drop the stale
                # copies BEFORE any re-send
                for rec in records:
                    self.egress.drop_pending(link.tags[rec.seq][0])
                for rec in records:
                    tag, app_name, stream = link.tags[rec.seq]
                    self._relay(link, tag, apps[app_name],
                                rec.stream_id, rec.payload,
                                rec.timestamps)
                    _count(f"cluster.worker.replayed_batches.{link.idx}")
                link.up = True
                link.ready.set()
            except Exception as e:   # noqa: BLE001 — supervisor retries
                print(f"[cluster-router] recovery of worker {link.idx} "
                      f"failed: {e}", flush=True)
                with self._lock:
                    link.invalidate_session_locked()
                    if self.supervisor is not None:
                        self.supervisor.worker_lost(link.idx)

    # --------------------------------------------------------------- query

    def query(self, app_name: str, query_text: str,
              timeout: float = 60.0) -> List[list]:
        """On-demand query, scatter-gathered: a PINNED app answers from
        its one owner; a SPLIT app fans out to every worker and the
        parts re-merge with the PR-6 deterministic stitch
        (serving/cluster_gather.py)."""
        from siddhi_tpu.serving.cluster_gather import gather_query_rows

        with self._lock:
            app = self.apps[app_name]
            self._qid += 1
            qid = self._qid
            targets = [self.links[i] for i in app.workers]
            ev, box, pending = (threading.Event(), {},
                                {li.idx for li in targets})
            self._query_waits[qid] = (ev, box, pending)
        try:
            for link in targets:
                if not link.ready.wait(timeout):
                    raise TimeoutError(f"worker {link.idx} not up")
                link.sock.send(P.MSG_QUERY, P.jdump(
                    {"qid": qid, "app": app_name, "query": query_text}))
            if not ev.wait(timeout):
                raise TimeoutError(
                    f"query fan-out: workers "
                    f"{sorted(pending)} never answered")
        finally:
            with self._lock:
                self._query_waits.pop(qid, None)
        parts = []
        for idx in sorted(box):
            r = box[idx]
            if r.get("error"):
                raise RuntimeError(
                    f"worker {idx} query failed: {r['error']}")
            parts.append(r.get("rows", []))
        _count("cluster.queries")
        return gather_query_rows(parts)

    def status(self) -> dict:
        """JSON-ready fabric status (the REST tier's GET /cluster)."""
        with self._lock:
            app_items = sorted(self.apps.items())
        eg = self.egress.counters()
        eg["outstanding"] = self.egress.outstanding()
        return {
            "workers": self.n_workers,
            "live": sum(1 for li in self.links if li.up),
            "ingest_port": self.ingest_port,
            "apps": {name: {"mode": spec.mode,
                            "workers": sorted(spec.workers),
                            "sinks": list(spec.sinks)}
                     for name, spec in app_items},
            "per_worker": {
                li.idx: {"up": li.up,
                         "acked_seq": li.acked_seq,
                         "wal_batches": len(li.wal),
                         "respawns": (self.supervisor.respawn_count(li.idx)
                                      if self.supervisor else 0)}
                for li in self.links},
            "egress": eg,
        }

    # ------------------------------------------------------------ teardown

    def wait_ready(self, timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        for link in self.links:
            if not link.ready.wait(max(0.0, deadline - time.monotonic())):
                raise TimeoutError(f"worker {link.idx} never came up")

    def quiesce(self, timeout: float = 120.0) -> bool:
        return self.egress.wait_quiesced(timeout)

    def shutdown(self) -> None:
        self._closing = True
        if self.supervisor is not None:
            self.supervisor.stop()
        for link in self.links:
            if link.sock is not None:
                try:
                    link.sock.send(P.MSG_SHUTDOWN)
                except OSError:
                    pass
                link.sock.close()
        for s in (self._sock, self._ingest_sock):
            try:
                s.close()
            except OSError:
                pass
        self._remove_gauges()
