"""Cluster fabric: the wire-speed multi-process distributed tier.

One **router process** owns ingest sequencing, key partitioning and
ordered egress; N **worker processes** each run a full single-process
engine over their key range; a **supervisor** respawns dead workers and
drives the PR-1 recovery protocol (restore last revision + replay the
router-side WAL suffix). See ``router.py`` for the architecture notes
and README "Cluster fabric" for the topology diagram.

Not ``jax.distributed``: plain-CPU XLA refuses multiprocess
computations (see tests/test_multihost.py skips), so the fabric is
plain sockets carrying the PR-13 zero-copy columnar wire format —
which also means it exercises REAL multicore parallelism on hosts
where the TPU tunnel is absent.
"""

from siddhi_tpu.cluster.egress import OrderedEgress
from siddhi_tpu.cluster.router import ClusterRuntime
from siddhi_tpu.cluster.supervisor import WorkerSupervisor

__all__ = ["ClusterRuntime", "OrderedEgress", "WorkerSupervisor"]
