"""Worker fleet supervisor: spawn, probe, respawn.

The process half of the fabric's effectively-once story. Each worker is
a child ``python -m siddhi_tpu.cluster.worker`` process; liveness is the
PR-1 peer-death protocol — every worker binds a ``PeerMonitor``
heartbeat listener (resilience/supervisor.py) whose address it reports
in its link hello, and this supervisor probes all of them each tick. A
worker is presumed dead when EITHER its process exits (``Popen.poll``)
or its heartbeat listener refuses ``misses`` consecutive probes (a
wedged-but-alive process); a dead worker is killed hard, respawned, and
its monitor entry re-armed. The RECOVERY itself (re-deploy + restore +
WAL replay + key-range resume) is the router's job
(``router._recover_worker``) and triggers automatically when the
replacement dials back in — this module only guarantees there is always
a process to dial.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from siddhi_tpu.analysis.guards import guarded
from siddhi_tpu.analysis.locks import make_lock


def _child_env() -> dict:
    """Workers are plain-CPU engines: strip inherited accelerator state
    (a TPU lock or an XLA flag meant for the router must not leak), and
    make the package importable from any cwd (the tree is not
    pip-installed)."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    env["JAX_PLATFORMS"] = "cpu"
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    parts = [root] + [p for p in env.get("PYTHONPATH", "").split(
        os.pathsep) if p]
    env["PYTHONPATH"] = os.pathsep.join(parts)
    return env


@guarded
class WorkerSupervisor:
    """Owns the worker processes of one ``ClusterRuntime``."""

    GUARDED_BY = {
        "procs": "cluster_supervisor", "respawns": "cluster_supervisor",
        "_addrs": "cluster_supervisor", "_held_down": "cluster_supervisor",
    }

    def __init__(self, runtime, persist_root: Optional[str] = None,
                 heartbeat_s: float = 0.5, misses: int = 3,
                 interval_s: float = 0.25):
        from siddhi_tpu.resilience.supervisor import PeerMonitor

        self.runtime = runtime
        self._own_root = persist_root is None
        self.persist_root = persist_root or tempfile.mkdtemp(
            prefix="siddhi-cluster-")
        self.heartbeat_s = float(heartbeat_s)
        self.interval_s = float(interval_s)
        self.monitor = PeerMonitor(probe_timeout_s=0.5, misses=misses)
        n = runtime.n_workers
        self.procs: List[Optional[subprocess.Popen]] = [None] * n
        self.respawns = [0] * n
        self._addrs: Dict[int, Tuple[str, int]] = {}
        self._held_down = set()      # killed on purpose, do not respawn
        self._lock = make_lock("cluster_supervisor")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "WorkerSupervisor":
        for idx in range(self.runtime.n_workers):
            self._spawn(idx)
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="cluster-supervisor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
        with self._lock:
            procs = list(self.procs)
        for proc in procs:
            if proc is not None and proc.poll() is None:
                proc.kill()
        for proc in procs:
            if proc is not None:
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
        self.monitor.close()
        if self._own_root:
            shutil.rmtree(self.persist_root, ignore_errors=True)

    # -------------------------------------------------------------- spawn

    def _spawn(self, idx: int) -> None:
        # the replacement binds a NEW heartbeat port; the old listener's
        # corpse must leave the monitor NOW or its death re-triggers
        # `worker_lost` against the fresh process
        with self._lock:
            old = self._addrs.pop(idx, None)
        if old is not None:
            self.monitor.unwatch(*old)
        store = os.path.join(self.persist_root, f"worker{idx}")
        os.makedirs(store, exist_ok=True)
        cmd = [sys.executable, "-m", "siddhi_tpu.cluster.worker",
               "--connect", f"127.0.0.1:{self.runtime.port}",
               "--index", str(idx),
               "--persist-dir", store,
               "--heartbeat-s", str(self.heartbeat_s)]
        with self._lock:
            self.procs[idx] = subprocess.Popen(cmd, env=_child_env(),
                                               cwd=self.persist_root)

    # ------------------------------------------------- router notifications

    def worker_attached(self, idx: int) -> None:
        """Router callback: worker ``idx`` completed its hello (its
        heartbeat listener address is now known) — arm the probe."""
        hb_port = self.runtime.links[idx].hb_port
        if not hb_port:
            return
        addr = ("127.0.0.1", int(hb_port))
        with self._lock:
            old = self._addrs.get(idx)
            self._addrs[idx] = addr
        # monitor calls stay outside the lock: the PeerMonitor has its
        # own (app_supervisor-ranked) lock and this one must stay a leaf
        if old is not None and old != addr:
            self.monitor.unwatch(*old)
        self.monitor.rearm(*addr)

    def worker_lost(self, idx: int) -> None:
        """Router callback: link EOF or send failure. A live process
        behind a dead link is useless — kill it so the poll loop
        respawns one that can dial back in."""
        with self._lock:
            proc = self.procs[idx]
        if proc is not None and proc.poll() is None:
            proc.kill()

    # ------------------------------------------------------------- control

    def kill(self, idx: int, respawn: bool = True) -> None:
        """Hard-kill worker ``idx`` (tests, soak's mid-run murder). With
        ``respawn=False`` the corpse is held down until ``release``."""
        with self._lock:
            if not respawn:
                self._held_down.add(idx)
            proc = self.procs[idx]
        if proc is not None and proc.poll() is None:
            proc.kill()

    def release(self, idx: int) -> None:
        """Allow a held-down worker to respawn on the next tick."""
        with self._lock:
            self._held_down.discard(idx)

    def respawn_count(self, idx: int) -> int:
        with self._lock:
            return self.respawns[idx]

    # ---------------------------------------------------------- poll loop

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._tick()
            except Exception as e:   # noqa: BLE001 — keep supervising
                print(f"[cluster-supervisor] tick failed: {e}",
                      flush=True)

    def _tick(self) -> None:
        # heartbeat-listener deaths: kill the (possibly wedged) process
        # so the exit check below owns the respawn decision
        dead_addrs = set(self.monitor.poll_dead())
        if dead_addrs:
            with self._lock:
                hit = [idx for idx, addr in self._addrs.items()
                       if addr in dead_addrs]
            for idx in hit:
                self.worker_lost(idx)
        for idx in range(self.runtime.n_workers):
            with self._lock:
                proc = self.procs[idx]
                held = idx in self._held_down
            if held or proc is None or proc.poll() is None:
                continue
            if self._stop.is_set():
                return
            with self._lock:
                self.respawns[idx] += 1
            self._spawn(idx)
