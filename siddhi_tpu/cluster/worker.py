"""Cluster worker process: one full engine over one key range.

Runnable as ``python -m siddhi_tpu.cluster.worker --connect HOST:PORT
--index I --persist-dir DIR --hb-port P``. The worker dials the router,
negotiates the wire hello (version + capability bits), then serves the
router's message loop on a single reader thread — DATA runs are
processed strictly in arrival order, which is what lets the router's
egress merger reconstruct exact global order from per-run completions.

State discipline: the worker holds NO replay log — the router records
every run it sends into a per-worker ``IngestWAL`` (resilience/
replay.py), so a killed worker loses only what the router can resend.
On respawn the router re-deploys with ``restore=true`` (the worker
restores its last persisted revision from its own store directory) and
replays the WAL suffix as ordinary DATA runs; the egress merger drops
the re-emissions of already-merged tags. Liveness is the PR-1 peer-
death protocol: the worker binds a ``PeerMonitor`` heartbeat listener
the router's supervisor probes, plus in-band ``CTRL_HEARTBEAT`` frames
on the link.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
import time


def _parse_args(argv):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--connect", required=True, help="router HOST:PORT")
    ap.add_argument("--index", type=int, required=True)
    ap.add_argument("--persist-dir", required=True)
    ap.add_argument("--hb-port", type=int, default=0,
                    help="PeerMonitor heartbeat listener port")
    ap.add_argument("--heartbeat-s", type=float, default=0.5)
    ap.add_argument("--ready-flag", default=None,
                    help="file to create once the hello is on the wire")
    return ap.parse_args(argv)


class _AppHost:
    """One deployed app on this worker: manager + runtime + sink taps."""

    def __init__(self, name: str, text: str, sinks, store_dir: str,
                 config=None, restore: bool = False):
        from siddhi_tpu.core.manager import SiddhiManager
        from siddhi_tpu.core.stream.output.stream_callback import (
            StreamCallback)
        from siddhi_tpu.core.util.config import InMemoryConfigManager
        from siddhi_tpu.core.util.persistence import (
            FileSystemPersistenceStore)

        self.name = name
        self.emitted = []     # [(stream, ts, [values])] of the CURRENT run
        self.manager = SiddhiManager()
        os.makedirs(store_dir, exist_ok=True)
        self.manager.set_persistence_store(
            FileSystemPersistenceStore(store_dir))
        if config:
            self.manager.set_config_manager(InMemoryConfigManager(config))
        self.runtime = self.manager.create_siddhi_app_runtime(text)

        host = self

        class _Tap(StreamCallback):
            def __init__(self, stream):
                super().__init__()
                self._stream = stream

            def receive(self, events):
                from siddhi_tpu.cluster.protocol import py_value

                host.emitted.extend(
                    (self._stream, int(e.timestamp),
                     [py_value(v) for v in e.data]) for e in events)

        for s in sinks:
            self.runtime.add_callback(s, _Tap(s))
        self.runtime.start()
        self.restored_revision = None
        if restore:
            self.restored_revision = self.runtime.restore_last_revision()
        self.handlers = {}
        self.definitions = {
            sid: j.definition for sid, j in self.runtime.junctions.items()}

    def handler(self, stream: str):
        h = self.handlers.get(stream)
        if h is None:
            h = self.handlers[stream] = \
                self.runtime.get_input_handler(stream)
        return h

    def take_emitted(self):
        out, self.emitted = self.emitted, []
        return out

    def shutdown(self):
        try:
            self.manager.shutdown()
        except Exception:   # noqa: BLE001 — exit path, best effort
            pass


def _serve(args) -> int:
    from siddhi_tpu.cluster import protocol as P
    from siddhi_tpu.core.stream.input.wire import (
        CAP_CONTROL, CAP_DICT_DELTA, CTRL_CHECKPOINT_CUT, CTRL_HEARTBEAT,
        CTRL_SEQ_ACK, DecoderRegistry, decode_control, decode_frame,
        encode_control, encode_hello, negotiate_hello)
    from siddhi_tpu.resilience.supervisor import PeerMonitor

    host, port = args.connect.rsplit(":", 1)
    # the PR-1 liveness listener the router's supervisor probes
    monitor = PeerMonitor(listen_port=args.hb_port)
    sock = socket.create_connection((host, int(port)), timeout=30)
    link = P.MessageSocket(sock)
    link.send(P.MSG_HELLO, encode_hello(
        sender_id=args.index,
        capabilities=CAP_CONTROL | CAP_DICT_DELTA | (1 << 0)))
    mtype, body = link.recv() or (None, b"")
    if mtype != P.MSG_HELLO:
        raise P.ProtocolError(f"router answered {mtype}, expected hello")
    negotiate_hello(body, required=CAP_CONTROL | CAP_DICT_DELTA)
    link.send(P.MSG_HELLO, encode_control(
        1, a=args.index, body=P.jdump({"index": args.index,
                                       "pid": os.getpid(),
                                       "hb_port": monitor.port})))
    if args.ready_flag:
        with open(args.ready_flag, "w") as f:
            f.write("up")

    apps = {}
    registry = DecoderRegistry()
    stop = threading.Event()

    def _heartbeats():
        tick = 0
        while not stop.is_set():
            tick += 1
            try:
                link.send(P.MSG_HEARTBEAT, encode_control(
                    CTRL_HEARTBEAT, a=args.index, b=tick))
            except OSError:
                return              # router gone: the reader exits too
            stop.wait(args.heartbeat_s)

    threading.Thread(target=_heartbeats, daemon=True,
                     name="cluster-worker-heartbeat").start()

    while True:
        msg = link.recv()
        if msg is None:
            break                   # router closed the link: exit
        mtype, body = msg
        if mtype == P.MSG_DEPLOY:
            spec = P.jload(body)
            name = spec["app"]
            try:
                old = apps.pop(name, None)
                if old is not None:
                    old.shutdown()
                apps[name] = _AppHost(
                    name, spec["text"], spec.get("sinks", ()),
                    os.path.join(args.persist_dir, name),
                    config=spec.get("config"),
                    restore=bool(spec.get("restore")))
                link.send(P.MSG_DEPLOY_OK, P.jdump({
                    "app": name,
                    "revision": apps[name].restored_revision,
                    # the router partitions + decodes against these
                    "streams": {
                        sid: [[a.name, a.type.name] for a in d.attributes]
                        for sid, d in apps[name].definitions.items()}}))
            except Exception as e:      # noqa: BLE001 — reported, not fatal
                link.send(P.MSG_DEPLOY_OK, P.jdump({
                    "app": name, "error": f"{type(e).__name__}: {e}"}))
        elif mtype == P.MSG_DATA:
            seq, run, app_name, stream, frame = P.unpack_data(body)
            app = apps[app_name]
            data, ts = decode_frame(
                frame, app.definitions[stream],
                app.runtime.app_context.string_dictionary,
                registry, scope=app_name)
            app.handler(stream).send_columns(data, timestamps=ts)
            # group the run's emissions into maximal same-stream slices
            # (order preserved — the egress merger replays EMITs of one
            # tag in arrival order)
            groups = []
            for out_stream, ets, values in app.take_emitted():
                if groups and groups[-1][0] == out_stream:
                    groups[-1][1].append([ets, values])
                else:
                    groups.append((out_stream, [[ets, values]]))
            for out_stream, rows in groups:
                link.send(P.MSG_EMIT, P.jdump({
                    "seq": seq, "run": run, "app": app_name,
                    "stream": out_stream, "rows": rows}))
            link.send(P.MSG_ACK, encode_control(CTRL_SEQ_ACK, a=run,
                                                b=seq))
        elif mtype == P.MSG_CHECKPOINT:
            cf = decode_control(body)
            revisions = {}
            for name, app in apps.items():
                revisions[name] = app.runtime.persist()
            link.send(P.MSG_CHECKPOINT_OK, encode_control(
                CTRL_CHECKPOINT_CUT, a=args.index, b=cf.b,
                body=P.jdump({"barrier": cf.b, "revisions": revisions})))
        elif mtype == P.MSG_QUERY:
            q = P.jload(body)
            try:
                events = apps[q["app"]].runtime.query(q["query"])
                rows = [[int(getattr(e, "timestamp", 0) or 0),
                         [P.py_value(v) for v in e.data]]
                        for e in events]
                link.send(P.MSG_QUERY_RESULT, P.jdump({
                    "qid": q["qid"], "rows": rows}))
            except Exception as e:      # noqa: BLE001 — reported, not fatal
                link.send(P.MSG_QUERY_RESULT, P.jdump({
                    "qid": q["qid"],
                    "error": f"{type(e).__name__}: {e}"}))
        elif mtype == P.MSG_HEARTBEAT:
            pass                        # router pings are informational
        elif mtype == P.MSG_SHUTDOWN:
            break
        else:
            link.send(P.MSG_ERROR, P.jdump(
                {"context": "dispatch",
                 "error": f"unknown message type {mtype}"}))
    stop.set()
    for app in apps.values():
        app.shutdown()
    monitor.close()
    link.close()
    return 0


def main(argv=None) -> int:
    import gc

    gc.disable()        # GC during jax tracing segfaults this build
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "")

    def _die(tp, v, tb):
        # an uncaught failure must EXIT (and be seen), never park the
        # process half-dead with its heartbeat listener still up
        import traceback

        traceback.print_exception(tp, v, tb)
        sys.stderr.flush()
        os._exit(3)

    sys.excepthook = _die
    args = _parse_args(argv)
    try:
        return _serve(args)
    except (ConnectionError, OSError) as e:
        print(f"[cluster-worker {args.index}] link lost: {e}",
              file=sys.stderr, flush=True)
        return 0


if __name__ == "__main__":
    # os._exit: a half-dead link must never hang in atexit teardown
    os._exit(main())
