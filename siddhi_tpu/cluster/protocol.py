"""Cluster socket protocol: framed messages over plain TCP.

Every link (router↔worker, ingest-client↔router) speaks the same
framing — ``u32 length | u8 type | body`` — and OPENS with the wire
format's hello control frame (``wire.encode_hello``), so version or
capability skew fails at link-open with an error naming both sides,
never as a mid-stream frame-parse error.

Bodies are one of three shapes:

- a wire CONTROL frame (``wire.encode_control``) for the link-
  management vocabulary: hello, heartbeat, seq-ack, checkpoint-cut;
- a DATA envelope — ``u64 seq | u32 run | u16 app_len | app |
  u16 stream_len | stream`` followed by one PR-13 columnar wire frame
  (``wire.WireEncoder``), the zero-copy payload path;
- UTF-8 JSON for low-rate structured control (deploy specs, query
  scatter/gather, worker emissions).

The ``RelayEncoder`` is the router's re-framing half: it re-encodes a
decoded batch (string columns already translated to ROUTER dictionary
ids) for one worker link with a vectorized router-id→client-id LUT —
no per-row Python on the relay path, same discipline as the decode
side's one-gather translation.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from siddhi_tpu.core.stream.input.wire import WireEncoder

# ---------------------------------------------------------- message types

MSG_HELLO = 1            # body: wire hello control frame (JSON in body)
MSG_DEPLOY = 2           # JSON: app text + routing spec (+ restore flag)
MSG_DEPLOY_OK = 3        # JSON: {app} (or {app, error})
MSG_DATA = 4             # data envelope + wire frame (router -> worker)
MSG_EMIT = 5             # JSON: one run's output rows (worker -> router)
MSG_ACK = 6              # wire CTRL_SEQ_ACK frame: a=run, b=seq
MSG_CHECKPOINT = 7       # wire CTRL_CHECKPOINT_CUT frame: b=barrier id
MSG_CHECKPOINT_OK = 8    # CTRL_CHECKPOINT_CUT frame, body JSON revisions
MSG_QUERY = 9            # JSON: {qid, app, query}
MSG_QUERY_RESULT = 10    # JSON: {qid, rows} | {qid, error}
MSG_HEARTBEAT = 11       # wire CTRL_HEARTBEAT frame
MSG_ERROR = 12           # JSON: {context, error} (worker -> router)
MSG_SHUTDOWN = 13        # empty body: orderly worker exit
MSG_INGEST = 14          # ingest envelope + wire frame (client -> router)
MSG_INGEST_ACK = 15      # CTRL_SEQ_ACK frame: b=assigned global seq

_LEN = struct.Struct("<IB")                # length covers type byte + body
_DATA_FIXED = struct.Struct("<QI")         # seq, run
MAX_MESSAGE = 1 << 30                      # 1 GiB sanity bound


class ProtocolError(RuntimeError):
    """A malformed or unexpected message on a cluster link."""


# ------------------------------------------------------------- low level


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly n bytes; None on clean EOF at a message boundary."""
    parts = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(min(n - got, 1 << 20))
        except (ConnectionResetError, BrokenPipeError, OSError):
            chunk = b""
        if not chunk:
            return None if not parts else parts  # mid-message EOF below
        parts.append(chunk)
        got += len(chunk)
    return b"".join(parts)


class MessageSocket:
    """One framed duplex link. Sends are serialized by an internal lock
    (multiple router threads share a worker link); receives belong to
    ONE reader thread by construction."""

    def __init__(self, sock: socket.socket):
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._send_lock = threading.Lock()
        self.peer = None
        try:
            self.peer = sock.getpeername()
        except OSError:
            pass

    def send(self, mtype: int, body: bytes = b"") -> None:
        msg = _LEN.pack(1 + len(body), mtype) + body
        with self._send_lock:
            self._sock.sendall(msg)

    def recv(self) -> Optional[Tuple[int, bytes]]:
        """Next (type, body), or None on EOF / reset."""
        head = _recv_exact(self._sock, _LEN.size)
        if head is None or isinstance(head, list):
            return None
        length, mtype = _LEN.unpack(head)
        if not 1 <= length <= MAX_MESSAGE:
            raise ProtocolError(f"message length {length} out of bounds")
        if length == 1:
            return mtype, b""
        body = _recv_exact(self._sock, length - 1)
        if body is None or isinstance(body, list):
            return None
        return mtype, body

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


# ------------------------------------------------------------- envelopes


def jdump(obj) -> bytes:
    return json.dumps(obj).encode("utf-8")


def jload(body: bytes):
    try:
        return json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"bad JSON body: {e}") from None


def _pack_name(name: str) -> bytes:
    b = name.encode("utf-8")
    if len(b) > 0xFFFF:
        raise ProtocolError(f"name too long: {len(b)} bytes")
    return struct.pack("<H", len(b)) + b


def _unpack_name(body: bytes, pos: int) -> Tuple[str, int]:
    if pos + 2 > len(body):
        raise ProtocolError("truncated envelope name")
    (n,) = struct.unpack_from("<H", body, pos)
    pos += 2
    if pos + n > len(body):
        raise ProtocolError("truncated envelope name body")
    return body[pos:pos + n].decode("utf-8"), pos + n


def pack_data(seq: int, run: int, app: str, stream: str,
              frame: bytes) -> bytes:
    """DATA/INGEST envelope. For MSG_INGEST the (seq, run) slots are 0 —
    the ROUTER assigns the global sequence, that is its whole job."""
    return (_DATA_FIXED.pack(seq, run) + _pack_name(app)
            + _pack_name(stream) + frame)


def unpack_data(body: bytes) -> Tuple[int, int, str, str, bytes]:
    if len(body) < _DATA_FIXED.size:
        raise ProtocolError("truncated data envelope")
    seq, run = _DATA_FIXED.unpack_from(body, 0)
    app, pos = _unpack_name(body, _DATA_FIXED.size)
    stream, pos = _unpack_name(body, pos)
    return seq, run, app, stream, body[pos:]


def py_value(v):
    """numpy scalar -> plain Python for the JSON emission path (exact:
    float32 widens losslessly, json round-trips float64 via repr)."""
    if isinstance(v, np.generic):
        return v.item()
    return v


# --------------------------------------------------------- relay encoder


class RelayEncoder(WireEncoder):
    """Router-side re-framing encoder for ONE (worker, app, stream) link.

    The router decodes an ingest frame against its own per-app
    ``StringDictionary`` (string columns become router ids), splits rows
    by key owner, and re-encodes each slice for its worker. String
    columns are already id arrays at that point, so this encoder keeps a
    dense router-id -> client-id LUT per instance: translating a column
    is one vectorized gather, and NEW router ids register their string
    in the inherited dictionary-delta state so the worker's decoder
    learns them from the frame's delta — per-row Python only ever runs
    once per NEW string, same as the ingest decode side."""

    def __init__(self, dictionary):
        super().__init__()
        self._dictionary = dictionary
        self._router_lut = np.full(0, -1, np.int64)

    def encode_ids(self, ids: np.ndarray) -> np.ndarray:
        """Translate a router-id column (int64, negative = null) to this
        link's client ids (int32)."""
        ids = np.asarray(ids, np.int64)
        if len(ids) == 0:
            return ids.astype(np.int32)
        hi = int(ids.max())
        if hi >= len(self._router_lut):
            grown = np.full(hi + 1, -1, np.int64)
            grown[:len(self._router_lut)] = self._router_lut
            self._router_lut = grown
        valid = ids >= 0
        missing = np.unique(ids[valid & (self._router_lut[
            np.where(valid, ids, 0)] < 0)]) if valid.any() else ()
        for rid in missing:
            self._router_lut[int(rid)] = self._intern(
                self._dictionary.decode(int(rid)))
        return np.where(valid, self._router_lut[np.where(valid, ids, 0)],
                        -1).astype(np.int32)

    def _intern(self, s: str) -> int:
        j = self._to_id.get(s)
        if j is None:
            j = len(self._strings)
            self._to_id[s] = j
            self._strings.append(s)
        return j


def encode_for_link(encoder: RelayEncoder, data: Dict[str, np.ndarray],
                    string_attrs, timestamps=None) -> bytes:
    """Re-encode a router-decoded column dict on a worker link: string
    columns (router ids) go through the LUT and travel as pre-encoded
    client ids; everything else passes through untouched."""
    out = {}
    for name, col in data.items():
        if name in string_attrs:
            out[name] = encoder.encode_ids(col)
        else:
            out[name] = col
    return encoder.encode(out, timestamps=timestamps,
                          string_ids=frozenset(string_attrs))
