"""Ordered egress: re-merge worker emissions into exact global order.

The discipline is the PR-6 aggregation-shard stitch — a deterministic
heapq merge over per-shard parts — lifted across sockets: the router
stamps every ingest batch with a global sequence and splits it into
maximal contiguous same-owner row RUNS tagged ``(seq, run)``; each
worker processes its runs in order and reports one completion (wire
``CTRL_SEQ_ACK``) per run, with the run's output rows riding ahead of
it. The merger releases emissions strictly in ``(seq, run)`` order —
the exact order a single process feeding the same run sequence would
have produced — by holding completed-but-early tags in a heap and
popping while the heap head matches the oldest outstanding tag
("Scaling Ordered Stream Processing on Shared-Memory Multicores":
sequence-ordered low-overhead merge, PAPERS.md).

Effectively-once lives here too: a respawned worker REPLAYS its WAL
suffix, so emissions for already-merged tags arrive a second time; the
completed-tag set drops them (``duplicate_emits``), which is what makes
replay safe to over-deliver — zero lost rows, zero doubled rows.
"""

from __future__ import annotations

import heapq
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from siddhi_tpu.analysis.guards import guarded
from siddhi_tpu.analysis.locks import make_lock

Tag = Tuple[int, int]


@guarded
class OrderedEgress:
    """Router-side merge point for worker emissions."""

    GUARDED_BY = {
        "_expected": "egress", "_expected_set": "egress",
        "_ready": "egress", "_pending_rows": "egress",
        "_done": "egress", "rows": "egress",
        "merged_rows": "egress", "merged_runs": "egress",
        "duplicate_emits": "egress",
    }

    def __init__(self):
        self._lock = make_lock("egress")
        self._cv = threading.Condition(self._lock)
        self._expected: deque = deque()     # tags in global send order
        self._expected_set = set()
        self._ready: list = []              # heap of completed early tags
        self._pending_rows: Dict[Tag, list] = {}
        self._done = set()                  # merged tags (replay dedup)
        # (app, stream) -> [(ts, tuple(values)), ...] in global order
        self.rows: Dict[Tuple[str, str], List[Tuple]] = {}
        self.merged_rows = 0
        self.merged_runs = 0
        self.duplicate_emits = 0

    # ------------------------------------------------------------ feeding

    def expect(self, tag: Tag) -> None:
        """Register an outstanding run at SEND time — tags must arrive
        here in global (seq, run) order; that order is the merge's
        ground truth."""
        with self._lock:
            if self._expected and self._expected[-1] >= tag:
                raise ValueError(
                    f"egress tags must be expected in order: {tag} after "
                    f"{self._expected[-1]}")
            self._expected.append(tag)
            self._expected_set.add(tag)

    def emit(self, tag: Tag, app: str, stream: str, rows: List[Tuple]
             ) -> bool:
        """Buffer one run's output rows (worker MSG_EMIT). Rows for a
        replayed, already-merged tag are dropped here (returns False)."""
        with self._lock:
            if tag in self._done or tag not in self._expected_set:
                self.duplicate_emits += 1
                return False
            self._pending_rows.setdefault(tag, []).append(
                (app, stream, rows))
            return True

    def complete(self, tag: Tag) -> bool:
        """Mark one run complete (worker seq-ack) and release every
        emission the global order now admits. False for a replayed ack
        of an already-merged tag."""
        with self._cv:
            if tag in self._done or tag not in self._expected_set:
                return False                # replayed ack: already merged
            self._done.add(tag)
            heapq.heappush(self._ready, tag)
            self._release_locked()
            self._cv.notify_all()
            return True

    def _release_locked(self) -> None:
        """Pop + merge while the heap head is the oldest outstanding
        tag. Caller holds the lock."""
        while (self._expected and self._ready
               and self._ready[0] == self._expected[0]):
            head = heapq.heappop(self._ready)
            self._expected.popleft()
            self._expected_set.discard(head)
            for app, stream, rows in self._pending_rows.pop(head, ()):
                out = self.rows.setdefault((app, stream), [])
                for ts, values in rows:
                    out.append((ts, tuple(values)))
                    self.merged_rows += 1
            self.merged_runs += 1

    def drop_pending(self, tag: Tag) -> None:
        """Discard buffered rows of an INCOMPLETE tag — the worker died
        between emitting and acking it, and the WAL replay is about to
        regenerate those rows; keeping both copies would double them."""
        with self._lock:
            if tag in self._done:
                return
            self._pending_rows.pop(tag, None)

    def forget(self, tag: Tag) -> None:
        """Drop an outstanding HEAD tag that will never complete (e.g. a
        run whose WAL record was lost to overflow — the recovery path
        surfaces that as a counted gap, never a silent hang)."""
        with self._cv:
            if tag not in self._expected_set or tag in self._done:
                return
            self._done.add(tag)
            self._pending_rows.pop(tag, None)    # ONLY the lost tag's rows
            heapq.heappush(self._ready, tag)
            # release through the normal path: later completed tags
            # unblocked by this gap still merge their rows
            self._release_locked()
            self._cv.notify_all()

    # ------------------------------------------------------------ reading

    def outstanding(self) -> int:
        with self._lock:
            return len(self._expected)

    def wait_quiesced(self, timeout: Optional[float] = None) -> bool:
        """Block until every expected run has merged — the checkpoint
        barrier's quiesce point."""
        with self._cv:
            return self._cv.wait_for(lambda: not self._expected,
                                     timeout=timeout)

    def counters(self) -> Dict[str, int]:
        """Merge counters under the lock — status endpoints and tools
        must read through here, never the raw attributes."""
        with self._lock:
            return {"merged_rows": self.merged_rows,
                    "merged_runs": self.merged_runs,
                    "duplicate_emits": self.duplicate_emits}

    def snapshot_rows(self) -> Dict[Tuple[str, str], List[Tuple]]:
        with self._lock:
            return {k: list(v) for k, v in self.rows.items()}

    def stream_rows(self, app: str, stream: str) -> List[Tuple]:
        with self._lock:
            return list(self.rows.get((app, stream), ()))
