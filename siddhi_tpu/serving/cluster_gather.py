"""Scatter-gather merge for cluster on-demand queries.

A PINNED app answers from its one owner worker — one part, returned
verbatim, so the result is bit-identical to the single-process runtime.
A SPLIT app fans out to every worker and each part covers a DISJOINT
key range (``crc32(key) % n`` ownership), so the stitch is the PR-6
sharded-aggregation rule (``serving/sharded_aggregation.py``): order
the union deterministically by a total row key, and fold buckets that
more than one shard reports. Disjoint ownership makes genuine
cross-shard buckets impossible in steady state — a duplicate bucket
here is the same snapshot row surfacing from two shards (e.g. a query
against a replicated table), which folds to a single copy, the
``first`` rule of the base-spec fold table.
"""

from __future__ import annotations

import heapq
from typing import List


def _row_key(row):
    """Total deterministic order over (ts, values) query rows. ``repr``
    per value keeps mixed-type columns comparable (ints never compare
    with strings directly) while staying exact for the types the wire
    carries."""
    ts, values = row[0], row[1]
    return (ts, tuple(repr(v) for v in values))


def gather_query_rows(parts: List[list]) -> list:
    """Merge per-worker on-demand query results into one deterministic
    answer. One part passes through untouched (exact single-process
    order); multiple parts heapq-stitch by row key with value-identical
    duplicate buckets folded."""
    parts = [p for p in parts if p is not None]
    if not parts:
        return []
    if len(parts) == 1:
        return list(parts[0])
    merged = heapq.merge(*(sorted(p, key=_row_key) for p in parts),
                         key=_row_key)
    out: list = []
    last_key = None
    for row in merged:
        key = _row_key(row)
        if key == last_key:
            continue            # duplicate bucket: fold to one copy
        out.append(row)
        last_key = key
    return out
