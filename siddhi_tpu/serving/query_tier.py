"""Scatter-gather query tier: shared scatter pool + bounded admission.

Two thread pools with different jobs:

- **Scatter pool** (``scatter_pool()``): a small process-wide executor the
  sharded aggregation uses to read shard partials concurrently. Shared
  across apps and queries — per-runtime pools would leak a thread set per
  deployed app.
- **Admission pool** (``AdmissionPool``): the on-demand query executor in
  front of the REST surface. Bounded workers bound query *concurrency*;
  per-endpoint queue caps bound query *backlog*; past the cap,
  ``try_submit`` raises ``QueryShedError`` and the REST layer answers
  503 — a query storm degrades to fast rejections instead of stacking
  handler threads behind the app barrier and stalling ingest. Sheds and
  admissions are counted on the process telemetry registry
  (``serving.queries`` / ``serving.sheds`` → ``/metrics``) and, when the
  target app collects statistics, on its ``resilience.query_sheds``
  counter.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Optional

from siddhi_tpu.observability.telemetry import global_registry

_SCATTER_LOCK = threading.Lock()
_SCATTER_POOL: Optional[ThreadPoolExecutor] = None


def scatter_pool(max_workers: int = 16) -> ThreadPoolExecutor:
    """Lazy process-wide executor for per-shard partial reads. Lives for
    the process (idle workers cost nothing; shard reads are lock-bounded,
    never hanging); submits after interpreter shutdown raise
    RuntimeError, which callers handle by reading inline."""
    global _SCATTER_POOL
    with _SCATTER_LOCK:
        if _SCATTER_POOL is None:
            _SCATTER_POOL = ThreadPoolExecutor(
                max_workers=max_workers,
                thread_name_prefix="siddhi-scatter")
        return _SCATTER_POOL


class QueryShedError(RuntimeError):
    """Raised by ``AdmissionPool.try_submit`` when an endpoint's queue cap
    is reached — map to HTTP 503 (Retry-After) at the service edge."""

    def __init__(self, endpoint: str, cap: int):
        super().__init__(
            f"query load shed: '{endpoint}' has {cap} requests in flight "
            f"(per-endpoint queue cap; retry later or raise "
            f"query_queue_cap)")
        self.endpoint = endpoint
        self.cap = cap


class AdmissionPool:
    """Bounded query executor with per-endpoint admission control."""

    def __init__(self, max_workers: int = 8, default_cap: int = 64,
                 queue_caps: Optional[Dict[str, int]] = None,
                 telemetry=None):
        self._exec = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="siddhi-query")
        self.default_cap = int(default_cap)
        self.queue_caps = dict(queue_caps or {})
        self._lock = threading.Lock()
        self._pending: Dict[str, int] = {}   # submitted, not yet finished
        self._active = 0                     # currently executing
        self._tel = telemetry if telemetry is not None else global_registry()
        self._gauge_names = ("serving.pool.pending", "serving.pool.active")
        self._tel.gauge(self._gauge_names[0],
                        lambda: sum(self._pending.values()))
        self._tel.gauge(self._gauge_names[1], lambda: self._active)

    def cap_for(self, endpoint: str) -> int:
        return self.queue_caps.get(endpoint, self.default_cap)

    def try_submit(self, endpoint: str, fn, *args, cap=None,
                   **kwargs) -> Future:
        """Admit or shed: raises ``QueryShedError`` when the endpoint
        already has ``cap`` requests pending (queued + executing).
        ``cap`` overrides the endpoint's configured cap for this submit —
        the service edge passes an app's own quota here when the target
        app registered one with the overload layer
        (``resilience/overload.py``), making admission per-TENANT: a
        ``/query:<app>`` endpoint tracks its own pending count, so one
        app's query storm sheds against its own cap instead of consuming
        the shared pool's."""
        if cap is None:
            cap = self.cap_for(endpoint)
        with self._lock:
            n = self._pending.get(endpoint, 0)
            if n >= cap:
                self._tel.count("serving.sheds")
                raise QueryShedError(endpoint, cap)
            self._pending[endpoint] = n + 1
        self._tel.count("serving.queries")

        def run():
            with self._lock:
                self._active += 1
            try:
                return fn(*args, **kwargs)
            finally:
                with self._lock:
                    self._active -= 1
                    self._pending[endpoint] -= 1

        try:
            return self._exec.submit(run)
        except RuntimeError:     # pool shut down mid-request
            with self._lock:
                self._pending[endpoint] -= 1
            raise

    def shutdown(self):
        # unregister the gauges: the registry is process-global, and a
        # dead pool's closures would otherwise be scraped (and pin the
        # pool) forever
        for name in self._gauge_names:
            self._tel.remove_gauge(name)
        self._exec.shutdown(wait=False)
