"""Serving tier: mesh-sharded incremental aggregation + scatter-gather
on-demand queries + admission control (README "Serving tier")."""

from siddhi_tpu.serving.query_tier import (
    AdmissionPool,
    QueryShedError,
    scatter_pool,
)
from siddhi_tpu.serving.sharded_aggregation import (
    AggregationShard,
    ShardedIncrementalAggregation,
)

__all__ = [
    "AdmissionPool",
    "AggregationShard",
    "QueryShedError",
    "ShardedIncrementalAggregation",
    "scatter_pool",
]
