"""Mesh-sharded incremental aggregation: the serving tier's write side.

The reference's only multi-node aggregation story shards through a shared
database — every node writes per-``shardId`` rows into common tables and
readers stitch them back (``AggregationParser.java:171-197``, mirrored
here by ``IncrementalAggregationRuntime.publish_shard/stitch_shards``).
This module replaces that DB round trip with in-process mesh sharding:

- **One rollup program, N shards.** ``ShardedIncrementalAggregation``
  compiles the aggregation's selector/base/output specs exactly once (the
  base-class constructor) and key-partitions only the *state*: each
  ``AggregationShard`` owns the sec/min/hour/day bucket stores for its
  slice of the group-key space ("On the Semantic Overlap of Operators in
  Stream Processing Engines" — share the program, split the data).
- **Routing.** A group tuple's owner is ``crc32(key) % n_shards`` — the
  same owner-by-modulus convention as the keyed-query sharding
  (``parallel/mesh.device_route_query_step``; the old host-side
  ``route_batch_to_shards`` is a deprecated shim). Ingest prepares a batch once
  (``_prepare_batch``) and folds each shard's row subset under that
  shard's own lock, so two shards never contend.
- **Snapshot reads, no stop-the-world.** Queries read per-shard
  *partials* — an epoch-pinned, immutable copy of the shard's buckets
  built under the shard lock and cached until the next fold bumps the
  epoch. A query storm therefore costs each shard at most one copy per
  ingest epoch, and ingest never waits on a reader. Each shard also
  materializes its partials as device-resident columnar arrays on its
  assigned mesh device (``shard_device_contents``).
- **Ordered merge.** ``rows()`` scatter-gathers the shards' partials and
  stitches them with a deterministic k-way ordered merge ("Scaling
  Ordered Stream Processing on Shared-Memory Multicores" — merge by
  (bucket, group), fold duplicates with ``_BaseSpec.fold``, the same
  shard-stitch rule the DB mode uses). Output rows are computed by the
  base class's ``_rows_from_items`` — one code path, so sharded and
  unsharded results are bit-identical.
- **Per-shard WALs + rebuild.** Each shard records its routed row subset
  in its own bounded ``IngestWAL``; ``checkpoint_shards`` cuts/trims
  them, and ``rebuild_shard`` restores a lost shard from its last blob
  plus the WAL suffix — effectively-once, shard-scoped, without touching
  the siblings. A blob whose cut predates the WAL's last checkpoint trims
  is restored WITHOUT replay (the suffix follows a newer base — the PR-1
  stale-revision rule).

Enable with the ``siddhi_tpu.agg_shards`` config key (>1) or construct
directly; ``@PartitionById`` DB-stitch mode still works and keeps the
legacy runtime (MIGRATION.md).
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from siddhi_tpu.analysis.locks import make_lock
from siddhi_tpu.core.aggregation.incremental import (
    IncrementalAggregationRuntime,
    parse_duration_name,
)
from siddhi_tpu.core.event import Event
from siddhi_tpu.query_api.definitions import Duration

_LOG = logging.getLogger("siddhi_tpu.serving")


def _merge_key(item):
    return item[0], item[1]


class AggregationShard:
    """One key-range's multi-granularity bucket stores.

    Owns the same ``{Duration: {bucket: {group: [bases]}}}`` layout as the
    single-shard runtime plus a monotonically increasing ``epoch`` (bumped
    on every fold/purge/restore) that pins snapshot reads: ``partials()``
    and the device view are cached per (duration, epoch), so a repeated
    dashboard read between two ingest folds touches no locks beyond one
    epoch check."""

    def __init__(self, index: int, durations: List[Duration], device=None,
                 wal=None):
        self.index = index
        self.device = device
        self.durations = durations
        self.store: Dict[Duration, Dict[int, Dict[tuple, list]]] = {
            d: {} for d in durations}
        self._dirty: set = set()
        self._deleted: set = set()
        self._lock = make_lock("shard")
        self.epoch = 0
        self.wal = wal
        # duration -> (epoch, sorted [(bucket, group, [bases copy])])
        self._partials_cache: Dict[Duration, Tuple[int, list]] = {}
        # duration -> (epoch, (definition, device cols, device valid))
        self._device_cache: Dict[Duration, Tuple[int, tuple]] = {}

    def bump(self) -> None:
        """Invalidate snapshot views; call under ``_lock`` after any
        store mutation."""
        self.epoch += 1

    def partials(self, duration: Duration) -> list:
        """Epoch-pinned snapshot of this shard's buckets for one duration:
        a sorted, immutable list of (bucket, group, base-values-copy).
        Readers share the cached copy; a concurrent fold builds new slots
        but never mutates a handed-out copy."""
        with self._lock:
            cached = self._partials_cache.get(duration)
            if cached is not None and cached[0] == self.epoch:
                return cached[1]
            # .get: after a cross-layout restore a shard re-creates a
            # declared duration only when ingest first touches it
            items = [(b, g, list(vals))
                     for b, groups in self.store.get(duration, {}).items()
                     for g, vals in groups.items()]
            items.sort(key=_merge_key)
            self._partials_cache[duration] = (self.epoch, items)
            return items

    def wipe(self) -> None:
        """Fault injection: lose this shard's state (the in-process analog
        of a died aggregation node). ``rebuild_shard`` recovers it."""
        with self._lock:
            self.store = {d: {} for d in self.durations}
            self._dirty.clear()
            self._deleted.clear()
            self._partials_cache.clear()
            self._device_cache.clear()
            self.bump()


class ShardedIncrementalAggregation(IncrementalAggregationRuntime):
    def __init__(self, definition, app_context, dictionary,
                 stream_definitions, n_shards: int,
                 wal_batches: Optional[int] = 1024):
        super().__init__(definition, app_context, dictionary,
                         stream_definitions)
        if self.shard_mode:
            raise ValueError(
                f"aggregation '{definition.id}': @PartitionById DB-stitch "
                f"mode and in-process mesh sharding are mutually exclusive "
                f"(MIGRATION.md)")
        if n_shards < 1:
            raise ValueError("agg_shards must be >= 1")
        self.n_shards = int(n_shards)

        # shard i answers from device i (round-robin over the mesh): the
        # device view caches live where the shard's keyed state would be
        # placed by parallel/mesh key-axis sharding
        try:
            import jax

            devs = jax.devices()
        except Exception:  # noqa: BLE001 — serving works host-only too
            devs = [None]

        from siddhi_tpu.resilience.replay import IngestWAL

        self.shards: List[AggregationShard] = []
        for i in range(self.n_shards):
            wal = (IngestWAL(max_batches=wal_batches,
                             app_context=app_context)
                   if wal_batches else None)
            self.shards.append(AggregationShard(
                i, self.durations, device=devs[i % len(devs)], wal=wal))
        self._last_cuts: List[int] = [0] * self.n_shards

        tel = getattr(app_context, "telemetry", None)
        self._fanout_hist = self._merge_hist = None
        self._query_hists: Dict[Duration, object] = {}
        if tel is not None and hasattr(tel, "histogram"):
            aid = definition.id
            tel.gauge(f"aggregation.{aid}.shards", lambda: self.n_shards)
            for s in self.shards:
                if s.wal is not None:
                    tel.gauge(f"aggregation.{aid}.shard{s.index}"
                              f".wal_batches", s.wal.__len__)
            self._fanout_hist = tel.histogram("serving.fanout_ms")
            self._merge_hist = tel.histogram("serving.merge_ms")
            self._query_hists = {
                d: tel.histogram(f"serving.query.{d.value}_ms")
                for d in self.durations}

    # ------------------------------------------------------------- routing

    def _owner_of(self, g: tuple) -> int:
        """Deterministic shard owner of one group tuple. Group components
        are numeric (strings travel as dictionary ids), so ``repr`` is a
        stable byte key within a runtime; WAL/snapshot recovery re-routes
        through this same function, so ownership survives restarts even
        if the hash landed differently before."""
        if self.n_shards == 1:
            return 0
        return zlib.crc32(repr(g).encode()) % self.n_shards

    # -------------------------------------------------------------- ingest

    def receive(self, events: List[Event]):
        prep = self._prepare_batch(events)
        if prep is None:
            return
        t0 = time.perf_counter()
        # base-class parity: ingest re-creates declared granularities a
        # shrinking restore removed (self.store is the sharded runtime's
        # queryable-duration marker; buckets live in the shards)
        for d in self.durations:
            self.store.setdefault(d, {})
        owned: Dict[int, list] = {}
        for i in prep["idx"]:
            owned.setdefault(
                self._owner_of(prep["group_tuples"][int(i)]), []).append(i)
        for s_idx, rows in owned.items():
            shard = self.shards[s_idx]
            with shard._lock:
                self._fold_rows(shard, prep, rows)
                shard.bump()
                if shard.wal is not None:
                    # the shard's routed sub-batch, in arrival order — the
                    # replay source for a shard-scoped rebuild. Recorded
                    # INSIDE the shard lock: a concurrent rebuild then
                    # sees this batch either folded+recorded or neither —
                    # fold-then-record outside the lock would let the
                    # rebuild's store swap discard the fold while the
                    # replay misses the not-yet-appended record
                    shard.wal.record_events(
                        self.input_stream_id,
                        [events[int(i)] for i in rows])
        if self._flush_hist is not None:
            self._flush_hist.record((time.perf_counter() - t0) * 1000.0)

    # --------------------------------------------------------------- query

    def _scatter(self, fn) -> list:
        """Run ``fn(shard)`` over all shards concurrently on the shared
        serving pool; falls back to inline reads when the executor
        refuses new work (interpreter teardown) so a late query never
        fails just because scatter cannot."""
        if self.n_shards == 1:
            return [fn(self.shards[0])]
        from siddhi_tpu.serving.query_tier import scatter_pool

        try:
            futures = [scatter_pool().submit(fn, s) for s in self.shards]
        except RuntimeError:
            return [fn(s) for s in self.shards]
        return [f.result() for f in futures]

    def rows(self, duration: Duration,
             within: Optional[Tuple[int, int]] = None) -> List[list]:
        within = self._resolve_within(duration, within)
        t0 = time.perf_counter()
        parts = self._scatter(lambda s: s.partials(duration))
        t1 = time.perf_counter()
        merged = self._ordered_merge(parts, within)
        t2 = time.perf_counter()
        if self._fanout_hist is not None:
            self._fanout_hist.record((t1 - t0) * 1000.0)
            self._merge_hist.record((t2 - t1) * 1000.0)
        out = self._rows_from_items(merged)
        h = self._query_hists.get(duration)
        if h is not None:
            h.record((time.perf_counter() - t0) * 1000.0)
        return out

    def _ordered_merge(self, parts: List[list],
                       within: Optional[Tuple[int, int]]) -> list:
        """Deterministic k-way merge of per-shard partials, ordered by
        (bucket, group). Ownership is disjoint in steady state, but a
        rebuild-in-progress or a cross-layout restore can surface the same
        (bucket, group) on two shards — duplicates fold by base
        (``_BaseSpec.fold``), the reference's shard-stitch rule."""
        base_specs = list(self.bases.values())
        merged: list = []
        for item in heapq.merge(*parts, key=_merge_key):
            if within is not None and not (within[0] <= item[0] < within[1]):
                continue
            if merged and _merge_key(merged[-1]) == _merge_key(item):
                prev = merged[-1][2]
                merged[-1] = (item[0], item[1], [
                    spec.fold(a, b)
                    for spec, a, b in zip(base_specs, prev, item[2])])
            else:
                merged.append(item)
        return merged

    def shard_device_contents(self, index: int, duration: Duration):
        """One shard's stitched rollup rows as device-resident columnar
        arrays on the shard's mesh device, cached per ingest epoch —
        repeated on-demand reads between folds are served from the device
        without re-walking the host cube. Returns (output_definition,
        {col: jax.Array}, valid)."""
        import jax

        shard = self.shards[index]
        with shard._lock:
            epoch = shard.epoch
            cached = shard._device_cache.get(duration)
        if cached is not None and cached[0] == epoch:
            return cached[1]
        definition, cols, valid = self._columnize(
            self._rows_from_items(shard.partials(duration)))
        dev = shard.device
        if dev is not None:
            cols = {k: jax.device_put(v, dev) for k, v in cols.items()}
            valid = jax.device_put(valid, dev)
        view = (definition, cols, valid)
        shard._device_cache[duration] = (epoch, view)
        return view

    def _bucket_count(self, duration: Duration) -> int:
        return sum(len(s.store.get(duration, ())) for s in self.shards)

    # --------------------------------------------------------------- purge

    def purge(self, now: Optional[int] = None) -> int:
        if now is None:
            now = int(self.app_context.timestamp_generator.current_time())
        purged = 0
        for shard in self.shards:
            with shard._lock:
                touched = False
                for d, dstore in shard.store.items():
                    keep_ms = self.retention.get(d)
                    if keep_ms is None:
                        continue
                    cutoff = now - keep_ms
                    drop = [b for b in dstore if b < cutoff]
                    for b in drop:
                        del dstore[b]
                        shard._deleted.add((d, b))
                        shard._dirty.discard((d, b))
                        touched = True
                    purged += len(drop)
                if touched:
                    shard.bump()
        return purged

    # ----------------------------------------------- checkpoint + rebuild

    def _ser_store(self, store) -> dict:
        return {d.value: {b: {g: list(v) for g, v in groups.items()}
                          for b, groups in dstore.items()}
                for d, dstore in store.items()}

    def _deser_store(self, ser) -> dict:
        out = {d: {} for d in self.durations}
        for dv, dstore in ser.items():
            d = parse_duration_name(dv)
            if d not in out:
                continue
            out[d] = {
                int(b): {(tuple(g) if isinstance(g, (list, tuple))
                          else (g,)): list(v)
                         for g, v in groups.items()}
                for b, groups in dstore.items()}
        return out

    def checkpoint_shards(self) -> List[dict]:
        """Per-shard checkpoint blobs ({"store", "cut"}) for the rebuild
        protocol. The WAL is trimmed at each shard's cut — the blob now
        covers that prefix — so the retained suffix is exactly what a
        later ``rebuild_shard`` must replay."""
        blobs = []
        for shard in self.shards:
            with shard._lock:
                cut = shard.wal.cut() if shard.wal is not None else 0
                blobs.append({"shard": shard.index,
                              "store": self._ser_store(shard.store),
                              "cut": cut})
            if shard.wal is not None:
                shard.wal.trim(cut)
        return blobs

    def kill_shard(self, index: int) -> None:
        """Fault injection: wipe one shard's state (its WAL survives, as a
        live process's log would)."""
        self.shards[index].wipe()

    def rebuild_shard(self, index: int, blob: dict) -> int:
        """Supervisor rebuild protocol for one lost shard: restore the
        shard's last checkpoint blob, then re-fold its WAL suffix (records
        newer than the blob's cut) — effectively-once, without touching
        sibling shards or blocking their ingest. A blob whose cut predates
        the WAL's last checkpoint trim skips the replay: the retained
        suffix follows a NEWER base, and grafting it onto this older one
        would silently lose the gap (the PR-1 stale-revision rule).
        Returns the number of replayed records."""
        from siddhi_tpu.resilience import stat_count

        shard = self.shards[index]
        cut = int(blob.get("cut", 0))
        replayed = 0
        with shard._lock:
            shard.store = self._deser_store(blob.get("store", {}))
            shard._dirty = {(d, b) for d, dstore in shard.store.items()
                            for b in dstore}
            shard._deleted.clear()
            shard._partials_cache.clear()
            shard._device_cache.clear()
            if shard.wal is not None:
                if cut < shard.wal.checkpoint_seq:
                    _LOG.warning(
                        "aggregation '%s' shard %d: checkpoint cut %d "
                        "predates the WAL's last trim %d — skipping the "
                        "replay (suffix follows a newer base)",
                        self.definition.id, index, cut,
                        shard.wal.checkpoint_seq)
                    stat_count(self.app_context,
                               "resilience.shard_replay_skips")
                else:
                    recs = shard.wal.records_after(cut)
                    # the bounded log drops OLDEST records on overflow:
                    # if appends happened past the cut but the retained
                    # suffix no longer starts at cut+1, the gap was
                    # dropped — the rebuild is incomplete and must say so
                    # (sequence numbers are contiguous, so a hole in the
                    # range is detectable exactly)
                    newest = shard.wal.cut()
                    first = recs[0].seq if recs else newest + 1
                    if newest > cut and first > cut + 1:
                        _LOG.error(
                            "aggregation '%s' shard %d: WAL overflow "
                            "dropped records %d..%d of the replay suffix "
                            "(bound too small / checkpoints too sparse) — "
                            "rebuilt state is missing those batches",
                            self.definition.id, index, cut + 1, first - 1)
                        stat_count(self.app_context,
                                   "resilience.shard_replay_gaps")
                        tel = getattr(self.app_context, "telemetry", None)
                        if tel is not None:
                            tel.count("serving.shard_replay_gaps")
                    for rec in recs:
                        prep = self._prepare_batch(
                            rec.payload if rec.kind == "events" else [])
                        if prep is None:
                            continue
                        rows = [i for i in prep["idx"]
                                if self._owner_of(
                                    prep["group_tuples"][int(i)]) == index]
                        self._fold_rows(shard, prep, rows)
                        replayed += 1
            shard.bump()
        stat_count(self.app_context, "resilience.shard_rebuilds")
        tel = getattr(self.app_context, "telemetry", None)
        if tel is not None:
            tel.count("serving.shard_rebuilds")
        return replayed

    # ---------------------------------------------------------- snapshots

    def snapshot(self) -> dict:
        shards = []
        self._last_cuts = []
        for shard in self.shards:
            with shard._lock:
                shards.append({"shard": shard.index,
                               "store": self._ser_store(shard.store)})
                self._last_cuts.append(
                    shard.wal.cut() if shard.wal is not None else 0)
        return {"sharded": True, "n_shards": self.n_shards,
                "base_keys": list(self.bases), "shards": shards}

    def restore(self, snap: dict):
        # merge to one flat store, realign base keys through the shared
        # helper, then re-route every (bucket, group) to its owner — an
        # UNSHARDED revision or a different shard count cross-restores
        # transparently
        if snap.get("sharded"):
            merged = self._merge_sharded_snapshot(snap)
        else:
            merged = snap
        # reuse the base realignment (snap base_keys -> current layout)
        holder = _RestoreTarget()
        _base_restore(self, holder, merged)
        # mirror the base class's wholesale-replace semantics: the
        # queryable granularity set follows the RESTORED state (fewer or
        # more durations than declared both work — _resolve_within checks
        # the store, and ingest re-creates declared durations on demand)
        for shard in self.shards:
            with shard._lock:
                shard.store = {d: {} for d in holder.store}
                shard._dirty.clear()
                shard._deleted.clear()
                shard._partials_cache.clear()
                shard._device_cache.clear()
        for d, dstore in holder.store.items():
            for b, groups in dstore.items():
                for g, vals in groups.items():
                    shard = self.shards[self._owner_of(g)]
                    shard.store[d].setdefault(b, {})[g] = vals
        self.store = {d: {} for d in holder.store}
        for shard in self.shards:
            with shard._lock:
                shard.bump()
                # the restored state supersedes any retained suffix
                if shard.wal is not None:
                    shard.wal.mark_checkpoint()

    # --------------------------------------------- incremental snapshots

    def incremental_snapshot(self) -> dict:
        shards = []
        for shard in self.shards:
            with shard._lock:
                out = {"buckets": {}, "deleted": []}
                for d, b in shard._dirty:
                    groups = shard.store.get(d, {}).get(b)
                    if groups is None:
                        continue
                    out["buckets"].setdefault(d.value, {})[b] = {
                        g: list(v) for g, v in groups.items()}
                out["deleted"] = [(d.value, b) for d, b in shard._deleted]
                shards.append(out)
        return {"sharded": True, "shards": shards}

    def clear_oplog(self):
        for i, shard in enumerate(self.shards):
            with shard._lock:
                shard._dirty.clear()
                shard._deleted.clear()
            if shard.wal is not None and i < len(self._last_cuts):
                # the revision covering _last_cuts is now durable: the
                # retained suffix follows it
                shard.wal.trim(self._last_cuts[i])

    def apply_increment(self, snap: dict):
        if snap.get("sharded") and len(snap.get("shards", [])) == self.n_shards:
            for shard, sub in zip(self.shards, snap["shards"]):
                with shard._lock:
                    for dv, b in sub.get("deleted", []):
                        shard.store.get(Duration(dv), {}).pop(b, None)
                    for dv, buckets in sub.get("buckets", {}).items():
                        d = Duration(dv)
                        dstore = shard.store.setdefault(d, {})
                        for b, groups in buckets.items():
                            dstore[b] = {g: list(v)
                                         for g, v in groups.items()}
                    shard.bump()
            return
        # foreign layout (unsharded, or a different shard count): buckets
        # REPLACE wholesale, split by ownership
        subs = (snap.get("shards", [snap])
                if snap.get("sharded") else [snap])
        for sub in subs:
            for dv, b in sub.get("deleted", []):
                d = Duration(dv)
                for shard in self.shards:
                    with shard._lock:
                        if shard.store.get(d, {}).pop(b, None) is not None:
                            shard.bump()
            for dv, buckets in sub.get("buckets", {}).items():
                d = Duration(dv)
                for b, groups in buckets.items():
                    owned: Dict[int, dict] = {}
                    for g, v in groups.items():
                        g = tuple(g) if isinstance(g, (list, tuple)) else (g,)
                        owned.setdefault(self._owner_of(g), {})[g] = list(v)
                    for shard in self.shards:
                        mine = owned.get(shard.index)
                        with shard._lock:
                            dstore = shard.store.setdefault(d, {})
                            if mine:
                                dstore[b] = mine
                            else:
                                dstore.pop(b, None)
                            shard.bump()

    # ------------------------------------------------- DB shard-stitch API

    def publish_shard(self):  # pragma: no cover — guarded at construction
        raise RuntimeError(
            "in-process mesh sharding replaces @PartitionById DB-stitch "
            "publishing (MIGRATION.md)")

    def stitch_shards(self) -> int:  # pragma: no cover
        raise RuntimeError(
            "in-process mesh sharding replaces @PartitionById DB-stitch "
            "reads (MIGRATION.md)")


class _RestoreTarget:
    """Bare store holder the base restore writes into."""

    def __init__(self):
        self.store: dict = {}


def _base_restore(runtime: ShardedIncrementalAggregation,
                  holder: _RestoreTarget, snap: dict) -> None:
    """Base-key realignment of a flat snapshot into ``holder.store`` —
    the body of ``IncrementalAggregationRuntime.restore`` minus the
    self-mutation, reused so sharded restore realigns identically."""
    snap_keys = snap.get("base_keys")
    cur_keys = list(runtime.bases)
    if snap_keys is None or snap_keys == cur_keys:
        remap = None
    else:
        remap = [snap_keys.index(k) if k in snap_keys else -1
                 for k in cur_keys]

    def realign(v):
        if remap is None:
            return list(v)
        return [v[j] if j >= 0 else None for j in remap]

    holder.store = {
        parse_duration_name(dv): {
            int(b): {(tuple(g) if isinstance(g, (list, tuple))
                      else (g,)): realign(v)
                     for g, v in groups.items()}
            for b, groups in dstore.items()
        }
        for dv, dstore in snap["store"].items()
    }
