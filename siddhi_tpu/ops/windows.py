"""Window processors as ring-buffer tensor stages.

Replaces the reference's window processor classes
(``query/processor/stream/window/*.java``, 27 classes / 6,866 LoC of
per-event queue surgery) with columnar ring buffers + masked emission.
Exact semantics reproduced per window (event order, CURRENT/EXPIRED/RESET
interleaving, timestamps patched to processing time where the reference
does so):

- length  (``LengthWindowProcessor.java:106-142``): sliding; when full each
  arrival emits [EXPIRED(oldest, ts=now), CURRENT] in that order.
- time    (``TimeWindowProcessor.java:133-168``): expired drained before
  each event with ts set to now; TIMER chunks consumed; notifyAt(ts+t).
- externalTime (``ExternalTimeWindowProcessor``): like time but the cutoff
  advances with each event's own timestamp; no timers; expired keep ts.
- lengthBatch (``LengthBatchWindowProcessor.java:153-260``): flush at exact
  count boundaries (possibly mid-chunk): [EXPIRED(prev batch, ts=now),
  RESET, CURRENT batch...] per flush.
- timeBatch  (``TimeBatchWindowProcessor.java:263-345``): flush check once
  per chunk; the arriving chunk's rows join the flushing batch; order
  [EXPIRED(prev, ts=now), RESET, CURRENT...].
- batch   (``BatchWindowProcessor``): every chunk is its own batch; expired
  = previous chunk.

A stage is ``apply(state, cols, ctx) -> (state, out_cols)``, traced inside
the query's jitted step; output capacity is a static function of the input
batch size. Stages needing timers return ``__notify__`` (next wanted wake
time, -1 if none) for the host scheduler; bounded buffers report
``__overflow__`` so the host can raise instead of silently dropping.

Emission order is produced by one order-key sort. The unified key scheme,
with STRIDE = Wc + B + 4:
  ring-expired item j  (drains before row r): key r*STRIDE + j
  in-batch expired of row i (before row r):   key r*STRIDE + Wc + i
  current row i:                              key i*STRIDE + Wc + B + 2
so expired events always precede the current event they are drained before,
FIFO order among them, exactly as ``insertBeforeCurrent`` produces.

Windows are per-query instances (K=1) exactly as in the reference, where
group-by does NOT partition a window — only `partition with` does (M3 vmaps
these stages over the partition-key axis).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from siddhi_tpu.ops.expressions import (
    OKEY_KEY, RIDX_KEY, TS_KEY, TYPE_KEY, VALID_KEY, CompileError)
from siddhi_tpu.query_api.definitions import AttrType
from siddhi_tpu.query_api.execution import Window
from siddhi_tpu.query_api.expressions import Constant, TimeConstant

CURRENT, EXPIRED, TIMER, RESET = 0, 1, 2, 3
NOTIFY_KEY = "__notify__"
OVERFLOW_KEY = "__overflow__"
FLUSH_KEY = "__flush__"

# numpy on purpose: a jnp scalar here would materialize a device array
# at import and initialize the backend before force_host_devices can
# configure the virtual mesh (graftlint R1); np.int64 promotes
# identically inside the jitted arithmetic below
_BIG = np.int64(2**62)


def _data_keys(cols: Dict) -> List[str]:
    # '#set'/'#setm' companions ([B, H] element snapshots of multi-element
    # set values) never enter window buffers — only the scalar base column
    # is buffered; a downstream unionSet that NEEDS the snapshot raises
    # (ops/aggregators.py arg_is_multi guard)
    return sorted(
        k for k in cols
        if k not in (TYPE_KEY, VALID_KEY, NOTIFY_KEY, OVERFLOW_KEY, FLUSH_KEY,
                     RIDX_KEY, OKEY_KEY)
        and "#set" not in k
    )


def _zero_rows(cols: Dict, n: int):
    return {k: jnp.zeros((n,), cols[k].dtype) for k in _data_keys(cols)}


def _order_emit(parts) -> Tuple[Dict, jnp.ndarray]:
    """Concatenate (data_cols, types, valid, order_key) groups and sort by
    order key with invalid rows last. Returns (out_cols, sorted_keys)."""
    keys = _data_keys(parts[0][0])
    data = {k: jnp.concatenate([p[0][k] for p in parts]) for k in keys}
    types = jnp.concatenate([p[1] for p in parts])
    valid = jnp.concatenate([p[2] for p in parts])
    okey = jnp.concatenate([p[3] for p in parts])
    okey = jnp.where(valid, okey, _BIG)
    order = jnp.argsort(okey, stable=True)
    out = {k: v[order] for k, v in data.items()}
    out[TYPE_KEY] = types[order]
    out[VALID_KEY] = valid[order]
    return out, okey[order]


def _row_order_base(cols: Dict, B: int):
    """Per-row base for emission order keys: the row's position in the
    ORIGINAL batch. Plain steps see ``arange(B)``; under device-routed
    sharding (``parallel/mesh.device_route_query_step``) the route wrapper
    attaches ``RIDX_KEY`` — each row's index in the pre-exchange global
    batch — so a stage's order keys stay comparable ACROSS shards and the
    egress merge can reproduce the exact unsharded emission order."""
    ridx = cols.get(RIDX_KEY)
    if ridx is not None:
        return jnp.asarray(ridx, jnp.int64)
    return jnp.arange(B, dtype=jnp.int64)


def _insert_ranks(valid_cur):
    """(rank per valid row, total inserts) — rank = segmented arrival index."""
    rank = jnp.cumsum(valid_cur.astype(jnp.int64)) - 1
    n_ins = jnp.sum(valid_cur.astype(jnp.int64))
    return rank, n_ins


class WindowStage:
    batch_mode = False
    needs_scheduler = False

    def init_state(self, num_keys: int = 1) -> dict:
        raise NotImplementedError

    def conform(self, cols: Dict) -> Dict:
        """Cast batch columns to this stage's declared ring dtypes.

        Hand-built batches (sharded routers, benches, dry runs) commonly
        carry int64 key/id columns where the ring buffer stores the
        dictionary's int32 ids; scattering int64 values into an int32 ring
        is a JAX FutureWarning today and an error in future releases. A
        matching batch traces to a no-op."""
        specs = getattr(self, "col_specs", None)
        if not specs:
            return cols
        out = dict(cols)
        for k, dt in specs.items():
            v = out.get(k)
            if v is not None and getattr(v, "dtype", dt) != dt:
                out[k] = v.astype(dt)
        return out

    def apply(self, state: dict, cols: Dict, ctx: Dict):
        raise NotImplementedError

    def contents(self, state: dict):
        """(cols [W], valid [W]) view of the currently-held events — the
        probe surface for joins (the role of FindableProcessor.find on
        window processors, reference ``JoinProcessor.java:134-147``)."""
        raise CompileError(
            f"{type(self).__name__} cannot be probed (used as a join side)"
        )


def conform_cols(stage, cols: Dict) -> Dict:
    """``stage.conform(cols)`` for any stage-like object: duck-typed
    stages that slot into the window position without subclassing
    WindowStage (``ops/fused_agg.FusedSlidingAggStage``) pass through."""
    fn = getattr(stage, "conform", None)
    return fn(cols) if fn is not None else cols


class PassthroughWindowStage(WindowStage):
    """A join side that retains nothing itself: a bare (window-less) stream
    side (reference ``EmptyWindowProcessor``; CURRENT only), or a named
    window's emission stream (``pass_expired=True``: the shared window
    already emitted typed CURRENT/EXPIRED events)."""

    def __init__(self, col_specs: Dict[str, np.dtype], pass_expired: bool = False,
                 empty_window: bool = False, expired_needed: bool = False,
                 emit_reset: bool = True):
        self.col_specs = col_specs
        self.pass_expired = pass_expired
        # empty_window: reference EmptyWindowProcessor.java:84 — every
        # arriving event becomes [CURRENT, EXPIRED(clone, ts=now) when the
        # output expects expireds, RESET], so per-trigger aggregates in
        # windowless joins restart per event (JoinTableTestCase query9).
        # emit_reset=False skips the RESET rows when the query has no
        # aggregate state to restart (pure projection joins).
        self.empty_window = empty_window
        self.expired_needed = expired_needed
        self.emit_reset = emit_reset

    def init_state(self, num_keys: int = 1) -> dict:
        return {"empty": jnp.zeros((1,), jnp.int32)}

    def apply(self, state, cols, ctx):
        if self.empty_window:
            keys = _data_keys(cols)
            B = cols[VALID_KEY].shape[0]
            now = jnp.int64(ctx["current_time"])
            valid_cur = cols[VALID_KEY] & (cols[TYPE_KEY] == CURRENT)
            rank, _n = _insert_ranks(valid_cur)
            parts = [({k: cols[k] for k in keys},
                      jnp.full((B,), CURRENT, jnp.int8), valid_cur, rank * 3)]
            if self.expired_needed:
                exp = {k: cols[k] for k in keys}
                exp[TS_KEY] = jnp.where(valid_cur, now, cols[TS_KEY])
                parts.append((exp, jnp.full((B,), EXPIRED, jnp.int8),
                              valid_cur, rank * 3 + 1))
            if self.emit_reset:
                reset_rows = _zero_rows(cols, B)
                reset_rows[TS_KEY] = jnp.where(valid_cur, now, jnp.int64(0))
                parts.append((reset_rows, jnp.full((B,), RESET, jnp.int8),
                              valid_cur, rank * 3 + 2))
            out, _ = _order_emit(parts)
            return state, out
        out = {k: cols[k] for k in _data_keys(cols)}
        out[TYPE_KEY] = cols[TYPE_KEY]
        live = cols[TYPE_KEY] == CURRENT
        if self.pass_expired:
            live = live | (cols[TYPE_KEY] == EXPIRED)
        out[VALID_KEY] = cols[VALID_KEY] & live
        return state, out

    def contents(self, state):
        cols = {k: jnp.zeros((1,), dt) for k, dt in self.col_specs.items()}
        return cols, jnp.zeros((1,), bool)


def _const_param(window: Window, i: int, name: str):
    if i >= len(window.parameters):
        raise CompileError(f"{window.name} window missing parameter '{name}'")
    p = window.parameters[i]
    if isinstance(p, TimeConstant):
        return int(p.value)
    if isinstance(p, Constant):
        return p.value
    raise CompileError(f"{window.name} window parameter '{name}' must be a constant")


def _int_const_param(window: Window, i: int, name: str):
    """A parameter that must be an int/long constant (or time constant) —
    the reference processors reject FLOAT/DOUBLE here at init
    (e.g. ``LengthWindowProcessor.init``, ``TimeWindowProcessor.init``)."""
    v = _const_param(window, i, name)
    if isinstance(v, (float, str, bool)):
        raise CompileError(
            f"{window.name} window parameter '{name}' must be int or long, "
            f"found a {type(v).__name__} constant")
    return int(v)


def _bool_const_param(window: Window, i: int, name: str) -> bool:
    p = window.parameters[i]
    if not (isinstance(p, Constant) and isinstance(p.value, bool)):
        raise CompileError(
            f"{window.name} window parameter '{name}' must be a bool constant")
    return p.value


def _expect_arity(window: Window, low: int, high: int):
    n = len(window.parameters)
    if not (low <= n <= high):
        want = str(low) if low == high else f"{low}..{high}"
        raise CompileError(
            f"{window.name} window expects {want} parameter(s), found {n}")


# ------------------------------------------------------------------ length

class LengthWindowStage(WindowStage):
    """Sliding length window."""

    def __init__(self, length: int, col_specs: Dict[str, np.dtype]):
        if length <= 0:
            raise CompileError("length window needs a positive length")
        self.length = length
        self.col_specs = col_specs

    def init_state(self, num_keys: int = 1) -> dict:
        W = self.length
        buf = {k: jnp.zeros((W,), dt) for k, dt in self.col_specs.items()}
        return {"buf": buf, "total": jnp.int64(0)}

    @property
    def ring_capacity(self) -> int:
        return self.length

    def live_fill(self, state):
        """Live rows in the ring (device scalar) — the ``win_fill``
        instrument slot (``observability/instruments.py``): computed
        inside the jitted step from state it already holds, so ring
        occupancy reaches /metrics with zero extra host transfers."""
        return jnp.minimum(state["total"], jnp.int64(self.length))

    def apply(self, state, cols, ctx):
        W = self.length
        keys = _data_keys(cols)
        B = cols[VALID_KEY].shape[0]
        now = jnp.int64(ctx["current_time"])
        valid_cur = cols[VALID_KEY] & (cols[TYPE_KEY] == CURRENT)

        total0 = state["total"]
        rank, n_ins = _insert_ranks(valid_cur)
        seq = total0 + rank  # global per-window sequence of each inserted row

        # rank -> original row index (for evictees inserted earlier this batch)
        rank_to_row = jnp.zeros((B,), jnp.int32).at[
            jnp.where(valid_cur, rank, B).astype(jnp.int32)
        ].set(jnp.arange(B, dtype=jnp.int32), mode="drop")

        evicts = valid_cur & (seq >= W)
        evict_seq = seq - W
        from_batch = evict_seq >= total0
        ring_slot = (evict_seq % W).astype(jnp.int32)
        batch_row = rank_to_row[jnp.clip(evict_seq - total0, 0, B - 1).astype(jnp.int32)]

        expired = {}
        for k in keys:
            ring_v = state["buf"][k][ring_slot]
            expired[k] = jnp.where(from_batch, cols[k][batch_row], ring_v)
        expired[TS_KEY] = jnp.broadcast_to(now, (B,))  # LengthWindowProcessor:120

        # ring update: write the last min(W, n_ins) inserted rows (unique slots)
        write = valid_cur & (rank >= n_ins - W)
        slot = jnp.where(write, (seq % W).astype(jnp.int32), W)
        new_buf = {k: state["buf"][k].at[slot].set(cols[k], mode="drop") for k in state["buf"]}

        idx = jnp.arange(B, dtype=jnp.int64)
        parts = [
            (expired, jnp.full((B,), EXPIRED, jnp.int8), evicts, 2 * idx),
            ({k: cols[k] for k in keys}, cols[TYPE_KEY], valid_cur, 2 * idx + 1),
        ]
        out, _ = _order_emit(parts)
        return {"buf": new_buf, "total": total0 + n_ins}, out

    def contents(self, state):
        valid = jnp.arange(self.length, dtype=jnp.int64) < state["total"]
        return dict(state["buf"]), valid


# -------------------------------------------------------------------- time

class TimeWindowStage(WindowStage):
    """Sliding time window; ``external=True`` drives the cutoff from event
    timestamps (externalTime) instead of the runtime clock."""

    def __init__(self, time_ms: int, col_specs: Dict[str, np.dtype], capacity: int,
                 external: bool = False, ts_key: str = TS_KEY):
        self.time_ms = time_ms
        self.capacity = capacity
        self.col_specs = col_specs
        self.external = external
        # externalTime clock column: the named timestamp ATTRIBUTE (falls
        # back to the event timestamp) — expiry cutoffs read this column,
        # expired emissions keep the original event timestamps
        self.ts_key = ts_key
        self.needs_scheduler = not external

    def init_state(self, num_keys: int = 1) -> dict:
        Wc = self.capacity
        buf = {k: jnp.zeros((Wc,), dt) for k, dt in self.col_specs.items()}
        return {"buf": buf, "total": jnp.int64(0), "expired_upto": jnp.int64(0)}

    @property
    def ring_capacity(self) -> int:
        return self.capacity

    def live_fill(self, state):
        """Live (unexpired) rows in the ring — ``win_fill`` instrument
        slot; near ``capacity`` means the ring is one skewed batch away
        from overflow."""
        return jnp.maximum(state["total"] - state["expired_upto"],
                           jnp.int64(0))

    def apply(self, state, cols, ctx):
        Wc = self.capacity
        t = jnp.int64(self.time_ms)
        keys = _data_keys(cols)
        B = cols[VALID_KEY].shape[0]
        valid_cur = cols[VALID_KEY] & (cols[TYPE_KEY] == CURRENT)
        ts = cols[TS_KEY]
        now = jnp.int64(ctx["current_time"])
        STRIDE = jnp.int64(Wc + B + 4)

        total0 = state["total"]
        exp0 = state["expired_upto"]

        # FIFO view: item j holds sequence exp0 + j (arrival timestamps are
        # monotone, so expiry always removes a FIFO prefix)
        fifo_seq = exp0 + jnp.arange(Wc, dtype=jnp.int64)
        occupied = fifo_seq < total0
        fifo_slot = (fifo_seq % Wc).astype(jnp.int32)
        ring_ts = state["buf"][TS_KEY][fifo_slot]

        if self.external:
            # cutoff for row i: clock_i - t (running max for safety)
            ck = cols[self.ts_key]
            ring_ck = state["buf"][self.ts_key][fifo_slot]
            run_max = lax.cummax(jnp.where(valid_cur, ck, jnp.int64(-(2**62))))
            final_cutoff = run_max[B - 1] - t
            expire_ring = occupied & (ring_ck <= final_cutoff)
            # first row whose cutoff covers item j
            covers = (run_max[None, :] - t) >= ring_ck[:, None]  # [Wc, B]
            first_row = jnp.where(
                jnp.any(covers, axis=1), jnp.argmax(covers, axis=1), 0
            ).astype(jnp.int64)
            exp_ts_ring = ring_ts  # externalTime keeps original timestamps
        else:
            expire_ring = occupied & (ring_ts + t <= now)
            first_row = jnp.zeros((Wc,), jnp.int64)  # all drain before row 0
            exp_ts_ring = jnp.broadcast_to(now, (Wc,))

        n_exp_ring = jnp.sum(expire_ring.astype(jnp.int64))

        # within-batch expiry: row i's clone expires before a later row r
        if self.external:
            # coverage by the clock attribute, not the event timestamp
            nxt = _first_later_covering(cols[self.ts_key], valid_cur, t)  # [B] (B if none)
            batch_exp = valid_cur & (nxt < B)
            exp_ts_batch = ts
        else:
            nxt = _next_valid_index(valid_cur)
            batch_exp = valid_cur & (ts + t <= now) & (nxt < B)
            exp_ts_batch = jnp.broadcast_to(now, (B,))

        idx = jnp.arange(B, dtype=jnp.int64)
        ring_okey = first_row * STRIDE + jnp.arange(Wc, dtype=jnp.int64)
        batch_okey = nxt.astype(jnp.int64) * STRIDE + Wc + idx
        cur_okey = idx * STRIDE + Wc + B + 2

        ring_rows = {k: state["buf"][k][fifo_slot] for k in state["buf"]}
        ring_rows[TS_KEY] = jnp.where(expire_ring, exp_ts_ring, ring_rows[TS_KEY])
        batch_exp_rows = {k: cols[k] for k in keys}
        batch_exp_rows[TS_KEY] = exp_ts_batch

        parts = [
            (ring_rows, jnp.full((Wc,), EXPIRED, jnp.int8), expire_ring, ring_okey),
            (batch_exp_rows, jnp.full((B,), EXPIRED, jnp.int8), batch_exp, batch_okey),
            ({k: cols[k] for k in keys}, cols[TYPE_KEY], valid_cur, cur_okey),
        ]
        out, _ = _order_emit(parts)

        # ring update: append inserted rows, advance the expired prefix
        rank, n_ins = _insert_ranks(valid_cur)
        seq = total0 + rank
        write = valid_cur & (rank >= n_ins - Wc)
        slot = jnp.where(write, (seq % Wc).astype(jnp.int32), Wc)
        new_buf = {k: state["buf"][k].at[slot].set(cols[k], mode="drop") for k in state["buf"]}
        new_total = total0 + n_ins
        n_batch_exp = jnp.sum(batch_exp.astype(jnp.int64))
        new_exp = exp0 + n_exp_ring + n_batch_exp

        live = new_total - new_exp
        out[OVERFLOW_KEY] = (live > Wc).astype(jnp.int32)
        if self.external:
            out[NOTIFY_KEY] = jnp.int64(-1)
        else:
            fifo2 = new_exp + jnp.arange(Wc, dtype=jnp.int64)
            occ2 = fifo2 < new_total
            ts2 = new_buf[TS_KEY][(fifo2 % Wc).astype(jnp.int32)]
            nxt_notify = jnp.min(jnp.where(occ2, ts2 + t, _BIG))
            out[NOTIFY_KEY] = jnp.where(jnp.any(occ2), nxt_notify, jnp.int64(-1))

        return {"buf": new_buf, "total": new_total, "expired_upto": new_exp}, out

    def contents(self, state):
        Wc = self.capacity
        total = state["total"]
        # slot j holds the newest sequence s < total with s % Wc == j
        j = jnp.arange(Wc, dtype=jnp.int64)
        s_j = total - 1 - ((total - 1 - j) % Wc)
        valid = (total > 0) & (s_j >= 0) & (s_j >= state["expired_upto"])
        return dict(state["buf"]), valid


def _next_valid_index(valid):
    """For each i: the smallest valid index j > i (B if none)."""
    B = valid.shape[0]
    idx = jnp.where(valid, jnp.arange(B, dtype=jnp.int64), jnp.int64(2 * B))
    suffix_min = lax.cummin(idx[::-1])[::-1]
    nxt = jnp.concatenate([suffix_min[1:], jnp.full((1,), 2 * B, jnp.int64)])
    return jnp.minimum(nxt, B)


def _first_later_covering(ts, valid, t):
    """First valid row j > i with ts_j >= ts_i + t (B if none)."""
    B = ts.shape[0]
    idx = jnp.arange(B)
    later = (idx[None, :] > idx[:, None]) & valid[None, :]
    ge = later & (ts[None, :] >= ts[:, None] + t)
    return jnp.where(jnp.any(ge, axis=1), jnp.argmax(ge, axis=1), B)


# ------------------------------------------------------------- lengthBatch

class LengthBatchWindowStage(WindowStage):
    """Tumbling count window; flushes exactly at count boundaries, possibly
    several times within one device batch. Each flush emits
    [EXPIRED(prev flush, ts=now), RESET, CURRENT rows].

    ``stream_current`` mirrors the reference's streamCurrentEvents overload
    (``LengthBatchWindowProcessor.processStreamCurrentEvents``): every
    arrival is emitted as CURRENT immediately; when the (W+1)-th event of a
    cycle arrives, [EXPIRED(previous W events, ts=now), RESET] are emitted
    just before it."""

    batch_mode = True

    def __init__(self, length: int, col_specs: Dict[str, np.dtype], expired_needed: bool = True,
                 stream_current: bool = False):
        if length < 0:
            raise CompileError("lengthBatch window needs a non-negative length")
        self.length = length
        self.col_specs = col_specs
        self.expired_needed = expired_needed
        self.stream_current = stream_current

    def _apply_zero(self, state, cols, ctx):
        """length 0: every arrival is its own instant batch —
        [CURRENT, EXPIRED(clone, ts=now), RESET] per event
        (``LengthBatchWindowProcessor.processLengthZeroBatch``)."""
        keys = _data_keys(cols)
        B = cols[VALID_KEY].shape[0]
        now = jnp.int64(ctx["current_time"])
        valid_cur = cols[VALID_KEY] & (cols[TYPE_KEY] == CURRENT)
        rank, _n = _insert_ranks(valid_cur)

        parts = [({k: cols[k] for k in keys},
                  jnp.full((B,), CURRENT, jnp.int8), valid_cur, rank * 3)]
        if self.expired_needed:
            exp = {k: cols[k] for k in keys}
            exp[TS_KEY] = jnp.where(valid_cur, now, cols[TS_KEY])
            parts.append((exp, jnp.full((B,), EXPIRED, jnp.int8), valid_cur, rank * 3 + 1))
        reset_rows = _zero_rows(cols, B)
        reset_rows[TS_KEY] = jnp.where(valid_cur, now, jnp.int64(0))
        parts.append((reset_rows, jnp.full((B,), RESET, jnp.int8), valid_cur, rank * 3 + 2))
        out, okeys = _order_emit(parts)
        out[FLUSH_KEY] = jnp.where(okeys == _BIG, 0, okeys // 3).astype(jnp.int32)
        return state, out

    def init_state(self, num_keys: int = 1) -> dict:
        W = self.length
        zero = lambda: {k: jnp.zeros((W,), dt) for k, dt in self.col_specs.items()}  # noqa: E731
        return {"cur": zero(), "prev": zero(),
                "count": jnp.int64(0), "prev_count": jnp.int64(0)}

    def _apply_stream(self, state, cols, ctx):
        """streamCurrentEvents mode: CURRENT rows pass through at arrival;
        each cycle boundary (an arrival at seq ≡ 0 mod W, seq > 0) first
        emits [EXPIRED(previous W events, ts=now), RESET]."""
        W = self.length
        keys = _data_keys(cols)
        B = cols[VALID_KEY].shape[0]
        now = jnp.int64(ctx["current_time"])
        valid_cur = cols[VALID_KEY] & (cols[TYPE_KEY] == CURRENT)

        count0 = state["count"]           # events buffered since last boundary
        rank, n_ins = _insert_ranks(valid_cur)
        seq = count0 + rank               # position since the last boundary
        total_after = count0 + n_ins
        S = jnp.int64(W + 2)              # per-trigger span: W expired, RESET, CURRENT
        lead = jnp.arange(W, dtype=jnp.int64)

        parts = []
        if self.expired_needed:
            # buffered rows all expire at the first boundary (trigger rank
            # r0 = W - count0), batch rows at the boundary closing their cycle
            r0 = jnp.int64(W) - count0
            buf_valid = (lead < count0) & (n_ins > r0)
            buf_rows = {k: state["cur"][k][lead.astype(jnp.int32)] for k in state["cur"]}
            buf_rows[TS_KEY] = jnp.where(buf_valid, now, buf_rows[TS_KEY])
            parts.append((buf_rows, jnp.full((W,), EXPIRED, jnp.int8), buf_valid, r0 * S + lead))

            rb = (seq // W + 1) * W - count0      # trigger rank of the closing boundary
            bexp_valid = valid_cur & (n_ins > rb)
            bexp = {k: cols[k] for k in keys}
            bexp[TS_KEY] = jnp.where(bexp_valid, now, cols[TS_KEY])
            parts.append((bexp, jnp.full((B,), EXPIRED, jnp.int8), bexp_valid, rb * S + seq % W))

        is_bnd = valid_cur & (seq > 0) & (seq % W == 0)
        reset_rows = _zero_rows(cols, B)
        reset_rows[TS_KEY] = jnp.where(is_bnd, now, jnp.int64(0))
        parts.append((reset_rows, jnp.full((B,), RESET, jnp.int8), is_bnd, rank * S + W))

        parts.append(({k: cols[k] for k in keys}, jnp.full((B,), CURRENT, jnp.int8),
                      valid_cur, rank * S + W + 1))

        out, okeys = _order_emit(parts)
        # selector chunk segmentation (QuerySelector batch dedup): each
        # arrival is one reference chunk — at a boundary that chunk holds
        # [expired×W, RESET, current] and collapses to its LAST type-valid
        # row (the current for `all events`, the last expired for
        # `expired events` — LengthBatchWindowTestCase test21/test12)
        out[FLUSH_KEY] = jnp.where(okeys == _BIG, 0, okeys // S).astype(jnp.int32)

        # state: rows of the still-open cycle stay buffered
        new_count = jnp.where(total_after > 0,
                              total_after - W * ((total_after - 1) // W),
                              jnp.int64(0))
        base_seq = total_after - new_count
        keep_old = base_seq == 0
        is_rem = valid_cur & (seq >= base_seq)
        slot = jnp.where(is_rem, (seq - base_seq).astype(jnp.int32), W)
        new_cur = {}
        for k in state["cur"]:
            base = jnp.where(keep_old, state["cur"][k], jnp.zeros_like(state["cur"][k]))
            new_cur[k] = base.at[slot].set(cols[k], mode="drop")
        return {"cur": new_cur, "prev": state["prev"],
                "count": new_count, "prev_count": state["prev_count"]}, out

    def apply(self, state, cols, ctx):
        if self.length == 0:
            return self._apply_zero(state, cols, ctx)
        if self.stream_current:
            return self._apply_stream(state, cols, ctx)
        W = self.length
        keys = _data_keys(cols)
        B = cols[VALID_KEY].shape[0]
        now = jnp.int64(ctx["current_time"])
        valid_cur = cols[VALID_KEY] & (cols[TYPE_KEY] == CURRENT)

        count0 = state["count"]
        rank, n_ins = _insert_ranks(valid_cur)
        seq = count0 + rank               # position in the accumulating stream
        total_after = count0 + n_ins
        n_flush = total_after // W
        flush_id = seq // W               # which flush a row's CURRENT belongs to
        pos_in_flush = seq % W

        # per-flush emission spans: flush f occupies [f*S, (f+1)*S):
        #   expired block at +0..W-1, RESET at +W, currents at +W+1..2W
        S = jnp.int64(2 * W + 2)
        lead = jnp.arange(W, dtype=jnp.int64)

        parts = []
        if self.expired_needed:
            # pre-step prev flush expires in flush 0
            prev_valid = (lead < state["prev_count"]) & (n_flush > 0)
            prev_rows = {k: state["prev"][k][lead.astype(jnp.int32)] for k in state["prev"]}
            prev_rows[TS_KEY] = jnp.where(prev_valid, now, prev_rows[TS_KEY])
            parts.append((prev_rows, jnp.full((W,), EXPIRED, jnp.int8), prev_valid, lead))
            # leftover buffered rows (in flush 0) expire in flush 1
            lead_exp_valid = (lead < count0) & (n_flush > 1)
            lead_exp = {k: state["cur"][k][lead.astype(jnp.int32)] for k in state["cur"]}
            lead_exp[TS_KEY] = jnp.where(lead_exp_valid, now, lead_exp[TS_KEY])
            parts.append((lead_exp, jnp.full((W,), EXPIRED, jnp.int8), lead_exp_valid, S + lead))
            # batch rows of flush f expire in flush f+1
            bexp_valid = valid_cur & (flush_id + 1 < n_flush)
            bexp = {k: cols[k] for k in keys}
            bexp[TS_KEY] = jnp.where(bexp_valid, now, cols[TS_KEY])
            parts.append((bexp, jnp.full((B,), EXPIRED, jnp.int8), bexp_valid,
                          (flush_id + 1) * S + pos_in_flush))

        n_reset_cap = B // W + 2
        ridx = jnp.arange(n_reset_cap, dtype=jnp.int64)
        reset_valid = ridx < n_flush
        reset_rows = _zero_rows(cols, n_reset_cap)
        reset_rows[TS_KEY] = jnp.where(reset_valid, now, jnp.int64(0))
        parts.append((reset_rows, jnp.full((n_reset_cap,), RESET, jnp.int8),
                      reset_valid, ridx * S + W))

        # currents: leftover buffer rows flush in flush 0...
        lead_valid = (lead < count0) & (n_flush > 0)
        lead_rows = {k: state["cur"][k][lead.astype(jnp.int32)] for k in state["cur"]}
        parts.append((lead_rows, jnp.full((W,), CURRENT, jnp.int8), lead_valid, W + 1 + lead))
        # ...batch rows of completed flushes flush now
        emitted_now = valid_cur & (flush_id < n_flush)
        parts.append(({k: cols[k] for k in keys}, jnp.full((B,), CURRENT, jnp.int8),
                      emitted_now, flush_id * S + W + 1 + pos_in_flush))

        out, okeys = _order_emit(parts)
        out[FLUSH_KEY] = jnp.where(okeys == _BIG, 0, okeys // S).astype(jnp.int32)

        # state update: remainder rows -> cur buffer
        keep_old = n_flush == 0
        rem_slot_val = jnp.where(keep_old, seq, seq - n_flush * W)
        is_rem = valid_cur & (flush_id == n_flush)
        slot = jnp.where(is_rem, rem_slot_val.astype(jnp.int32), W)
        new_cur = {}
        for k in state["cur"]:
            base = jnp.where(keep_old, state["cur"][k], jnp.zeros_like(state["cur"][k]))
            new_cur[k] = base.at[slot].set(cols[k], mode="drop")
        new_count = total_after - n_flush * W

        # prev buffer <- rows of the last completed flush
        last_flush = n_flush - 1
        in_last = valid_cur & (flush_id == last_flush)
        lead_in_last = (lead < count0) & (n_flush == 1)
        pslot_lead = jnp.where(lead_in_last, lead.astype(jnp.int32), W)
        pslot_batch = jnp.where(in_last, pos_in_flush.astype(jnp.int32), W)
        new_prev = {}
        for k in state["prev"]:
            base = jnp.where(n_flush > 0, jnp.zeros_like(state["prev"][k]), state["prev"][k])
            base = base.at[pslot_lead].set(state["cur"][k], mode="drop")
            base = base.at[pslot_batch].set(cols[k], mode="drop")
            new_prev[k] = base
        new_prev_count = jnp.where(n_flush > 0, jnp.int64(W), state["prev_count"])

        return {"cur": new_cur, "prev": new_prev,
                "count": new_count, "prev_count": new_prev_count}, out

    def contents(self, state):
        """Join/find probes hit the reference's ``expiredEventQueue``
        (LengthBatchWindowProcessor.java:288-299): the LAST COMPLETED batch
        in full-batch mode; the current cycle's arrivals in
        streamCurrentEvents mode (clones queue on arrival there)."""
        if self.length == 0:
            return dict(state["cur"]), jnp.zeros((0,), bool)
        if self.stream_current:
            valid = jnp.arange(self.length, dtype=jnp.int64) < state["count"]
            return dict(state["cur"]), valid
        valid = jnp.arange(self.length, dtype=jnp.int64) < state["prev_count"]
        return dict(state["prev"]), valid


# --------------------------------------------------------------- timeBatch

class TimeBatchWindowStage(WindowStage):
    """Tumbling time window; flush check once per chunk (arriving rows join
    the flushing batch), exactly as the reference processes chunks.

    ``stream_current`` mirrors the reference's streamCurrentEvents overload
    (``TimeBatchWindowProcessor.java:297-335``): CURRENT rows pass through
    at arrival (never queued); each flush emits [EXPIRED(arrivals since the
    last flush, ts=now), RESET] after any currents of the flushing chunk."""

    batch_mode = True
    needs_scheduler = True

    def __init__(self, time_ms: int, col_specs: Dict[str, np.dtype], capacity: int,
                 expired_needed: bool = True, start_time: int = -1,
                 stream_current: bool = False):
        self.time_ms = time_ms
        self.capacity = capacity
        self.col_specs = col_specs
        self.expired_needed = expired_needed
        self.start_time = start_time
        self.stream_current = stream_current

    def init_state(self, num_keys: int = 1) -> dict:
        Wc = self.capacity
        zero = lambda: {k: jnp.zeros((Wc,), dt) for k, dt in self.col_specs.items()}  # noqa: E731
        return {"cur": zero(), "prev": zero(),
                "count": jnp.int64(0), "prev_count": jnp.int64(0),
                "next_emit": jnp.int64(-1)}

    def apply(self, state, cols, ctx):
        Wc = self.capacity
        t = jnp.int64(self.time_ms)
        keys = _data_keys(cols)
        now = jnp.int64(ctx["current_time"])
        valid_cur = cols[VALID_KEY] & (cols[TYPE_KEY] == CURRENT)

        # boundary init on first chunk (TimeBatchWindowProcessor:266-276)
        next_emit0 = state["next_emit"]
        if self.start_time >= 0:
            st = jnp.int64(self.start_time)
            init_emit = now + (t - ((now - st) % t))
        else:
            init_emit = now + t
        next_emit = jnp.where(next_emit0 < 0, init_emit, next_emit0)
        send = now >= next_emit
        next_emit = jnp.where(send, next_emit + t, next_emit)

        count0 = state["count"]
        rank, n_ins = _insert_ranks(valid_cur)
        slot = jnp.where(valid_cur, (count0 + rank).astype(jnp.int32), Wc)
        cur_buf = {k: state["cur"][k].at[slot].set(cols[k], mode="drop") for k in state["cur"]}
        count = count0 + n_ins

        widx = jnp.arange(Wc, dtype=jnp.int64)

        if self.stream_current:
            B = cols[VALID_KEY].shape[0]
            parts = [({k: cols[k] for k in keys},
                      jnp.full((B,), CURRENT, jnp.int8), valid_cur, rank)]
            if self.expired_needed:
                # the whole queue — arrivals before AND inside the flushing
                # chunk — expires at the flush (clones join the queue before
                # it drains, TimeBatchWindowProcessor.java:298-314)
                qrows = {k: cur_buf[k][widx.astype(jnp.int32)] for k in cur_buf}
                q_valid = (widx < count) & send
                qrows[TS_KEY] = jnp.where(q_valid, now, qrows[TS_KEY])
                parts.append((qrows, jnp.full((Wc,), EXPIRED, jnp.int8),
                              q_valid, jnp.int64(B) + widx))
            reset_rows = _zero_rows(cols, 1)
            reset_rows[TS_KEY] = jnp.broadcast_to(now, (1,))
            parts.append((reset_rows, jnp.full((1,), RESET, jnp.int8),
                          jnp.broadcast_to(send & (count > 0), (1,)),
                          jnp.full((1,), jnp.int64(B) + Wc, jnp.int64)))
            out, okeys = _order_emit(parts)
            # chunk ids for the selector's per-chunk collapse: currents are
            # singleton chunks; the flush's EXPIRED rows share one chunk
            out[FLUSH_KEY] = jnp.minimum(okeys, jnp.int64(B)).astype(jnp.int32)
            new_state = {
                "cur": {k: jnp.where(send, jnp.zeros_like(v), v) for k, v in cur_buf.items()},
                "prev": state["prev"],
                "count": jnp.where(send, jnp.int64(0), count),
                "prev_count": state["prev_count"],
                "next_emit": next_emit,
            }
            out[NOTIFY_KEY] = next_emit
            out[OVERFLOW_KEY] = (count > Wc).astype(jnp.int32)
            return new_state, out

        parts = []
        if self.expired_needed:
            prev_valid = (widx < state["prev_count"]) & send
            prev_rows = {k: state["prev"][k][widx.astype(jnp.int32)] for k in state["prev"]}
            prev_rows[TS_KEY] = jnp.where(prev_valid, now, prev_rows[TS_KEY])
            parts.append((prev_rows, jnp.full((Wc,), EXPIRED, jnp.int8), prev_valid, widx))
        reset_rows = _zero_rows(cols, 1)
        reset_rows[TS_KEY] = jnp.broadcast_to(now, (1,))
        parts.append((reset_rows, jnp.full((1,), RESET, jnp.int8),
                      jnp.broadcast_to(send & (count > 0), (1,)), jnp.full((1,), Wc, jnp.int64)))
        cur_valid = (widx < count) & send
        cur_rows = {k: cur_buf[k][widx.astype(jnp.int32)] for k in cur_buf}
        parts.append((cur_rows, jnp.full((Wc,), CURRENT, jnp.int8), cur_valid, Wc + 1 + widx))
        out, _ = _order_emit(parts)
        out[FLUSH_KEY] = jnp.zeros_like(out[TS_KEY], dtype=jnp.int32)

        zero_count = jnp.int64(0)
        # prev (the findable expiredEventQueue): with expired outputs an
        # empty flush drains it (its expireds were just emitted); find-only
        # queries never drain it, so an empty flush RETAINS the last batch
        # for join probes (TimeBatchWindowProcessor flush: the expired
        # drain is gated on outputExpectsExpiredEvents)
        replace_prev = send & (self.expired_needed | (count > 0))
        new_state = {
            "cur": {k: jnp.where(send, jnp.zeros_like(v), v) for k, v in cur_buf.items()},
            "prev": {k: jnp.where(replace_prev, cur_buf[k], state["prev"][k])
                     for k in state["prev"]},
            "count": jnp.where(send, zero_count, count),
            "prev_count": jnp.where(replace_prev, count, state["prev_count"]),
            "next_emit": next_emit,
        }
        out[NOTIFY_KEY] = next_emit
        out[OVERFLOW_KEY] = (count > Wc).astype(jnp.int32)
        return new_state, out

    def contents(self, state):
        """Join/find probes hit the reference's ``expiredEventQueue``
        (TimeBatchWindowProcessor.java:368-380): the last flushed batch in
        full-batch mode; the arrivals since the last flush in
        streamCurrentEvents mode."""
        if self.stream_current:
            valid = jnp.arange(self.capacity, dtype=jnp.int64) < state["count"]
            return dict(state["cur"]), valid
        valid = jnp.arange(self.capacity, dtype=jnp.int64) < state["prev_count"]
        return dict(state["prev"]), valid


class HoppingWindowStage(WindowStage):
    """``hopping(windowTime, hopTime)``: every hop, emit the events of the
    trailing windowTime as a batch (reference HopingWindowProcessor — a
    time batch whose emission period is decoupled from its retention)."""

    batch_mode = True
    needs_scheduler = True

    def __init__(self, window_ms: int, hop_ms: int,
                 col_specs: Dict[str, np.dtype], capacity: int):
        if hop_ms <= 0 or window_ms <= 0:
            raise CompileError("hopping window needs positive window and hop times")
        self.window_ms = window_ms
        self.hop_ms = hop_ms
        self.capacity = capacity
        self.col_specs = col_specs

    def init_state(self, num_keys: int = 1) -> dict:
        Wc = self.capacity
        zero = lambda: {k: jnp.zeros((Wc,), dt) for k, dt in self.col_specs.items()}  # noqa: E731
        return {"buf": zero(), "prev": zero(),
                "total": jnp.int64(0), "expired_upto": jnp.int64(0),
                "prev_count": jnp.int64(0), "next_emit": jnp.int64(-1)}

    def apply(self, state, cols, ctx):
        Wc = self.capacity
        w = jnp.int64(self.window_ms)
        hop = jnp.int64(self.hop_ms)
        keys = _data_keys(cols)
        now = jnp.int64(ctx["current_time"])
        valid_cur = cols[VALID_KEY] & (cols[TYPE_KEY] == CURRENT)

        next_emit0 = state["next_emit"]
        next_emit = jnp.where(next_emit0 < 0, now + hop, next_emit0)
        send = now >= next_emit
        next_emit = jnp.where(send, next_emit + hop, next_emit)

        # append arrivals to the ts-monotone FIFO ring
        total0 = state["total"]
        exp0 = state["expired_upto"]
        rank, n_ins = _insert_ranks(valid_cur)
        slot = jnp.where(valid_cur, ((total0 + rank) % Wc).astype(jnp.int32), Wc)
        buf = {k: state["buf"][k].at[slot].set(cols[k], mode="drop") for k in state["buf"]}
        total = total0 + n_ins

        # live FIFO view; rows older than the trailing window can never be
        # emitted again — drop them from the live range
        widx = jnp.arange(Wc, dtype=jnp.int64)
        fifo_seq = exp0 + widx
        occ = fifo_seq < total
        flat = (fifo_seq % Wc).astype(jnp.int32)
        ring_ts = buf[TS_KEY][flat]
        stale = occ & (ring_ts <= now - w)
        new_exp = exp0 + jnp.sum(stale.astype(jnp.int64))

        in_window = occ & ~stale & send
        cur_rows = {k: buf[k][flat] for k in buf}
        n_emit = jnp.sum(in_window.astype(jnp.int64))

        parts = []
        prev_valid = (widx < state["prev_count"]) & send
        prev_rows = {k: state["prev"][k][widx.astype(jnp.int32)] for k in state["prev"]}
        prev_rows[TS_KEY] = jnp.where(prev_valid, now, prev_rows[TS_KEY])
        parts.append((prev_rows, jnp.full((Wc,), EXPIRED, jnp.int8), prev_valid, widx))
        reset_rows = _zero_rows(cols, 1)
        reset_rows[TS_KEY] = jnp.broadcast_to(now, (1,))
        parts.append((reset_rows, jnp.full((1,), RESET, jnp.int8),
                      jnp.broadcast_to(send & (state["prev_count"] > 0), (1,)),
                      jnp.full((1,), Wc, jnp.int64)))
        parts.append((cur_rows, jnp.full((Wc,), CURRENT, jnp.int8), in_window,
                      Wc + 1 + widx))
        out, _ = _order_emit(parts)
        out[FLUSH_KEY] = jnp.zeros_like(out[TS_KEY], dtype=jnp.int32)

        # the emitted snapshot becomes the next expiry batch (packed)
        emit_rank = jnp.cumsum(in_window.astype(jnp.int64)) - 1
        pslot = jnp.where(in_window, emit_rank.astype(jnp.int32), Wc)
        new_prev = {}
        for k in state["prev"]:
            base = jnp.where(send, jnp.zeros_like(state["prev"][k]), state["prev"][k])
            new_prev[k] = base.at[pslot].set(cur_rows[k], mode="drop")
        new_state = {
            "buf": buf,
            "prev": new_prev,
            "total": total,
            "expired_upto": new_exp,
            "prev_count": jnp.where(send, n_emit, state["prev_count"]),
            "next_emit": next_emit,
        }
        out[NOTIFY_KEY] = next_emit
        out[OVERFLOW_KEY] = ((total - new_exp) > Wc).astype(jnp.int32)
        return new_state, out

    def contents(self, state):
        Wc = self.capacity
        widx = jnp.arange(Wc, dtype=jnp.int64)
        fifo_seq = state["expired_upto"] + widx
        occ = fifo_seq < state["total"]
        flat = (fifo_seq % Wc).astype(jnp.int32)
        return {k: v[flat] for k, v in state["buf"].items()}, occ


# ------------------------------------------------------------------- batch

class BatchWindowStage(WindowStage):
    """`#window.batch([chunkLength])`: each chunk is its own batch; the
    previous chunk expires first. With ``chunkLength`` the arriving chunk is
    split into sub-batches of at most that many rows, each flushed in turn
    (``BatchWindowProcessor.java:91-118``; the trailing partial group still
    flushes at chunk end — nothing carries over unflushed)."""

    batch_mode = True

    def __init__(self, col_specs: Dict[str, np.dtype], capacity: int, expired_needed: bool = True,
                 chunk_length: int = 0):
        if chunk_length < 0:
            raise CompileError(
                "batch window chunkLength must be greater than zero")
        self.col_specs = col_specs
        self.capacity = capacity
        self.expired_needed = expired_needed
        self.chunk_length = chunk_length

    def init_state(self, num_keys: int = 1) -> dict:
        Wc = self.capacity
        prev = {k: jnp.zeros((Wc,), dt) for k, dt in self.col_specs.items()}
        return {"prev": prev, "prev_count": jnp.int64(0)}

    def apply(self, state, cols, ctx):
        Wc = self.capacity
        keys = _data_keys(cols)
        B = cols[VALID_KEY].shape[0]
        now = jnp.int64(ctx["current_time"])
        valid_cur = cols[VALID_KEY] & (cols[TYPE_KEY] == CURRENT)
        any_cur = jnp.any(valid_cur)
        rank, n_ins = _insert_ranks(valid_cur)

        widx = jnp.arange(Wc, dtype=jnp.int64)

        if self.chunk_length:
            # split the chunk into n-row flushes: flush f emits
            # [EXPIRED(flush f-1, or prev chunk for f=0), RESET, CURRENTs]
            n = jnp.int64(self.chunk_length)
            flush_id = rank // n
            n_flush = (n_ins + n - 1) // n
            S = jnp.int64(Wc + 1 + self.chunk_length)

            parts = []
            if self.expired_needed:
                prev_valid = (widx < state["prev_count"]) & any_cur
                prev_rows = {k: state["prev"][k][widx.astype(jnp.int32)] for k in state["prev"]}
                prev_rows[TS_KEY] = jnp.where(prev_valid, now, prev_rows[TS_KEY])
                parts.append((prev_rows, jnp.full((Wc,), EXPIRED, jnp.int8), prev_valid, widx))
                bexp_valid = valid_cur & (flush_id + 1 < n_flush)
                bexp = {k: cols[k] for k in keys}
                bexp[TS_KEY] = jnp.where(bexp_valid, now, cols[TS_KEY])
                parts.append((bexp, jnp.full((B,), EXPIRED, jnp.int8), bexp_valid,
                              (flush_id + 1) * S + rank % n))
            n_reset_cap = B // self.chunk_length + 2
            ridx = jnp.arange(n_reset_cap, dtype=jnp.int64)
            reset_valid = (ridx < n_flush) & ((ridx > 0) | (state["prev_count"] > 0))
            reset_rows = _zero_rows(cols, n_reset_cap)
            reset_rows[TS_KEY] = jnp.where(reset_valid, now, jnp.int64(0))
            parts.append((reset_rows, jnp.full((n_reset_cap,), RESET, jnp.int8),
                          reset_valid, ridx * S + Wc))
            parts.append(({k: cols[k] for k in keys}, jnp.full((B,), CURRENT, jnp.int8),
                          valid_cur, flush_id * S + Wc + 1 + rank % n))
            out, okeys = _order_emit(parts)
            out[FLUSH_KEY] = jnp.where(okeys == _BIG, 0, okeys // S).astype(jnp.int32)

            # prev <- rows of the trailing (possibly partial) flush
            last = n_flush - 1
            base_rank = last * n
            is_last = valid_cur & (flush_id == last)
            slot = jnp.where(is_last, (rank - base_rank).astype(jnp.int32), Wc)
            new_prev = {}
            for k in state["prev"]:
                base = jnp.where(any_cur, jnp.zeros_like(state["prev"][k]), state["prev"][k])
                new_prev[k] = base.at[slot].set(cols[k], mode="drop")
            new_count = jnp.where(any_cur, n_ins - base_rank, state["prev_count"])
            out[OVERFLOW_KEY] = jnp.int32(0)
            return {"prev": new_prev, "prev_count": new_count}, out

        parts = []
        if self.expired_needed:
            prev_valid = (widx < state["prev_count"]) & any_cur
            prev_rows = {k: state["prev"][k][widx.astype(jnp.int32)] for k in state["prev"]}
            prev_rows[TS_KEY] = jnp.where(prev_valid, now, prev_rows[TS_KEY])
            parts.append((prev_rows, jnp.full((Wc,), EXPIRED, jnp.int8), prev_valid, widx))
        reset_rows = _zero_rows(cols, 1)
        reset_rows[TS_KEY] = jnp.broadcast_to(now, (1,))
        parts.append((reset_rows, jnp.full((1,), RESET, jnp.int8),
                      jnp.broadcast_to(any_cur & (state["prev_count"] > 0), (1,)),
                      jnp.full((1,), Wc, jnp.int64)))
        idx = jnp.arange(B, dtype=jnp.int64)
        parts.append(({k: cols[k] for k in keys}, cols[TYPE_KEY], valid_cur, Wc + 1 + idx))
        out, _ = _order_emit(parts)
        out[FLUSH_KEY] = jnp.zeros_like(out[TS_KEY], dtype=jnp.int32)

        slot = jnp.where(valid_cur, rank.astype(jnp.int32), Wc)
        new_prev = {}
        for k in state["prev"]:
            base = jnp.where(any_cur, jnp.zeros_like(state["prev"][k]), state["prev"][k])
            new_prev[k] = base.at[slot].set(cols[k], mode="drop")
        new_count = jnp.where(any_cur, n_ins, state["prev_count"])
        out[OVERFLOW_KEY] = (n_ins > Wc).astype(jnp.int32)
        return {"prev": new_prev, "prev_count": new_count}, out

    def contents(self, state):
        valid = jnp.arange(self.capacity, dtype=jnp.int64) < state["prev_count"]
        return dict(state["prev"]), valid


# -------------------------------------------------------------- timeLength

class TimeLengthWindowStage(WindowStage):
    """Sliding window bounded by time AND count
    (``TimeLengthWindowProcessor``): entries older than t drain on timers;
    when the window holds `length` live entries, each arrival evicts the
    oldest. Both evictions are FIFO-prefix drops, so one ring of exactly
    ``length`` slots suffices. Within-batch time expiry (playback jumps
    inside one chunk) is deferred to the immediately-scheduled timer.
    """

    needs_scheduler = True

    def __init__(self, time_ms: int, length: int, col_specs: Dict[str, np.dtype]):
        if length <= 0:
            raise CompileError("timeLength window needs a positive length")
        self.time_ms = time_ms
        self.length = length
        self.col_specs = col_specs

    def init_state(self, num_keys: int = 1) -> dict:
        L = self.length
        buf = {k: jnp.zeros((L,), dt) for k, dt in self.col_specs.items()}
        return {"buf": buf, "total": jnp.int64(0), "expired_upto": jnp.int64(0)}

    def apply(self, state, cols, ctx):
        L = self.length
        t = jnp.int64(self.time_ms)
        keys = _data_keys(cols)
        B = cols[VALID_KEY].shape[0]
        valid_cur = cols[VALID_KEY] & (cols[TYPE_KEY] == CURRENT)
        now = jnp.int64(ctx["current_time"])
        STRIDE = jnp.int64(L + B + 4)

        total0 = state["total"]
        exp0 = state["expired_upto"]

        # ---- time drain (FIFO prefix), before the batch
        j = jnp.arange(L, dtype=jnp.int64)
        fifo_seq = exp0 + j
        occupied = fifo_seq < total0
        fifo_slot = (fifo_seq % L).astype(jnp.int32)
        ring_ts = state["buf"][TS_KEY][fifo_slot]
        time_exp = occupied & (ring_ts + t <= now)
        n_time = jnp.sum(time_exp.astype(jnp.int64))
        exp1 = exp0 + n_time
        live0 = total0 - exp1

        # ---- length evictions per insert: insert rank r evicts FIFO entry
        # j = live0 + r - L (when >= 0); entry seq exp1 + j
        rank, n_ins = _insert_ranks(valid_cur)
        n_len = jnp.clip(live0 + n_ins - L, 0, n_ins)
        rank_to_row = jnp.zeros((B,), jnp.int32).at[
            jnp.where(valid_cur, rank, B).astype(jnp.int32)
        ].set(jnp.arange(B, dtype=jnp.int32), mode="drop")

        lev_seq = exp1 + j                       # candidate eviction seqs
        lev = (j < n_len) & (lev_seq < total0 + n_ins)
        from_batch = lev_seq >= total0
        batch_row = rank_to_row[jnp.clip(lev_seq - total0, 0, B - 1).astype(jnp.int32)]
        lev_slot = (lev_seq % L).astype(jnp.int32)
        # eviction j precedes the row of insert rank r = L - live0 + j
        lev_rank = jnp.clip(L - live0 + j, 0, B - 1)
        lev_row = rank_to_row[lev_rank.astype(jnp.int32)].astype(jnp.int64)

        time_rows = {k: state["buf"][k][fifo_slot] for k in state["buf"]}
        time_rows[TS_KEY] = jnp.where(time_exp, now, time_rows[TS_KEY])
        lev_rows = {}
        for k in state["buf"]:
            ring_v = state["buf"][k][lev_slot]
            lev_rows[k] = jnp.where(from_batch, cols[k][batch_row], ring_v)
        lev_rows[TS_KEY] = jnp.broadcast_to(now, (L,))

        idx = jnp.arange(B, dtype=jnp.int64)
        parts = [
            (time_rows, jnp.full((L,), EXPIRED, jnp.int8), time_exp, j),
            (lev_rows, jnp.full((L,), EXPIRED, jnp.int8), lev, lev_row * STRIDE + L + j),
            ({k: cols[k] for k in keys}, cols[TYPE_KEY], valid_cur,
             idx * STRIDE + L + B + 2),
        ]
        out, _ = _order_emit(parts)

        # ---- ring update: write the last min(L, n_ins) inserts
        seq = total0 + rank
        write = valid_cur & (rank >= n_ins - L)
        slot = jnp.where(write, (seq % L).astype(jnp.int32), L)
        new_buf = {k: state["buf"][k].at[slot].set(cols[k], mode="drop")
                   for k in state["buf"]}
        new_total = total0 + n_ins
        new_exp = exp1 + n_len

        fifo2 = new_exp + j
        occ2 = fifo2 < new_total
        ts2 = new_buf[TS_KEY][(fifo2 % L).astype(jnp.int32)]
        nxt = jnp.min(jnp.where(occ2, ts2 + t, _BIG))
        out[NOTIFY_KEY] = jnp.where(jnp.any(occ2), nxt, jnp.int64(-1))
        return {"buf": new_buf, "total": new_total, "expired_upto": new_exp}, out

    def contents(self, state):
        L = self.length
        total = state["total"]
        j = jnp.arange(L, dtype=jnp.int64)
        s_j = total - 1 - ((total - 1 - j) % L)
        valid = (total > 0) & (s_j >= 0) & (s_j >= state["expired_upto"])
        return dict(state["buf"]), valid


# ------------------------------------------------------------------- delay

class DelayWindowStage(WindowStage):
    """``delay(t)``: events are held for t, then released downstream as
    CURRENT with the release time as timestamp
    (``DelayWindowProcessor.java:135-143``). Nothing is emitted on arrival."""

    needs_scheduler = True

    def __init__(self, delay_ms: int, col_specs: Dict[str, np.dtype], capacity: int):
        self.delay_ms = delay_ms
        self.capacity = capacity
        self.col_specs = col_specs

    def init_state(self, num_keys: int = 1) -> dict:
        Wc = self.capacity
        buf = {k: jnp.zeros((Wc,), dt) for k, dt in self.col_specs.items()}
        return {"buf": buf, "total": jnp.int64(0), "released_upto": jnp.int64(0)}

    def apply(self, state, cols, ctx):
        Wc = self.capacity
        d = jnp.int64(self.delay_ms)
        valid_cur = cols[VALID_KEY] & (cols[TYPE_KEY] == CURRENT)
        now = jnp.int64(ctx["current_time"])

        total0 = state["total"]
        rel0 = state["released_upto"]
        j = jnp.arange(Wc, dtype=jnp.int64)
        fifo_seq = rel0 + j
        occupied = fifo_seq < total0
        fifo_slot = (fifo_seq % Wc).astype(jnp.int32)
        ring_ts = state["buf"][TS_KEY][fifo_slot]
        release = occupied & (ring_ts + d <= now)
        n_rel = jnp.sum(release.astype(jnp.int64))

        rel_rows = {k: state["buf"][k][fifo_slot] for k in state["buf"]}
        rel_rows[TS_KEY] = jnp.where(release, now, rel_rows[TS_KEY])
        out, _ = _order_emit([
            (rel_rows, jnp.full((Wc,), CURRENT, jnp.int8), release, j),
        ])

        rank, n_ins = _insert_ranks(valid_cur)
        seq = total0 + rank
        write = valid_cur & (rank >= n_ins - Wc)
        slot = jnp.where(write, (seq % Wc).astype(jnp.int32), Wc)
        new_buf = {k: state["buf"][k].at[slot].set(cols[k], mode="drop")
                   for k in state["buf"]}
        new_total = total0 + n_ins
        new_rel = rel0 + n_rel

        out[OVERFLOW_KEY] = (new_total - new_rel > Wc).astype(jnp.int32)
        fifo2 = new_rel + j
        occ2 = fifo2 < new_total
        ts2 = new_buf[TS_KEY][(fifo2 % Wc).astype(jnp.int32)]
        nxt = jnp.min(jnp.where(occ2, ts2 + d, _BIG))
        out[NOTIFY_KEY] = jnp.where(jnp.any(occ2), nxt, jnp.int64(-1))
        return {"buf": new_buf, "total": new_total, "released_upto": new_rel}, out


# -------------------------------------------------------- externalTimeBatch

class ExternalTimeBatchWindowStage(WindowStage):
    """Tumbling batches by an event-time attribute
    (``ExternalTimeBatchWindowProcessor``): when an event's time crosses the
    window end, the accumulated batch flushes as CURRENT (previous batch as
    EXPIRED + RESET) and the window slides by whole multiples of t. Several
    flushes can happen inside one chunk."""

    batch_mode = True

    def __init__(self, ts_fn, time_ms: int, col_specs: Dict[str, np.dtype],
                 capacity: int, expired_needed: bool = True,
                 start_time: int = -1, timeout: int = 0):
        self.expired_needed = expired_needed
        self.ts_fn = ts_fn          # compiled expr for the time attribute
        self.time_ms = time_ms
        self.capacity = capacity
        self.col_specs = col_specs
        self.start_time = start_time
        # timeout > 0: flush the open batch when no event arrives for
        # `timeout` ms of runtime-clock time (scheduler-driven); the window
        # end does NOT advance, and the next event-time crossing APPENDS to
        # the already-flushed output instead of re-expiring it
        # (ExternalTimeBatchWindowProcessor.java:256-307 timer path)
        self.timeout = timeout
        self.needs_scheduler = timeout > 0

    def init_state(self, num_keys: int = 1) -> dict:
        Wc = self.capacity
        zero = lambda: {k: jnp.zeros((Wc,), dt) for k, dt in self.col_specs.items()}  # noqa: E731
        return {"cur": zero(), "prev": zero(),
                "count": jnp.int64(0), "prev_count": jnp.int64(0),
                "end": jnp.int64(-1),
                "flushed": jnp.bool_(False), "last_sched": jnp.int64(-1)}

    def apply(self, state, cols, ctx):
        Wc = self.capacity
        t = jnp.int64(self.time_ms)
        keys = _data_keys(cols)
        B = cols[VALID_KEY].shape[0]
        now = jnp.int64(ctx["current_time"])
        valid_cur = cols[VALID_KEY] & (cols[TYPE_KEY] == CURRENT)

        tsv, _m = self.ts_fn(cols, ctx)
        tsv = jnp.asarray(tsv).astype(jnp.int64)
        tsv = jnp.broadcast_to(tsv, (B,))

        # window end: first event initializes it (startTime anchors the grid)
        first_ts = jnp.max(jnp.where(
            valid_cur & (jnp.cumsum(valid_cur) == 1), tsv, jnp.int64(0)))
        if self.start_time >= 0:
            st = jnp.int64(self.start_time)
            init_end = first_ts - jnp.maximum(first_ts - st, 0) % t + t
        else:
            init_end = first_ts + t
        end0 = jnp.where(state["end"] < 0, init_end, state["end"])

        # Grid distance per row (how many whole windows past end0 its ts
        # lies), monotone-ized against out-of-order timestamps. Flushes are
        # ORDINAL: one per crossing event, regardless of how far the time
        # jumped — the reference emits a single flush and snaps endTime to
        # cover the event (ExternalTimeBatchWindowProcessor.java:285-297),
        # never synthesizing empty intermediate batches. b_i = the ordinal
        # batch a row belongs to (0 = the carried open window).
        raw_b = jnp.where(tsv >= end0, (tsv - end0) // t + 1, 0)
        rawm = lax.cummax(jnp.where(valid_cur, raw_b, jnp.int64(0)))
        prev_rawm = jnp.concatenate([jnp.zeros((1,), jnp.int64), rawm[:-1]])
        jump = valid_cur & (rawm > prev_rawm)
        b_i = jnp.cumsum(jump.astype(jnp.int64))
        n_flush = b_i[B - 1]
        max_raw = rawm[B - 1]             # grid distance the end advances by

        count0 = state["count"]
        flushed0 = state["flushed"]
        last_sched0 = state["last_sched"]
        if self.timeout > 0:
            # timer-driven flush: no event arrived within `timeout`
            has_timer = jnp.any(cols[VALID_KEY] & (cols[TYPE_KEY] == TIMER))
            due = (has_timer & (last_sched0 >= 0) & (now >= last_sched0)
                   & (state["end"] >= 0) & ((count0 > 0) | ~flushed0)
                   & (n_flush == 0))
        else:
            due = jnp.bool_(False)
        n_flush_eff = jnp.where(due, jnp.int64(1), n_flush)
        # flush 1 appends to the already-timeout-flushed batch: its prev
        # expiry and RESET are suppressed, prev grows instead of replacing
        append1 = flushed0 & (n_flush_eff > 0)
        rank, n_ins = _insert_ranks(valid_cur)
        pos = rank  # arrival position among the batch's inserts

        # flush-k span layout (k >= 1): expired [0, Wc+B), RESET at Wc+B,
        # currents [Wc+B+1, 2Wc+2B+1)
        S = jnp.int64(2 * Wc + 2 * B + 2)
        RESET_OFF = jnp.int64(Wc + B)
        CUR_OFF = jnp.int64(Wc + B + 1)
        lead = jnp.arange(Wc, dtype=jnp.int64)
        parts = []
        # prev state buffer expires at flush 1 — except in append mode,
        # where the appended output IS the prev batch continued, so prev
        # expires together with it at flush 2 (if the chunk crosses twice)
        prev_exp_flush = jnp.where(append1, jnp.int64(2), jnp.int64(1))
        prev_valid = (lead < state["prev_count"]) & (n_flush_eff >= prev_exp_flush)
        prev_rows = {k: state["prev"][k][lead.astype(jnp.int32)] for k in state["prev"]}
        prev_rows[TS_KEY] = jnp.where(prev_valid, now, prev_rows[TS_KEY])
        parts.append((prev_rows, jnp.full((Wc,), EXPIRED, jnp.int8), prev_valid,
                      prev_exp_flush * S + lead))
        # carry-over cur buffer (window 0): CURRENT at flush 1, EXPIRED at flush 2
        carry_valid = (lead < count0) & (n_flush_eff > 0)
        carry_rows = {k: state["cur"][k][lead.astype(jnp.int32)] for k in state["cur"]}
        parts.append((carry_rows, jnp.full((Wc,), CURRENT, jnp.int8), carry_valid,
                      S + CUR_OFF + lead))
        carry_exp_valid = (lead < count0) & (n_flush_eff > 1)
        carry_exp = dict(carry_rows)
        carry_exp[TS_KEY] = jnp.where(carry_exp_valid, now, carry_exp[TS_KEY])
        parts.append((carry_exp, jnp.full((Wc,), EXPIRED, jnp.int8), carry_exp_valid,
                      2 * S + lead))
        # batch rows of window k: CURRENT at flush k+1, EXPIRED at flush k+2
        cur_valid = valid_cur & (b_i < n_flush_eff)
        parts.append(({k: cols[k] for k in keys}, jnp.full((B,), CURRENT, jnp.int8),
                      cur_valid, (b_i + 1) * S + CUR_OFF + Wc + pos))
        bexp_valid = valid_cur & (b_i + 1 < n_flush_eff)
        bexp = {k: cols[k] for k in keys}
        bexp[TS_KEY] = jnp.where(bexp_valid, now, cols[TS_KEY])
        parts.append((bexp, jnp.full((B,), EXPIRED, jnp.int8), bexp_valid,
                      (b_i + 2) * S + Wc + pos))
        # one RESET per flush, between that flush's expired and currents
        n_reset_cap = B + 2
        ridx = jnp.arange(n_reset_cap, dtype=jnp.int64)
        reset_valid = (ridx >= 1) & (ridx <= n_flush_eff) & ~(append1 & (ridx == 1))
        reset_rows = _zero_rows(cols, n_reset_cap)
        reset_rows[TS_KEY] = jnp.where(reset_valid, now, jnp.int64(0))
        parts.append((reset_rows, jnp.full((n_reset_cap,), RESET, jnp.int8),
                      reset_valid, ridx * S + RESET_OFF))

        out, okeys = _order_emit(parts)
        out[FLUSH_KEY] = jnp.where(okeys == _BIG, 0, okeys // S).astype(jnp.int32)

        # ---- state update
        keep_old = n_flush_eff == 0
        is_rem = valid_cur & (b_i == n_flush_eff)          # open window rows
        rem_rank = jnp.cumsum(is_rem.astype(jnp.int64)) - 1
        base_cnt = jnp.where(keep_old, count0, 0)
        slot = jnp.where(is_rem, (base_cnt + rem_rank).astype(jnp.int32), Wc)
        new_cur = {}
        for k in state["cur"]:
            base = jnp.where(keep_old, state["cur"][k], jnp.zeros_like(state["cur"][k]))
            new_cur[k] = base.at[slot].set(cols[k], mode="drop")
        n_rem = jnp.sum(is_rem.astype(jnp.int64))
        new_count = base_cnt + n_rem

        # prev <- window n_flush_eff-1 (carry buffer if n_flush_eff == 1 and no batch
        # rows in window 0... both can contribute: carry + batch B==0 rows)
        in_last = valid_cur & (b_i == n_flush_eff - 1) & (n_flush_eff > 0)
        last_rank = jnp.cumsum(in_last.astype(jnp.int64)) - 1
        carry_in_last = (lead < count0) & (n_flush_eff == 1)
        # append mode: the flushed batch is already in prev — grow it
        app = append1 & (n_flush_eff == 1)
        app_off = jnp.where(app, state["prev_count"], 0).astype(jnp.int32)
        pslot_carry = jnp.where(carry_in_last, app_off + lead.astype(jnp.int32), Wc)
        n_carry_last = jnp.where(n_flush_eff == 1, count0, 0)
        pslot_batch = jnp.where(
            in_last, app_off + (n_carry_last + last_rank).astype(jnp.int32), Wc)
        new_prev = {}
        for k in state["prev"]:
            base = jnp.where((n_flush_eff > 0) & ~app,
                             jnp.zeros_like(state["prev"][k]), state["prev"][k])
            base = base.at[pslot_carry].set(state["cur"][k], mode="drop")
            base = base.at[pslot_batch].set(cols[k], mode="drop")
            new_prev[k] = base
        n_last = jnp.sum(in_last.astype(jnp.int64)) + n_carry_last
        new_prev_count = jnp.where(
            n_flush_eff > 0,
            n_last + jnp.where(app, state["prev_count"], 0),
            state["prev_count"])

        any_first = jnp.any(valid_cur)
        new_end = jnp.where(state["end"] < 0,
                            jnp.where(any_first, end0 + max_raw * t, jnp.int64(-1)),
                            end0 + max_raw * t)
        out[OVERFLOW_KEY] = ((new_count > Wc) | (new_prev_count > Wc)).astype(jnp.int32)
        new_flushed = jnp.where(n_flush > 0, jnp.bool_(False),
                                jnp.where(due, jnp.bool_(True), flushed0))
        new_sched = last_sched0
        if self.timeout > 0:
            # a firing timer ALWAYS advances the schedule, due or not —
            # the reference's timer branch reschedules unconditionally
            # (ExternalTimeBatchWindowProcessor.java:270-274); leaving a
            # stale last_sched <= now would re-notify the same past instant
            # and spin the playback sweep forever
            timer_fired = has_timer & (last_sched0 >= 0) & (now >= last_sched0)
            resched = (due | timer_fired | (n_flush > 0)
                       | ((state["end"] < 0) & any_first))
            new_sched = jnp.where(resched, now + jnp.int64(self.timeout),
                                  last_sched0)
            out[NOTIFY_KEY] = jnp.where(new_sched >= 0, new_sched, jnp.int64(-1))
        return {"cur": new_cur, "prev": new_prev, "count": new_count,
                "prev_count": new_prev_count, "end": new_end,
                "flushed": new_flushed, "last_sched": new_sched}, out

    def contents(self, state):
        valid = jnp.arange(self.capacity, dtype=jnp.int64) < state["count"]
        return dict(state["cur"]), valid


# ----------------------------------------------------------------- factory

def _external_ts_key(window, input_def) -> str:
    """externalTime clock column: must be a plain LONG attribute reference
    (anything else fails app creation, as in the reference processor)."""
    from siddhi_tpu.query_api.expressions import Variable

    p0 = window.parameters[0] if window.parameters else None
    if isinstance(p0, Variable):
        attr = input_def.attribute(p0.attribute_name)
        if attr.type != AttrType.LONG:
            raise CompileError(
                "externalTime timestamp attribute must be long (ms epoch)")
        return attr.name
    raise CompileError(
        f"{window.name} window's first parameter must be a long attribute "
        "reference (the external timestamp)")


def window_col_specs(input_def, extra: Tuple[str, ...] = ()) -> Dict[str, np.dtype]:
    """Column dtypes a window ring buffer must carry for a stream: every
    attribute + its null mask, the timestamp, and reserved id columns."""
    from siddhi_tpu.ops.types import dtype_of

    col_specs: Dict[str, np.dtype] = {}
    for a in input_def.attributes:
        col_specs[a.name] = dtype_of(a.type)
        col_specs[a.name + "?"] = np.bool_
    col_specs[TS_KEY] = np.int64
    col_specs["__gk__"] = np.int32
    for name in extra:
        col_specs[name] = np.int32
    return col_specs


def create_window_stage(window: Window, input_def, resolver, app_context,
                        expired_needed: bool = True) -> WindowStage:
    """Build a window stage from a ``#window.<name>(params)`` handler — the
    factory role of reference ``SingleInputStreamParser.generateProcessor``
    plus each window's ``init`` validation.

    ``expired_needed=False`` mirrors the reference's
    outputExpectsExpiredEvents=false: batch windows skip expired emission
    and their findable queue is never drained by empty flushes (join sides
    of `insert into` queries keep probing the last non-empty batch)."""
    name = window.name.lower()
    col_specs = window_col_specs(input_def)

    capacity = getattr(app_context, "window_capacity", 4096)

    if name == "length":
        _expect_arity(window, 1, 1)
        return LengthWindowStage(_int_const_param(window, 0, "length"), col_specs)
    if name == "lengthbatch":
        # lengthBatch(length[, streamCurrentEvents])
        _expect_arity(window, 1, 2)
        stream_current = False
        if len(window.parameters) == 2:
            stream_current = _bool_const_param(window, 1, "streamCurrentEvents")
        return LengthBatchWindowStage(_int_const_param(window, 0, "length"), col_specs,
                                      expired_needed=expired_needed,
                                      stream_current=stream_current)
    if name == "time":
        _expect_arity(window, 1, 1)
        return TimeWindowStage(_int_const_param(window, 0, "time"), col_specs, capacity)
    if name == "externaltime":
        # externalTime(tsAttr, time) — expiry driven by the named
        # long timestamp attribute
        _expect_arity(window, 2, 2)
        ts_key = _external_ts_key(window, input_def)
        return TimeWindowStage(_int_const_param(window, 1, "time"), col_specs, capacity,
                               external=True, ts_key=ts_key)
    if name == "timebatch":
        # overloads (TimeBatchWindowProcessor.init): (time),
        # (time, startTime int/long), (time, streamCurrentEvents bool),
        # (time, startTime, streamCurrentEvents)
        _expect_arity(window, 1, 3)
        start_time = -1
        stream_current = False
        if len(window.parameters) == 2:
            p1 = window.parameters[1]
            if isinstance(p1, Constant) and isinstance(p1.value, bool):
                stream_current = p1.value
            elif (isinstance(p1, TimeConstant)
                  or (isinstance(p1, Constant)
                      and p1.type in (AttrType.INT, AttrType.LONG))):
                start_time = int(p1.value)
            else:
                raise CompileError(
                    "timeBatch second parameter must be an int/long startTime "
                    "or a bool streamCurrentEvents constant")
        elif len(window.parameters) == 3:
            start_time = _int_const_param(window, 1, "startTime")
            stream_current = _bool_const_param(window, 2, "streamCurrentEvents")
        return TimeBatchWindowStage(_int_const_param(window, 0, "time"), col_specs,
                                    capacity, expired_needed=expired_needed,
                                    start_time=start_time,
                                    stream_current=stream_current)
    if name == "batch":
        # batch([chunkLength]) — BatchWindowProcessor.java:107-118
        _expect_arity(window, 0, 1)
        chunk_length = 0
        if window.parameters:
            chunk_length = _int_const_param(window, 0, "chunkLength")
        return BatchWindowStage(col_specs, capacity, expired_needed=expired_needed,
                                chunk_length=chunk_length)
    if name == "timelength":
        _expect_arity(window, 2, 2)
        return TimeLengthWindowStage(_int_const_param(window, 0, "time"),
                                     _int_const_param(window, 1, "length"), col_specs)
    if name == "delay":
        _expect_arity(window, 1, 1)
        return DelayWindowStage(_int_const_param(window, 0, "delay"), col_specs, capacity)
    if name == "externaltimebatch":
        # externalTimeBatch(tsAttr, time[, startTime[, timeout]])
        from siddhi_tpu.ops.expressions import compile_expr

        _expect_arity(window, 2, 4)
        ts_fn, _t = compile_expr(window.parameters[0], resolver)
        start_time = -1
        if len(window.parameters) >= 3:
            p = window.parameters[2]
            if not isinstance(p, (Constant, TimeConstant)):
                raise CompileError(
                    "externalTimeBatch startTime must be a constant")
            start_time = int(p.value)
        timeout = 0
        if len(window.parameters) >= 4:
            timeout = _int_const_param(window, 3, "timeout")
        return ExternalTimeBatchWindowStage(
            ts_fn, _int_const_param(window, 1, "time"), col_specs, capacity,
            expired_needed=expired_needed, start_time=start_time,
            timeout=timeout)
    if name == "hopping":
        _expect_arity(window, 2, 2)
        return HoppingWindowStage(
            _int_const_param(window, 0, "windowTime"),
            _int_const_param(window, 1, "hopTime"), col_specs, capacity)
    if name in ("sort", "frequent", "lossyfrequent", "session", "cron",
                "expression", "expressionbatch"):
        from siddhi_tpu.ops.host_windows import create_host_window_stage

        return create_host_window_stage(window, input_def, resolver, app_context)
    raise CompileError(f"window '{window.name}' is not implemented yet")
